"""Paired interleaved commit-rule A/B: classic Tusk vs a challenger rule
(ROADMAP item 2, the r10/r19 A/B methodology; r19 generalizes the
challenger arm).

Arms differ ONLY in ``NARWHAL_COMMIT_RULE`` — same committee shape, same
rate, same wire/crypto planes — except that the challenger arm may also
carry ``--header-linger`` (the multileader rule's proposer-side knob;
classic never reads it, so giving it to classic would only add latency
noise to the baseline):

- **classic** — Tusk: the round-L leader commits at depth 3 (a
  round-(L+3) certificate triggers, f+1 round-(L+1) support).
- **lowdepth** — the Mysticeti-style rule: the leader commits the
  moment 2f+1 round-(L+1) certificates cite it (depth 1 on the leader,
  ~2 averaged over the flattened window), judged against its own frozen
  oracle everywhere else in the tree.
- **multileader** — Mysticeti multi-slot: 3 round-salted leader slots
  per even round, the commit anchors on the lowest 2f+1-supported slot;
  its own frozen oracle (``consensus/golden_multileader.py``).

Arms are interleaved (classic, challenger, classic, ...) so slow host
drift hits both equally.  The target series is the ``cert_to_commit``
stage leg from the bench JSON (the PR 4 sub-stage attribution measured
it 97-98% protocol cadence — commit depth × round period — which is
exactly what a lower commit depth cuts).  Gates:

- zero run errors on BOTH arms;
- challenger median committed TPS within ``--tps-tolerance`` of classic
  (the latency cut must come at EQUAL throughput);
- classic/challenger median ``cert_to_commit`` ratio ≥ ``--min-speedup``
  (default 1.6, the "~2×" claim with room for the non-leader tail) —
  on a drifting shared-core host record WHY with ``--verdict-note``
  (the r06/r19 honest-verdict precedent) instead of deleting the gate.

Artifact keys are ``classic_runs``/``<challenger>_runs`` — deliberately
NOT ``runs`` so benchmark/trajectory.py does not read a fixed-rate A/B
as a saturation-series point.

    python benchmark/commit_rule_ab.py --pairs 3 --duration 15 \
        --challenger multileader --artifact artifacts/commit_rule_ab_r23.json
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmark.local_bench import run_bench  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The cert→commit sub-stage legs (PR 4): protocol-cadence wait up to the
# commit trigger, the walk itself, and delivery — reported per arm so
# the artifact shows WHERE the cut landed (it must be the trigger wait).
SUB_LEGS = (
    "cert_inserted_to_commit_trigger",
    "commit_trigger_to_walk_done",
    "walk_done_to_commit",
)


def _one_run(arm: str, idx: int, args) -> dict:
    result = run_bench(
        nodes=args.nodes,
        workers=1,
        rate=args.rate,
        tx_size=args.tx_size,
        duration=args.duration,
        base_port=args.base_port,
        workdir=os.path.join(REPO, ".bench_commit_rule_ab"),
        quiet=True,
        progress_wait=args.progress_wait,
        commit_rule=arm,
        header_linger=(args.header_linger if arm == args.challenger else 0),
    )
    stages = result.stages_ms or {}
    return {
        "arm": arm,
        "run": idx,
        "errors": result.errors,
        "consensus_tps": result.consensus_tps,
        "consensus_latency_ms": result.consensus_latency_ms,
        "end_to_end_tps": result.end_to_end_tps,
        "end_to_end_latency_ms": result.end_to_end_latency_ms,
        "committed_bytes": result.committed_bytes,
        "cert_to_commit_ms": stages.get("cert_to_commit"),
        "seal_to_commit_ms": stages.get("seal_to_commit"),
        "sub_legs_ms": {leg: stages.get(leg) for leg in SUB_LEGS},
        "stages_ms": stages,
    }


def _median(vals):
    vals = [v for v in vals if v is not None]
    return round(statistics.median(vals), 3) if vals else None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--pairs", type=int, default=3)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--rate", type=int, default=3_000)
    ap.add_argument("--tx-size", type=int, default=512)
    ap.add_argument("--duration", type=int, default=15)
    ap.add_argument("--base-port", type=int, default=7600)
    ap.add_argument("--progress-wait", type=float, default=30.0)
    ap.add_argument(
        "--challenger", choices=["lowdepth", "multileader"],
        default="lowdepth",
        help="The non-classic arm of the pair",
    )
    ap.add_argument(
        "--header-linger", type=int, default=0,
        help="header_linger (ms) for the CHALLENGER arm only — the "
        "multileader rule's proposer knob; classic ignores it, so the "
        "baseline stays the shipped default",
    )
    ap.add_argument(
        "--min-speedup", type=float, default=1.6,
        help="Required classic/challenger median cert_to_commit ratio "
        "(the ~2× claim with room for the non-leader tail)",
    )
    ap.add_argument(
        "--tps-tolerance", type=float, default=0.25,
        help="Challenger median committed TPS may be at most this "
        "fraction below classic (shared-core noise floor)",
    )
    ap.add_argument(
        "--verdict-note", default=None,
        help="Free-text honest-verdict note recorded as the artifact's "
        "`host_verdict` (the r06/r19 convention for gates the host "
        "cannot meet: say WHY, with the measurements)",
    )
    ap.add_argument("--artifact", default="artifacts/commit_rule_ab_r20.json")
    args = ap.parse_args(argv)
    challenger = args.challenger

    runs = {"classic": [], challenger: []}
    for i in range(args.pairs):
        for arm in ("classic", challenger):
            print(f"== commit-rule A/B pair {i + 1}/{args.pairs}: {arm} ==")
            r = _one_run(arm, i, args)
            runs[arm].append(r)
            print(
                f"   committed TPS {r['consensus_tps']:,.0f}, "
                f"cert_to_commit {r['cert_to_commit_ms']} ms, "
                f"consensus latency {r['consensus_latency_ms']} ms"
            )

    failures = []
    for r in runs["classic"] + runs[challenger]:
        if r["errors"]:
            failures.append(f"{r['arm']} run {r['run']}: {r['errors'][:3]}")

    c2c_classic = _median(
        [r["cert_to_commit_ms"] for r in runs["classic"]]
    )
    c2c_challenger = _median(
        [r["cert_to_commit_ms"] for r in runs[challenger]]
    )
    tps_classic = _median([r["consensus_tps"] for r in runs["classic"]])
    tps_challenger = _median([r["consensus_tps"] for r in runs[challenger]])
    speedup = None
    if c2c_classic is None or c2c_challenger is None:
        failures.append("cert_to_commit missing from an arm's stage trace")
    else:
        speedup = round(c2c_classic / c2c_challenger, 3)
        if speedup < args.min_speedup:
            failures.append(
                f"cert_to_commit speedup {speedup}x < required "
                f"{args.min_speedup}x (classic {c2c_classic} ms, "
                f"{challenger} {c2c_challenger} ms)"
            )
    if tps_classic and tps_challenger is not None and (
        tps_challenger < tps_classic * (1 - args.tps_tolerance)
    ):
        failures.append(
            f"{challenger} median committed TPS {tps_challenger:,.0f} more "
            f"than {args.tps_tolerance:.0%} below classic "
            f"{tps_classic:,.0f}"
        )

    summary = {
        "challenger": challenger,
        "header_linger_ms": args.header_linger,
        "cert_to_commit_ms": {
            "classic": c2c_classic, challenger: c2c_challenger,
        },
        "speedup": speedup,
        "consensus_tps": {
            "classic": tps_classic, challenger: tps_challenger,
        },
        "consensus_latency_ms": {
            arm: _median([r["consensus_latency_ms"] for r in arm_runs])
            for arm, arm_runs in runs.items()
        },
        "sub_legs_ms": {
            arm: {
                leg: _median([r["sub_legs_ms"].get(leg) for r in arm_runs])
                for leg in SUB_LEGS
            }
            for arm, arm_runs in runs.items()
        },
        "gates_failed": failures,
    }

    artifact = {
        "what": (
            "Paired interleaved commit-rule A/B: classic Tusk vs the "
            f"{challenger} rule on a {args.nodes}-node local_bench, rate "
            f"{args.rate}, {args.tx_size} B tx, {args.duration} s "
            "windows; arms differ only in NARWHAL_COMMIT_RULE"
            + (
                f" plus header_linger={args.header_linger} ms on the "
                "challenger arm."
                if args.header_linger
                else "."
            )
        ),
        "classic_runs": runs["classic"],
        f"{challenger}_runs": runs[challenger],
        "summary": summary,
    }
    if args.verdict_note:
        artifact["host_verdict"] = args.verdict_note
    os.makedirs(os.path.dirname(args.artifact) or ".", exist_ok=True)
    with open(args.artifact, "w") as f:
        json.dump(artifact, f, indent=1)

    print("== commit-rule A/B summary ==")
    print(json.dumps(summary, indent=1))
    if failures:
        print(f"commit-rule A/B FAILED: {failures}", file=sys.stderr)
        return 1
    print(
        f"commit-rule A/B ok: cert_to_commit {c2c_classic} -> "
        f"{c2c_challenger} ms ({speedup}x) at committed TPS "
        f"{tps_classic:,.0f} -> {tps_challenger:,.0f}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
