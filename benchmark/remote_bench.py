"""Multi-host benchmark launcher: run one committee across several machines.

The reference's remote harness (benchmark/benchmark/remote.py:139-311) does
install (git clone + cargo build per host), config upload, node launch in
per-host tmux sessions, log download, and reuses LogParser for the numbers;
instance.py adds AWS-specific EC2 lifecycle.  This is the deployment-agnostic
analog: a host is anything a `Runner` can reach — `ssh://user@ip` for real
clusters (install = rsync of this checkout, no AWS dependency) or
`local:<dir>` subprocess sandboxes, which give a faithful 2+-"host" run
(separate working dirs, separate stores, full TCP mesh) on one machine and
are what the test suite exercises.  A cloud-instance lifecycle module
(instance.py's boto3 create/start/stop/terminate) is deliberately out of
scope: it is provider-specific and needs egress; the Runner protocol is the
seam where one would plug in — provision however you like, hand this file
ssh targets.

    python benchmark/remote_bench.py --hosts ssh://10.0.0.1 ssh://10.0.0.2 \
        --rate 40000 --duration 30
    python benchmark/remote_bench.py --hosts local:/tmp/h0 local:/tmp/h1 \
        --nodes 4 --rate 10000 --duration 15

Each authority i runs (primary + workers + its clients) on host i%H; the
committee file carries each host's address, so all inter-authority traffic
crosses the real network between hosts.  ``--no-collocate`` instead spreads
each authority's roles round-robin (the reference's ``collocate=False``
control-plane/data-plane machine split, remote.py:108-130): given at least
1+W hosts per authority-role-set, its primary and every worker land on
different hosts and the primary↔worker LAN hop also crosses the network
(with fewer hosts the round-robin wraps and a warning says which part of
that claim still holds).
"""

from __future__ import annotations

import argparse
import json
import os
import shlex
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from narwhal_tpu.config import Parameters, export_keypair  # noqa: E402
from narwhal_tpu.crypto import KeyPair  # noqa: E402
from benchmark.local_bench import build_committee  # noqa: E402
from benchmark.logs import parse_logs  # noqa: E402
from benchmark.metrics_check import (  # noqa: E402
    build_timeline,
    check_quiesce_health,
    clock_summary,
    corrected_stage_join,
    critical_path_summary,
    queue_pressure_summary,
    quorum_straggler_summary,
    wire_crypto_summary,
)
from benchmark.scraper import Scraper  # noqa: E402


class LocalRunner:
    """A 'host' that is a directory on this machine (127.0.0.1 traffic).

    Faithful to the SSH path — separate workdir, nohup'd processes, log
    fetch — minus the wire between machines; used by tests and for smoke
    runs without a cluster."""

    def __init__(self, workdir: str):
        self.workdir = os.path.abspath(workdir)
        self.ip = "127.0.0.1"
        self.python = shlex.quote(sys.executable)
        os.makedirs(self.workdir, exist_ok=True)

    def install(self) -> None:
        # Same machine: the host's "repo" is a symlink to this checkout.
        link = os.path.join(self.workdir, "repo")
        if not os.path.islink(link):
            os.symlink(REPO, link)

    def put(self, local: str, remote_rel: str) -> None:
        dst = os.path.join(self.workdir, remote_rel)
        os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
        subprocess.run(["cp", local, dst], check=True)

    def get(self, remote_rel: str, local: str) -> None:
        src = os.path.join(self.workdir, remote_rel)
        os.makedirs(os.path.dirname(local) or ".", exist_ok=True)
        subprocess.run(["cp", src, local], check=True)

    def run(self, cmd: str, check: bool = True) -> subprocess.CompletedProcess:
        return subprocess.run(
            cmd, shell=True, cwd=self.workdir, check=check,
            capture_output=True, text=True,
        )


class SshRunner:
    """A host reached over ssh; install = rsync this checkout across.

    ``workdir`` is relative to the login home (no tilde games): every
    command runs from it, and all node/client paths are workdir-relative."""

    def __init__(
        self,
        host: str,
        workdir: str = "narwhal_bench",
        python: str = "python3",
    ):
        # host: "user@ip" or "ip".  `python3`, not `python`: modern distros
        # ship no bare `python` on the PATH.
        self.host = host
        self.ip = host.split("@")[-1]
        self.workdir = workdir
        self.python = python

    def install(self) -> None:
        subprocess.run(
            ["ssh", "-o", "BatchMode=yes", self.host,
             f"mkdir -p {shlex.quote(self.workdir)}"],
            check=True,
        )
        subprocess.run(
            [
                "rsync", "-az", "--delete",
                "--exclude", ".git", "--exclude", ".bench",
                "--exclude", "__pycache__", "--exclude", "*.pyc",
                f"{REPO}/", f"{self.host}:{self.workdir}/repo/",
            ],
            check=True,
        )
        # Build the native data plane on the target's own toolchain.
        self.run("make -C repo/native", check=False)

    def put(self, local: str, remote_rel: str) -> None:
        d = os.path.dirname(remote_rel)
        if d:
            self.run(f"mkdir -p {shlex.quote(d)}")
        subprocess.run(
            ["scp", "-q", local, f"{self.host}:{self.workdir}/{remote_rel}"],
            check=True,
        )

    def get(self, remote_rel: str, local: str) -> None:
        os.makedirs(os.path.dirname(local) or ".", exist_ok=True)
        subprocess.run(
            ["scp", "-q", f"{self.host}:{self.workdir}/{remote_rel}", local],
            check=True,
        )

    def run(self, cmd: str, check: bool = True) -> subprocess.CompletedProcess:
        return subprocess.run(
            ["ssh", "-o", "BatchMode=yes", self.host,
             f"cd {shlex.quote(self.workdir)} && {cmd}"],
            check=check, capture_output=True, text=True,
        )


def make_runner(spec: str):
    if spec.startswith("ssh://"):
        return SshRunner(spec[len("ssh://"):])
    if spec.startswith("local:"):
        return LocalRunner(spec[len("local:"):])
    raise ValueError(f"host spec must be ssh://... or local:<dir>, got {spec!r}")


def _spawn_cmd(runner, args: list, logfile: str) -> None:
    """Start a long-running node/client on the host, detached from the
    launcher (reference runs each in a tmux session; nohup + pid file is
    the dependency-free equivalent).  Paths in `args` are workdir-relative;
    the process runs from the workdir with the rsynced repo on PYTHONPATH.
    logs/ and pids/ were created by the per-host prep pass."""
    quoted = " ".join(shlex.quote(a) for a in args)
    # NARWHAL_BIND_ANY: listen sockets bind 0.0.0.0 — committee addresses
    # carry each host's *reachable* IP, which on NAT'd/cloud hosts is not a
    # local interface address.
    runner.run(
        f"PYTHONPATH=repo NARWHAL_BIND_ANY=1 nohup {runner.python} {quoted} "
        f"> {shlex.quote(logfile)} 2>&1 & "
        "echo $! >> pids/all"
    )


# Kill only pids whose live cmdline is actually one of our node/client
# processes: pids/all can be stale across reboots/PID wrap, and a blind
# `kill -9 $(cat pids/all)` would then hit unrelated processes (the local
# harness's kill_stale_nodes() guards the same way via /proc cmdline).
_KILL_OURS = (
    "if [ -f pids/all ]; then for p in $(cat pids/all); do "
    "grep -aq narwhal_tpu /proc/$p/cmdline 2>/dev/null && kill -{sig} $p; "
    "done; fi; true"
)


def kill_ours(runner, sig="TERM", clear_pidfile: bool = False) -> None:
    """Kill the runner's recorded node/client pids (cmdline-verified) —
    the one definition of the kill contract for every caller."""
    cmd = _KILL_OURS.format(sig=sig)
    if clear_pidfile:
        cmd += "; rm -f pids/all"
    runner.run(cmd, check=False)


def run_remote_bench(
    hosts,
    nodes: int = 4,
    workers: int = 1,
    rate: int = 20_000,
    tx_size: int = 512,
    duration: int = 30,
    base_port: int = 7500,
    batch_size: int = 500_000,
    header_size: int = 1_000,
    max_header_delay: int = 100,
    max_batch_delay: int = 100,
    install: bool = True,
    keep_logs: bool = False,
    quiet: bool = False,
    collocate: bool = True,
    scrape_interval: float = 1.0,
    progress_wait: float = 0.0,
):
    """Launch the committee across ``hosts`` and measure.

    ``progress_wait``: extra seconds (beyond ``duration``) the
    measurement window may stretch while the scraped metrics show ZERO
    committed payload batches committee-wide — a wall-clock progress
    check replacing blind trust in one fixed sleep (on a loaded shared
    core the whole boot can eat the window; the reference harness has
    the same failure mode).  Batch digests, not certificates: empty
    headers commit on an idle committee too.  0 keeps the reference's
    fixed-duration behavior.
    """
    runners = [make_runner(h) for h in hosts]
    # Role→host placement.  Collocated (default): authority i's primary,
    # workers and clients all on host i%H — the reference's default.  Non-
    # collocated (reference remote.py:108-130, `collocate=False`): each
    # authority's roles spread round-robin over the host list — the
    # control-plane/data-plane machine split that lets payload bandwidth
    # scale independently of the primary (SURVEY §2.3.2).  Every role of
    # one authority lands on a distinct host iff 1+workers ≤ H; with
    # fewer hosts the round-robin wraps and some worker shares its
    # primary's host (warned below — those hops are loopback, and
    # published numbers should say so).
    n_hosts = len(runners)
    if collocate:
        p_host = lambda i: runners[i % n_hosts]  # noqa: E731
        w_host = lambda i, w: runners[i % n_hosts]  # noqa: E731
    else:
        stride = 1 + workers
        p_host = lambda i: runners[(i * stride) % n_hosts]  # noqa: E731
        w_host = (  # noqa: E731
            lambda i, w: runners[(i * stride + 1 + w) % n_hosts]
        )
        if stride > n_hosts and not quiet:
            print(
                f"WARNING: --no-collocate with {workers} worker(s) needs "
                f"{stride} hosts per authority for fully split roles but "
                f"only {n_hosts} are available; some primary-worker hops "
                "stay on one host",
                file=sys.stderr,
            )
    if install:
        for r in runners:
            r.install()
    # Per-host prep (reference remote.py `kill` task + fresh dirs): kill
    # leftovers from a previous run, clear its stores/logs (an interrupted
    # run never reached its own cleanup — replaying its multi-GB store logs
    # would eat the next run's boot window), and create the run dirs once.
    for r in runners:
        kill_ours(r, sig=9, clear_pidfile=True)
        r.run(
            "rm -rf db-primary-* db-worker-* logs && mkdir -p logs pids",
            check=False,
        )

    stage = os.path.join(REPO, ".bench_remote")
    subprocess.run(["rm", "-rf", stage], check=False)
    os.makedirs(stage, exist_ok=True)

    keypairs = [KeyPair.generate() for _ in range(nodes)]
    committee = build_committee(
        keypairs,
        base_port,
        workers,
        ips=[p_host(i).ip for i in range(nodes)],
        worker_ips=[
            [w_host(i, w).ip for w in range(workers)] for i in range(nodes)
        ],
    )
    committee.export(f"{stage}/committee.json")
    Parameters(
        header_size=header_size,
        batch_size=batch_size,
        max_header_delay=max_header_delay,
        max_batch_delay=max_batch_delay,
    ).export(f"{stage}/parameters.json")
    for i, kp in enumerate(keypairs):
        export_keypair(kp, f"{stage}/node-{i}.json")

    # Upload configs (reference remote.py:161-211): shared files once per
    # host, each authority's keypair to its own host only.
    for r in runners:
        r.put(f"{stage}/committee.json", "configs/committee.json")
        r.put(f"{stage}/parameters.json", "configs/parameters.json")
    for i in range(nodes):
        # Every host running one of authority i's roles needs its keypair.
        for r in {p_host(i)} | {w_host(i, w) for w in range(workers)}:
            r.put(f"{stage}/node-{i}.json", f"configs/node-{i}.json")

    # Launch primaries and workers, then clients (reference remote.py:213-271).
    # Every node gets a --metrics-port in the block directly after the
    # committee's own ports (globally sequential, so co-hosted nodes
    # never collide); the launcher scrapes them across the wire during
    # the run — the remote harness finally collects live metrics instead
    # of nothing (ROADMAP item).  The servers bind 0.0.0.0 via the
    # NARWHAL_BIND_ANY=1 that _spawn_cmd already sets.
    metrics_port_base = base_port + nodes * (2 + 3 * workers)
    scrape_targets = []  # (name, host_ip, port)
    primary_logs, worker_logs, client_logs = [], [], []
    for i in range(nodes):
        common = [
            "-m", "narwhal_tpu.node", "run",
            "--keys", f"configs/node-{i}.json",
            "--committee", "configs/committee.json",
            "--parameters", "configs/parameters.json",
            "--benchmark",
        ]
        r = p_host(i)
        mport = metrics_port_base + i
        scrape_targets.append((f"primary-{i}", r.ip, mport))
        primary_logs.append((r, f"logs/primary-{i}.log"))
        _spawn_cmd(
            r,
            common + [
                "--store", f"db-primary-{i}",
                "--metrics-port", str(mport),
                "primary",
            ],
            f"logs/primary-{i}.log",
        )
        for w in range(workers):
            rw = w_host(i, w)
            mport = metrics_port_base + nodes + i * workers + w
            scrape_targets.append((f"worker-{i}-{w}", rw.ip, mport))
            worker_logs.append((rw, f"logs/worker-{i}-{w}.log"))
            _spawn_cmd(
                rw,
                common + [
                    "--store", f"db-worker-{i}-{w}",
                    "--metrics-port", str(mport),
                    "worker", "--id", str(w),
                ],
                f"logs/worker-{i}-{w}.log",
            )

    # Same lesson as the local bench: never open the measurement window
    # against a committee that hasn't booted.
    deadline = time.time() + 120
    pending = set(primary_logs + worker_logs)
    while pending and time.time() < deadline:
        # One batched grep per host per round (not one ssh exec per log):
        # -l prints each file that matched, -s silences not-yet-created.
        for r in runners:
            files = [rel for rr, rel in pending if rr is r]
            if not files:
                continue
            cp = r.run(
                "grep -ls 'successfully booted' "
                + " ".join(shlex.quote(f) for f in files),
                check=False,
            )
            for line in (cp.stdout or "").splitlines():
                pending.discard((r, line.strip()))
        if pending:
            time.sleep(1)
    if pending and not quiet:
        names = [rel for _, rel in pending]
        print(f"WARNING: nodes never booted: {names}", file=sys.stderr)

    rate_share = max(1, rate // max(1, nodes * workers))
    idx = 0
    for i in range(nodes):
        for w in range(workers):
            # Clients live with the worker they feed (reference
            # remote.py:226-237 runs clients on the worker's instance).
            r = w_host(i, w)
            addr = committee.worker(keypairs[i].name, w).transactions
            client_logs.append((r, f"logs/client-{i}-{w}.log"))
            _spawn_cmd(
                r,
                [
                    "-m", "narwhal_tpu.node.benchmark_client", addr,
                    "--size", str(tx_size),
                    "--rate", str(rate_share),
                    "--sample-offset", str(idx << 32),
                    "--nodes", addr,
                ],
                f"logs/client-{i}-{w}.log",
            )
            idx += 1

    if not quiet:
        print(f"Running remote benchmark ({duration} s)...", file=sys.stderr)
    scraper = Scraper(scrape_targets, interval_s=scrape_interval).start()
    time.sleep(duration)
    # Wall-clock progress check: only close the window once the scraped
    # metrics have shown a committed payload batch (or progress_wait
    # runs out).
    scraper.wait_for_payload_commits(progress_wait, quiet=quiet)
    # Quiesce gate BEFORE teardown: any firing health rule fails the run.
    healthz = scraper.healthz_all()
    # Flight rings ride along (same convention as local_bench): each
    # node's last-seconds event history in the bench JSON.
    flight_rings = scraper.flight_all()
    # One FULL snapshot round (stage traces + clock.offset_ms gauges)
    # before teardown — the remote stand-in for local_bench's
    # --metrics-path post-mortem files.  This is the input to the
    # skew-corrected cross-node join: remote hosts have genuinely
    # different wall clocks, so this harness is where the correction
    # earns its keep rather than being an identity.
    full_snaps: list = []
    for node_name, snap in scraper.snapshot_all().items():
        if isinstance(snap, dict):
            snap["node"] = node_name
            full_snaps.append(snap)
    scraper.stop()

    for r in runners:
        kill_ours(r, sig="TERM")
    time.sleep(2)
    for r in runners:
        kill_ours(r, sig=9, clear_pidfile=True)

    # Fetch logs (reference remote.py `_logs`) and parse with the same
    # LogParser the local bench uses.
    def fetch(entries, kind):
        texts = []
        for j, (r, rel) in enumerate(entries):
            local = f"{stage}/{kind}-{j}.log"
            try:
                r.get(rel, local)
                texts.append(open(local).read())
            except Exception as e:  # host unreachable: parse what we have
                print(f"WARNING: fetch {rel}: {e}", file=sys.stderr)
                texts.append("")
        return texts

    result = parse_logs(
        fetch(client_logs, "client"),
        fetch(worker_logs, "worker"),
        fetch(primary_logs, "primary"),
        tx_size,
    )
    check_quiesce_health(healthz, result.errors)
    result.timeline = build_timeline(
        scraper.samples, interval_s=scrape_interval, healthz=healthz
    )
    # Wire & crypto ledger sections from each node's LAST scraped sample
    # (cumulative counters, so last ≈ whole run minus the post-scrape
    # tail; the remote harness has no post-mortem snapshot files to
    # read).  Same join as local_bench, same bench-JSON keys.
    last_sample: dict = {}
    for s in scraper.samples:
        prev = last_sample.get(s["node"])
        if prev is None or s["t"] >= prev["t"]:
            last_sample[s["node"]] = s
    wc = wire_crypto_summary(
        list(last_sample.values()),
        committed_payload_bytes=result.committed_bytes,
        quorum_weight=committee.quorum_threshold(),
    )
    result.wire, result.crypto = wc["wire"], wc["crypto"]
    # Per-channel backpressure accounting: last samples as the snapshot
    # proxy (totals), the full 1 Hz timeline for first_saturating.
    result.queues = queue_pressure_summary(
        list(last_sample.values()), scraper.samples
    )
    result.flight = flight_rings
    # Skew-corrected cross-node stage join over the full quiesce
    # snapshots: per-node reconciled offsets (recorded in the bench
    # JSON), the slowest causal chain, and the ranked quorum-straggler
    # attribution — the same sections, same keys, as local_bench.
    result.clock = clock_summary(full_snaps)
    stage_ts, _seal_bytes = corrected_stage_join(full_snaps)
    result.critical_path = critical_path_summary(stage_ts)
    result.stragglers = quorum_straggler_summary(full_snaps)
    with open(f"{stage}/timeline.json", "w") as f:
        json.dump(result.timeline, f, indent=1)
    for r in runners:
        r.run("rm -rf db-primary-* db-worker-*", check=False)
        if not keep_logs:
            r.run("rm -rf logs", check=False)
    return result


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--settings",
        default=None,
        help="JSON deployment settings file (hosts + bench params); CLI "
        "flags override it.  The analog of the reference's "
        "benchmark/settings.json, minus the AWS-specific keys "
        "(see benchmark/settings.example.json)",
    )
    ap.add_argument(
        "--hosts", nargs="+", default=None,
        help="ssh://user@ip or local:<dir> per host",
    )
    ap.add_argument("--nodes", type=int, default=None)
    ap.add_argument("--workers", type=int, default=None)
    ap.add_argument("--rate", type=int, default=None)
    ap.add_argument("--tx-size", type=int, default=None)
    ap.add_argument("--duration", type=int, default=None)
    ap.add_argument("--base-port", type=int, default=None)
    ap.add_argument("--batch-size", type=int, default=None)
    ap.add_argument("--no-install", action="store_true")
    ap.add_argument(
        "--no-collocate",
        action="store_true",
        help="Place each authority's primary and workers on different "
        "hosts (reference collocate=False, remote.py:108-130) instead of "
        "packing an authority per host",
    )
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    settings = {}
    if args.settings:
        with open(args.settings) as f:
            settings = json.load(f)
        known = {
            "hosts", "nodes", "workers", "rate", "tx_size", "duration",
            "base_port", "batch_size",
        }
        unknown = set(settings) - known
        if unknown:
            # Fail loudly: a misspelled key ("tx-size", "batchsize") would
            # otherwise silently run the bench at the default it meant to
            # override, mislabeling the results.
            ap.error(
                f"unknown settings key(s) {sorted(unknown)}; "
                f"known: {sorted(known)}"
            )

    def pick(name, default):
        v = getattr(args, name)
        if v is not None:
            return v
        return settings.get(name, default)

    hosts = pick("hosts", None)
    if not hosts:
        ap.error("--hosts (or a settings file with \"hosts\") is required")
    args.hosts = hosts
    args.nodes = pick("nodes", 4)
    args.workers = pick("workers", 1)
    args.rate = pick("rate", 20_000)
    args.tx_size = pick("tx_size", 512)
    args.duration = pick("duration", 30)
    args.base_port = pick("base_port", 7500)
    args.batch_size = pick("batch_size", 500_000)

    result = run_remote_bench(
        args.hosts,
        nodes=args.nodes,
        workers=args.workers,
        rate=args.rate,
        tx_size=args.tx_size,
        duration=args.duration,
        base_port=args.base_port,
        batch_size=args.batch_size,
        install=not args.no_install,
        collocate=not args.no_collocate,
    )
    if result.errors:
        print("ERRORS detected in logs:", file=sys.stderr)
        for e in result.errors[:10]:
            print("  " + e, file=sys.stderr)
    if args.json:
        print(
            json.dumps(
                {
                    "consensus_tps": result.consensus_tps,
                    "consensus_latency_ms": result.consensus_latency_ms,
                    "end_to_end_tps": result.end_to_end_tps,
                    "end_to_end_latency_ms": result.end_to_end_latency_ms,
                    "samples": result.samples,
                    "errors": result.errors[:10],
                    "wire": result.wire,
                    "crypto": result.crypto,
                    "timeline": result.timeline,
                    "flight": result.flight,
                    "queues": result.queues,
                    # Per-node reconciled clock offsets (the correction
                    # the cross-host stage join applied), the slowest
                    # causal chain, and the straggler table.
                    "clock": result.clock,
                    "critical_path": result.critical_path,
                    "stragglers": result.stragglers,
                }
            )
        )
    else:
        print(result.summary(args.rate, args.tx_size, args.nodes, args.workers))
    sys.exit(1 if result.errors or result.committed_batches == 0 else 0)


if __name__ == "__main__":
    main()
