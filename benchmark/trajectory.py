"""Cross-revision performance trajectory: read every bench artifact the
repo carries, build per-metric revision series, and gate on regressions.

Five ``BENCH_r*.json`` driver artifacts existed before this tool and
nothing read them across revisions — the r05 e2e regression (0.71× the
reference baseline vs r02's 0.92×, BENCH_r05 vs BENCH_r02) sat unlocated
for five PRs because nothing watched the trajectory.  This tool is that
watcher:

- **collect**: ``BENCH_r*.json`` at the repo root (the driver's
  ``{n, cmd, rc, parsed}`` wrapper) plus recognizable bench artifacts
  under ``artifacts/`` (flat bench-result dicts and ``{"runs": [...]}``
  A/B captures, e.g. ``metrics_stage_breakdown_r07.json``).  Everything
  else under ``artifacts/`` is listed as skipped with a reason — a
  partial or foreign artifact must never crash the gate (missing file,
  malformed JSON, ``rc != 0``, zero-valued failed measurements: all
  warn-and-skip).
- **series**: tps / latency / per-stage pipeline legs / the wire &
  crypto ledger headline metrics (goodput ratio, cert signature bytes
  fraction, empty-cert overhead per committed byte), keyed by the
  ``rNN`` revision in the filename.
- **gate**: each gated metric's value per revision is compared against
  the BEST of all prior revisions; a drop (rise, for lower-is-better
  metrics) beyond the pinned tolerance is a regression.  Tolerances and
  waivers are pinned in-repo (``benchmark/trajectory_gate.json``) so the
  gate's meaning is versioned with the code; a waived regression stays
  in the report but does not fail the gate (the r05 regression is waived
  by name — ROADMAP item 3 owns recovering it, and a gate that fails on
  five-PR-old history forever would just be muted).

Exit status: 0 when no unwaived regression (skips and waived regressions
only warn), 2 when the gate trips, 1 on usage errors.

    python benchmark/trajectory.py --report .ci-artifacts/trajectory.json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_GATE_CONFIG = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "trajectory_gate.json"
)

# Direction per metric family.  Metrics in neither set are tracked in
# the report but never gated (informational: e.g. cert signature bytes
# fraction moves with committee size, not code quality).
HIGHER_BETTER = {
    "end_to_end_tps",
    "consensus_tps",
    "vs_baseline",
    "goodput_ratio",
    # Wire-v2 series (PR 13): syscall coalescing and byte compression —
    # a drop means the coalescing got bypassed or the codec regressed.
    "frames_per_flush_mean",
    "compression_ratio",
}
LOWER_BETTER = {
    "consensus_latency_ms",
    "end_to_end_latency_ms",
    # Commit-rule headline (PR 15): mean cert→commit from the bench
    # JSON's stage trace, published per revision like goodput was in
    # PR 13 — the claimed lowdepth latency cut stays pinned
    # cross-revision instead of living in one A/B artifact.
    "cert_to_commit_ms",
    # Support-quorum spread: first direct supporter → the 2f+1 arrival
    # that closes a committed leader's support quorum (the slack the
    # lowdepth rule converts into latency).  Graduated from the
    # round-attribution report to a gated series: a creep here is the
    # committee getting slower at the exact quorum the commit rule
    # waits on, upstream of any cert_to_commit movement.
    "support_arrival_ms",
    # Halfagg signature fraction of the certificate frame at the pinned
    # N=20 sim capture (PR 20) — the one artifacts/-sourced metric that
    # IS gated: the capture is deterministic per seed at one committee
    # size, so unlike the bench-JSON fraction it cannot move for
    # non-code reasons.  Lower is better (the aggregate shrinking, or a
    # pairing backend landing, pushes it down; an encoder regression
    # pushes it up).
    "attr.cert_sig_bytes_fraction",
}
# Pipeline stage legs (stage.<leg>) are lower-better but host-noise
# swings them ±40% (r09/r10 artifacts), so they are tracked, not gated.
_STAGE_PREFIX = "stage."

_REV_RE = re.compile(r"r(\d+)")


def parse_revision(path: str) -> Optional[str]:
    """``rNN`` label from a filename, or None (no revision = no series
    membership; the file is still reported as skipped)."""
    m = _REV_RE.search(os.path.basename(path))
    return f"r{int(m.group(1)):02d}" if m else None


def _num(v) -> Optional[float]:
    """A usable measurement: finite, strictly positive number.  Every
    tracked metric is positive when valid — the r03/r04 driver files
    published 0.0 for a failed measurement with a clean rc, which is
    exactly the value a trajectory must not treat as 'we got slower'."""
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    if v != v or v in (float("inf"), float("-inf")) or v <= 0:
        return None
    return float(v)


def _bench_result_metrics(d: dict) -> Dict[str, float]:
    """Metrics from one flat bench-result dict (bench.py's JSON line /
    local_bench --json / a stage-breakdown artifact)."""
    out: Dict[str, float] = {}
    # The driver reports end_to_end OR consensus tps under "metric"/
    # "value"; newer shapes carry the explicit keys too.
    metric_name = d.get("metric") or ""
    v = _num(d.get("value"))
    if v is not None:
        if metric_name.startswith("end_to_end_tps"):
            out["end_to_end_tps"] = v
        elif metric_name.startswith("consensus_tps"):
            out["consensus_tps"] = v
    # vs_baseline only when it is the E2E normalization: the driver
    # falls back to value/consensus-baseline when the e2e join fails
    # (bench.py), and mixing the two normalizations into one gated
    # series would make best-of-prior comparisons apples-to-oranges.
    if metric_name.startswith("end_to_end_tps"):
        v = _num(d.get("vs_baseline"))
        if v is not None:
            out["vs_baseline"] = v
    for key in (
        "end_to_end_tps",
        "consensus_tps",
        "consensus_latency_ms",
        "end_to_end_latency_ms",
        "goodput_ratio",
        "cert_sig_bytes_fraction",
        "empty_cert_overhead_per_committed_byte",
        "frames_per_flush_mean",
        "compression_ratio",
    ):
        v = _num(d.get(key))
        if v is not None:
            out.setdefault(key, v)
    # Wire/crypto sections when embedded whole (local_bench --json).
    wire = d.get("wire")
    if isinstance(wire, dict):
        for key in (
            "goodput_ratio",
            "cert_sig_bytes_fraction",
            "empty_cert_overhead_per_committed_byte",
            "frames_per_flush_mean",
            "compression_ratio",
        ):
            v = _num(wire.get(key))
            if v is not None:
                out.setdefault(key, v)
    # cert_to_commit_ms headline: a first-class key when the artifact
    # publishes it (BENCH_r20 onward), else lifted out of the stage
    # breakdown — the one stage leg that graduated from tracked to GATED
    # (its driver-artifact form is a median of interleaved runs, which
    # tames the ±40% single-run host swing that keeps the other legs
    # ungated).
    v = _num(d.get("cert_to_commit_ms"))
    if v is None:
        stages_d = d.get("stages_ms")
        if isinstance(stages_d, dict):
            v = _num(stages_d.get("cert_to_commit"))
    if v is not None:
        out.setdefault("cert_to_commit_ms", v)
    # support_arrival_ms: first-class key when the artifact publishes it
    # (bench.py from r22), else lifted from the straggler section's gap
    # histograms (local_bench --json embeds the whole summary).
    v = _num(d.get("support_arrival_ms"))
    if v is None:
        stragglers = d.get("stragglers")
        if isinstance(stragglers, dict):
            gap = (stragglers.get("gaps") or {}).get("support_arrival_ms")
            if isinstance(gap, dict):
                v = _num(gap.get("mean"))
    if v is not None:
        out.setdefault("support_arrival_ms", v)
    stages = d.get("stages_ms")
    if isinstance(stages, dict):
        for leg, ms in stages.items():
            v = _num(ms)
            if v is not None and leg != "trace_evictions":
                out[f"{_STAGE_PREFIX}{leg}"] = v
    return out


def load_bench_file(path: str) -> Tuple[Optional[Dict[str, float]], str]:
    """One artifact → (metrics, note).  ``metrics`` is None when the file
    is skipped; ``note`` says why (or "ok")."""
    base = os.path.basename(path)
    if re.search(r"(_before|_pre|_baseline)\b|_before\.|_pre\.", base):
        return None, "baseline/before arm (skipped by design)"
    try:
        with open(path) as f:
            d = json.load(f)
    except FileNotFoundError:
        return None, "missing"
    except (OSError, ValueError) as e:
        return None, f"malformed: {e}"
    if not isinstance(d, dict):
        return None, "malformed: not a JSON object"

    # Knee matrix (benchmark/knee_matrix): per-committee-size saturation
    # knees.  Flattened to knee.n<N>.{rate,tps,latency_ms} — attribution
    # metrics (artifacts/ placement → attr. namespace, never gated); the
    # first-saturating channel names live in the artifact itself.
    if d.get("generated_by") == "benchmark/knee_matrix":
        metrics: Dict[str, float] = {}
        for cfg in d.get("configs") or []:
            n = cfg.get("n")
            knee = cfg.get("knee") or {}
            if not isinstance(n, int) or not knee:
                continue
            for key in ("rate", "tps", "latency_ms"):
                v = _num(knee.get(key))
                if v is not None:
                    metrics[f"knee.n{n}.{key}"] = v
        if metrics:
            return metrics, "ok (knee matrix)"
        return None, "knee matrix without located knees"

    # Cert-scheme paired capture (benchmark/cert_scheme_gate): per-
    # scheme sim wire captures at ONE pinned committee size.  The N=20
    # halfagg signature fraction graduates to the gated lower-is-better
    # `attr.cert_sig_bytes_fraction` series — unlike the bench-JSON
    # fraction (which moves with committee size and stays ungated),
    # this capture is deterministic per seed at a fixed size, so it is
    # cross-revision comparable.  Other sizes are tracked under
    # cert_scheme.n<N>.* informationally.
    if d.get("generated_by") == "benchmark/cert_scheme_gate":
        metrics = {}
        n = d.get("nodes")
        hl = d.get("headline") or {}
        hag = hl.get("halfagg") or {}
        if isinstance(n, int):
            frac = _num(hag.get("cert_sig_bytes_fraction"))
            ratio = _num(hl.get("cert_bytes_per_frame_ratio"))
            if frac is not None:
                metrics[f"cert_scheme.n{n}.halfagg_sig_fraction"] = frac
                if n == 20:
                    metrics["cert_sig_bytes_fraction"] = frac
            if ratio is not None:
                metrics[f"cert_scheme.n{n}.frame_ratio"] = ratio
        if metrics:
            return metrics, "ok (cert-scheme capture)"
        return None, "cert-scheme capture without headline numbers"

    # Driver wrapper: {n, cmd, rc, tail, parsed}.
    if "parsed" in d and "cmd" in d:
        rc = d.get("rc")
        if rc not in (0, None):
            return None, f"rc={rc} (failed run, skipped)"
        parsed = d.get("parsed")
        if not isinstance(parsed, dict):
            return None, "driver file without parsed JSON"
        metrics = _bench_result_metrics(parsed)
        if not metrics:
            return None, "no usable measurement (failed run published zeros)"
        return metrics, "ok"

    # A/B capture: {"runs": [bench-result, ...]} — median by the primary
    # throughput metric so one lucky/degraded run doesn't set the series.
    runs = d.get("runs")
    if isinstance(runs, list) and runs:
        cands = [
            (_bench_result_metrics(r), r) for r in runs if isinstance(r, dict)
        ]
        cands = [(m, r) for m, r in cands if m]
        if not cands:
            return None, "runs list without usable measurements"

        def tput(m: Dict[str, float]) -> float:
            return m.get("end_to_end_tps") or m.get("consensus_tps") or 0.0

        cands.sort(key=lambda mr: tput(mr[0]))
        metrics = cands[len(cands) // 2][0]
        return metrics, f"ok (median of {len(cands)} runs)"

    # Flat bench-result artifact.
    metrics = _bench_result_metrics(d)
    if metrics:
        return metrics, "ok"
    return None, "unrecognized shape (not a bench result)"


def collect(root: str, quiet: bool = False) -> Tuple[dict, List[dict]]:
    """Scan ``root`` → ({revision: {"metrics", "sources"}}, skipped)."""
    paths = sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))) + sorted(
        glob.glob(os.path.join(root, "artifacts", "*.json"))
    )
    revisions: Dict[str, dict] = {}
    skipped: List[dict] = []
    for path in paths:
        rel = os.path.relpath(path, root)
        rev = parse_revision(path)
        metrics, note = load_bench_file(path)
        if metrics is None or rev is None:
            if rev is None and metrics is not None:
                note = "no rNN revision in filename"
            skipped.append({"file": rel, "reason": note})
            if not quiet:
                print(
                    f"trajectory: skipping {rel}: {note}", file=sys.stderr
                )
            continue
        # Only the root BENCH_r* driver artifacts share a workload (the
        # per-run saturation probe), so only they feed the GATED series.
        # artifacts/ captures run pinned, usually lower rates (e.g. the
        # r07/r09 stage-breakdown attributions at rate 3000) — their
        # numbers are cross-revision comparable with each other but not
        # with the saturation probe, so they land in an `attr.`
        # namespace the gate config mostly never names (the one
        # exception: attr.cert_sig_bytes_fraction, whose pinned-size
        # deterministic capture is the comparability the namespace
        # split exists to protect — see LOWER_BETTER).
        if os.path.dirname(rel):
            metrics = {f"attr.{n}": v for n, v in metrics.items()}
        entry = revisions.setdefault(rev, {"metrics": {}, "sources": []})
        entry["sources"].append(rel)
        for name, v in metrics.items():
            # First loader wins per (revision, metric): BENCH_r* files
            # sort ahead of artifacts/, so the driver artifact is the
            # canonical source and artifacts only add what it lacks.
            entry["metrics"].setdefault(name, v)
    return revisions, skipped


def build_series(revisions: dict) -> Dict[str, List[Tuple[str, float]]]:
    series: Dict[str, List[Tuple[str, float]]] = {}
    for rev in sorted(revisions):
        for name, v in revisions[rev]["metrics"].items():
            series.setdefault(name, []).append((rev, v))
    return series


def load_gate_config(path: str) -> dict:
    """Pinned tolerances + waivers.  A missing/broken config falls back
    to gating nothing (loudly): a misplaced file must not turn the gate
    into a random failure generator."""
    try:
        with open(path) as f:
            cfg = json.load(f)
        if not isinstance(cfg, dict):
            raise ValueError("gate config must be a JSON object")
        return cfg
    except (OSError, ValueError) as e:
        print(
            f"trajectory: WARNING: gate config {path} unusable ({e}); "
            "gating disabled for this run",
            file=sys.stderr,
        )
        return {"tolerances": {}, "waivers": []}


def find_regressions(
    series: Dict[str, List[Tuple[str, float]]], config: dict
) -> List[dict]:
    """Every gated metric's value vs the best of all PRIOR revisions.
    Only metrics named in the config's ``tolerances`` are gated — the
    tolerance is pinned per metric, in-repo, on purpose."""
    tolerances: dict = config.get("tolerances") or {}
    waivers: List[dict] = config.get("waivers") or []
    out: List[dict] = []
    for name, points in sorted(series.items()):
        tol = tolerances.get(name)
        if tol is None or len(points) < 2:
            continue
        higher = name in HIGHER_BETTER
        if not higher and name not in LOWER_BETTER:
            continue  # informational metric; direction undefined
        best_v, best_rev = points[0][1], points[0][0]
        for rev, v in points[1:]:
            if higher:
                regressed = v < best_v * (1 - tol)
                change = v / best_v - 1
            else:
                regressed = v > best_v * (1 + tol)
                change = v / best_v - 1
            if regressed:
                waiver = next(
                    (
                        w
                        for w in waivers
                        if w.get("metric") == name
                        and w.get("revision") == rev
                    ),
                    None,
                )
                out.append(
                    {
                        "metric": name,
                        "revision": rev,
                        "value": v,
                        "baseline": best_v,
                        "baseline_revision": best_rev,
                        "change_pct": round(100 * change, 1),
                        "tolerance_pct": round(100 * tol, 1),
                        "waived": waiver is not None,
                        **(
                            {"waiver_reason": waiver.get("reason")}
                            if waiver
                            else {}
                        ),
                    }
                )
            if (higher and v > best_v) or (not higher and v < best_v):
                best_v, best_rev = v, rev
    return out


def render_table(series: Dict[str, List[Tuple[str, float]]]) -> str:
    revs = sorted({rev for pts in series.values() for rev, _ in pts})
    lines = []
    name_w = max((len(n) for n in series), default=6)
    header = "metric".ljust(name_w) + "".join(f"{r:>12}" for r in revs)
    lines.append(header)
    lines.append("-" * len(header))
    for name in sorted(series):
        vals = dict(series[name])
        row = name.ljust(name_w)
        for r in revs:
            v = vals.get(r)
            row += f"{v:>12.4g}" if v is not None else f"{'—':>12}"
        lines.append(row)
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=REPO, help="repo root to scan")
    ap.add_argument(
        "--gate-config",
        default=DEFAULT_GATE_CONFIG,
        help="pinned tolerances + waivers (benchmark/trajectory_gate.json)",
    )
    ap.add_argument(
        "--report", default=None, help="write the full JSON report here"
    )
    ap.add_argument(
        "--no-gate",
        action="store_true",
        help="report only; exit 0 even on unwaived regressions",
    )
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    revisions, skipped = collect(args.root, quiet=args.quiet)
    series = build_series(revisions)
    config = load_gate_config(args.gate_config)
    regressions = find_regressions(series, config)
    unwaived = [r for r in regressions if not r["waived"]]

    report = {
        "revisions": {
            rev: revisions[rev] for rev in sorted(revisions)
        },
        "series": {
            name: [[rev, v] for rev, v in pts]
            for name, pts in sorted(series.items())
        },
        "regressions": regressions,
        "skipped": skipped,
        "gate": {
            "config": args.gate_config,
            "tolerances": config.get("tolerances") or {},
            "unwaived_regressions": len(unwaived),
        },
    }
    if args.report:
        os.makedirs(os.path.dirname(args.report) or ".", exist_ok=True)
        with open(args.report, "w") as f:
            json.dump(report, f, indent=1)

    if not args.quiet:
        if series:
            print(render_table(series))
        else:
            print("trajectory: no usable bench artifacts found")
        for r in regressions:
            tag = "WAIVED" if r["waived"] else "REGRESSION"
            line = (
                f"{tag}: {r['metric']} {r['revision']} = {r['value']:g} "
                f"vs {r['baseline']:g} at {r['baseline_revision']} "
                f"({r['change_pct']:+.1f}%, tolerance "
                f"±{r['tolerance_pct']:.0f}%)"
            )
            if r["waived"]:
                line += f" — {r.get('waiver_reason')}"
            print(line, file=sys.stderr if not r["waived"] else sys.stdout)

    if unwaived and not args.no_gate:
        print(
            f"trajectory gate FAILED: {len(unwaived)} unwaived "
            "regression(s) beyond pinned tolerance",
            file=sys.stderr,
        )
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
