# One-command build + test entry point (the reference's CI does the same
# four steps: build all targets, test, fmt, lint — .github/workflows/rust.yml).
#
#   make check     build the native data plane, then run the test suite
#   make native    build native/libnarwhal_dp.so only
#   make bench     one driver benchmark run (prints the JSON line)
#   make clean     remove build products and bench scratch

PYTHON ?= python

.PHONY: check native test bench clean

check: native test

native:
	$(MAKE) -C native

test:
	$(PYTHON) -m pytest tests/ -x -q

# The crypto differential suite under the float32 lane dtype (the default
# run covers int32 + a narrow f32 subprocess check; run this after any
# change to narwhal_tpu/ops/field25519.py or ed25519.py).
test-f32:
	NARWHAL_FIELD_DTYPE=float32 $(PYTHON) -m pytest \
		tests/test_field25519.py tests/test_ed25519.py -x -q

bench: native
	$(PYTHON) bench.py

clean:
	$(MAKE) -C native clean
	rm -rf .bench .bench_remote .pytest_cache
