# One-command build + test entry point (the reference's CI does the same
# four steps: build all targets, test, fmt, lint — .github/workflows/rust.yml).
# .github/workflows/check.yml runs `make native lint test-ci` on every push.
#
#   make check     build the native data plane, lint, then run the test suite
#   make lint      syntax-compile every source tree (+ flake8 when installed)
#   make native    build native/libnarwhal_dp.so only
#   make bench     one driver benchmark run (prints the JSON line)
#   make clean     remove build products and bench scratch

PYTHON ?= python

.PHONY: check native lint lint-invariants test test-ci metrics-smoke \
	trace-smoke fault-smoke fault-fuzz-smoke trajectory race-explore \
	sim-smoke wire-ab-smoke crypto-ab-smoke commit-rule-smoke \
	cert-scheme-smoke knee-matrix knee-smoke sanitize bench clean

check: native lint test

native:
	$(MAKE) -C native

lint:
	$(PYTHON) -m compileall -q narwhal_tpu benchmark tests bench.py \
		bench_consensus.py bench_cadence.py bench_crypto.py \
		__graft_entry__.py
	@if $(PYTHON) -c "import flake8" 2>/dev/null; then \
		$(PYTHON) -m flake8 --select=F,E9 --extend-ignore=F401 \
			narwhal_tpu benchmark tests; \
	else \
		echo "flake8 not installed; syntax compile check only"; \
	fi
	$(PYTHON) -m narwhal_tpu.analysis

# Invariant linter alone, with the JSON findings report for the CI
# artifact upload (the `lint-invariants` job): AST rules over
# narwhal_tpu/ + benchmark/ — no-blocking-in-async, task-retention,
# wire-type coverage, metric-name drift, env-var registry + README
# env-table drift.  Nonzero exit on any non-pragma'd finding.
lint-invariants:
	mkdir -p .ci-artifacts
	$(PYTHON) -m narwhal_tpu.analysis \
		--report .ci-artifacts/lint-invariants.json

test:
	$(PYTHON) -m pytest tests/ -x -q

# CI variant: CPU backend pinned, tier-1 subset, no -x so one flaky test
# doesn't mask the rest of the report.
test-ci:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors

# Standalone in-process pipeline metrics test (4-node committee in one
# process; asserts sealed==committed+dropped and monotonic stage stamps),
# then a live-node /healthz probe: boots a real `node run` process with
# --metrics-port and fails on anything but 200 with zero firing rules.
# Dumps the final registry snapshot to .ci-artifacts/metrics-smoke.json,
# which CI uploads as a workflow artifact.
metrics-smoke: native
	JAX_PLATFORMS=cpu NARWHAL_METRICS_DUMP=.ci-artifacts \
		$(PYTHON) -m pytest tests/test_metrics_pipeline.py -x -q
	JAX_PLATFORMS=cpu $(PYTHON) benchmark/health_smoke.py

# Committee flight-recorder + trace-export smoke (ISSUE 11): drive the
# health-bench clean run (4-node local_bench with --trace-out) and drop
# the exported Perfetto trace, the quiesce flight rings, the scraped
# timeline, and the critical-path/straggler/clock artifact into
# .ci-artifacts/ for the workflow upload.  The test itself round-trips
# the trace (8 process rows, ≥1 cross-process digest flow, sampled-CPU
# track, committee critical-path row), asserts every node's flight ring
# is populated, and gates a non-empty critical_path whose per-leg sums
# telescope to the e2e span within 10%.
trace-smoke:
	JAX_PLATFORMS=cpu NARWHAL_METRICS_DUMP=.ci-artifacts \
		$(PYTHON) -m pytest tests/test_health_bench.py -x -q

# Fault-injection smoke: the two CI scenarios (one Byzantine, one
# crash/restart) through the scenario runner, each gated on the three
# machine-checked verdicts (safety/liveness/detection) plus the
# zero-false-positive control arm.  Artifacts in .ci-artifacts/.
fault-smoke:
	mkdir -p .ci-artifacts
	JAX_PLATFORMS=cpu $(PYTHON) benchmark/fault_bench.py \
		--scenario benchmark/scenarios/byz_wrong_key.json \
		--scenario benchmark/scenarios/crash_restart.json \
		--artifact '.ci-artifacts/fault-{name}.json'

# Worker-plane + fuzz smoke: one worker-plane Byzantine scenario, one
# multi-fault composition, and a bounded fuzz run (three fixed seeds
# through narwhal_tpu/faults/fuzz.py — each generated scenario is dumped
# as a replayable .spec.json beside its artifact), all three-verdict
# gated with clean-control arms.  Artifacts in .ci-artifacts/.
fault-fuzz-smoke:
	mkdir -p .ci-artifacts
	JAX_PLATFORMS=cpu $(PYTHON) benchmark/fault_bench.py \
		--scenario benchmark/scenarios/byz_sync_flood.json \
		--scenario benchmark/scenarios/compose_equivocate_wan_lossy.json \
		--fuzz-seed 101 --fuzz-seed 202 --fuzz-seed 303 \
		--artifact '.ci-artifacts/fault-{name}.json'

# Cross-revision perf-trajectory gate (benchmark/trajectory.py): reads
# every BENCH_r*.json + recognizable artifacts/ bench capture, renders
# the revision series, and exits nonzero on any regression beyond the
# tolerances pinned in benchmark/trajectory_gate.json that no waiver
# names.  The rendered report lands in .ci-artifacts/ for upload.
trajectory:
	mkdir -p .ci-artifacts
	$(PYTHON) benchmark/trajectory.py \
		--report .ci-artifacts/trajectory.json

# narwhal-race schedule explorer (ISSUE 10): 16 seeded schedules of the
# reference pipeline scenario must commit byte-identically to the golden
# walk (plus a same-seed reproducibility pin), the socketed 4-node
# committee arm must pass its golden-replay + cross-node-prefix safety
# verdicts per seed, and the planted RacyConsensus race must be caught
# by BOTH the static interleave rule and a divergent schedule (the
# non-vacuity gate).  Divergent seeds dump `*.repro-<seed>.json` repros
# next to the artifact; replay one with
# `python benchmark/race_explore.py --repro <seed> [--mutated]`.
race-explore:
	mkdir -p .ci-artifacts
	JAX_PLATFORMS=cpu $(PYTHON) benchmark/race_explore.py \
		--seeds 16 --committee-seeds 4 \
		--artifact .ci-artifacts/race-explore.json

# Deterministic committee-at-scale simulation sweep (ISSUE 12): ≥200
# fuzzed (seed × fault × committee-size) points — sizes 4/7/10/20, at
# least one N=20 — run single-process on the virtual clock and judged
# by the three-verdict engine (golden-replay safety, virtual-time
# liveness, health-rule detection), plus per-size clean controls (zero
# firings), a same-seed bit-reproducibility pin, the planted-mutation
# honesty arms (RacyConsensus + stripped-expectation Byzantine), and
# the N=20/60-virtual-second acceptance arm whose wall-clock
# compression ratio is measured and gated.  Failing points dump
# replayable (seed, spec) repro files beside the artifact; replay one
# with `python benchmark/sim_bench.py --replay <file>`.
sim-smoke:
	mkdir -p .ci-artifacts
	JAX_PLATFORMS=cpu $(PYTHON) benchmark/sim_bench.py \
		--points 200 --artifact .ci-artifacts/sim-smoke.json --quiet

# Paired interleaved wire-format A/B (ISSUE 13): legacy
# (NARWHAL_WIRE_V2=0) vs v2 arms on a short 4-node local_bench,
# ledger-read gates — v2 goodput_ratio >= 0.45 at committed TPS no
# worse than the legacy arm (within the shared-host noise floor),
# sender_coverage ≈ 1.0 and protocol_check within 5% on BOTH arms.
# The before/after artifact is uploaded by the workflow.
wire-ab-smoke:
	mkdir -p .ci-artifacts
	JAX_PLATFORMS=cpu $(PYTHON) benchmark/wire_ab.py \
		--pairs 2 --duration 8 \
		--artifact .ci-artifacts/wire-ab.json

# Paired interleaved crypto A/B (ISSUE 14): serial per-burst verify
# (cpu backend, window off) vs the batched arm (verify-batch window on;
# --batched-backend cpu on deviceless CI runners — the window deepening
# is backend-independent, and the jax kernel's verdicts are covered by
# tests/test_backend_differential.py).  Ledger-read gates: zero errors
# + protocol_check within 5% on BOTH arms, and the batched arm's
# crypto.verify.batch_size.batch_burst mean >= the serial arm's at
# committed TPS no worse than the noise floor.
crypto-ab-smoke:
	mkdir -p .ci-artifacts
	JAX_PLATFORMS=cpu $(PYTHON) benchmark/crypto_ab.py \
		--pairs 2 --duration 8 --batched-backend cpu \
		--min-batch-mean 0 \
		--artifact .ci-artifacts/crypto-ab.json

# Commit-rule smoke (ISSUE 15; ISSUE 19 adds the multileader arm): the
# non-classic rules' full validation ladder in CI-affordable sizes —
# (a) the equivalence + flag-plumbing suites (each live rule
# byte-identical to ITS frozen oracle, classic byte-identical to
# GoldenTusk, cross-rule checkpoint refusal in all six directions,
# audit rule markers); (b) one race-explore run per non-classic rule:
# 16 seeded schedules byte-identical to that rule's oracle + the
# socketed committee replay verdicts + the planted race caught; (c) a
# sim flag-flip mini-sweep (--commit-rule all): every fuzzed point,
# control, mutation and acceptance arm under EACH of the three rules,
# three verdicts per arm, per-arm virtual-time cert→commit means in
# the artifact.  The full-size flag-flip sweep (200 points) is the
# release gate run manually; this keeps every arm of it exercised per
# push.
commit-rule-smoke:
	mkdir -p .ci-artifacts
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest \
		tests/test_lowdepth_equivalence.py \
		tests/test_multileader_equivalence.py -x -q
	JAX_PLATFORMS=cpu $(PYTHON) benchmark/race_explore.py \
		--seeds 16 --committee-seeds 2 --commit-rule lowdepth \
		--workdir .race_explore_lowdepth \
		--artifact .ci-artifacts/race-explore-lowdepth.json
	JAX_PLATFORMS=cpu $(PYTHON) benchmark/race_explore.py \
		--seeds 16 --committee-seeds 2 --commit-rule multileader \
		--workdir .race_explore_multileader \
		--artifact .ci-artifacts/race-explore-multileader.json
	JAX_PLATFORMS=cpu $(PYTHON) benchmark/sim_bench.py \
		--points 20 --commit-rule all --mutation-seeds 8 \
		--workdir .sim_commit_rule \
		--artifact .ci-artifacts/sim-commit-rule-flip.json --quiet

# Certificate-signature-scheme smoke (ISSUE 20): the frozen
# differential/refusal suite (halfagg must never accept what
# individual rejects; cross-scheme frames and checkpoints refuse
# loudly), then the paired per-scheme N=20 sim wire captures gated on
# the half-aggregation floor — exactly 1 verify op/cert, sig fraction
# <= 0.5, cert bytes/frame < 0.75x individual.  The gate driver's
# docstring explains why the thresholds are NOT the ISSUE's 0.25/0.6
# (those price a pairing aggregate; no pairing library in-container).
cert-scheme-smoke:
	mkdir -p .ci-artifacts
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_cert_scheme.py -x -q
	JAX_PLATFORMS=cpu $(PYTHON) benchmark/cert_scheme_gate.py \
		--nodes 20 \
		--artifact .ci-artifacts/cert_scheme_gate_n20.json

# Saturation-knee matrix (ISSUE 17): sweep offered load across
# committee sizes (socketed N=4, sim N=10/20), locate each config's
# TPS/latency knee, and name the first-saturating inter-task channel
# at the knee from the InstrumentedQueue accounting.  The full matrix
# is a release artifact (artifacts/knee_matrix_<rev>.json); knee-smoke
# is the 2-point N=4 CI arm, gated on a non-empty queue attribution.
knee-matrix: native
	JAX_PLATFORMS=cpu $(PYTHON) benchmark/knee_matrix.py

knee-smoke:
	mkdir -p .ci-artifacts
	JAX_PLATFORMS=cpu $(PYTHON) benchmark/knee_matrix.py \
		--smoke --duration 8 \
		--out .ci-artifacts/knee-smoke.json

# Asyncio sanitizer tier (ISSUE 10): the fast concurrency-sensitive
# tier-1 subset under `python -X dev` — asyncio debug mode with the
# slow-callback threshold aligned to the PR 9 watchdog default
# (NARWHAL_LOOP_WATCHDOG_MS=100 arms it on node-booting tests, and
# loop.slow_callback_duration follows it), plus ResourceWarning
# escalated to an error: an unclosed socket/file surfacing at GC is a
# task-teardown bug, not noise.
sanitize:
	JAX_PLATFORMS=cpu NARWHAL_LOOP_WATCHDOG_MS=100 \
		$(PYTHON) -X dev -W error::ResourceWarning -m pytest \
		tests/test_store.py tests/test_tasks.py \
		tests/test_sync_timeouts.py \
		tests/test_checkpoint_under_load.py tests/test_schedule.py \
		tests/test_interleave.py -q

# The crypto differential suite under the float32 lane dtype (the default
# run covers int32 + a narrow f32 subprocess check; run this after any
# change to narwhal_tpu/ops/field25519.py or ed25519.py).
test-f32:
	NARWHAL_FIELD_DTYPE=float32 $(PYTHON) -m pytest \
		tests/test_field25519.py tests/test_ed25519.py -x -q

bench: native
	$(PYTHON) bench.py

clean:
	$(MAKE) -C native clean
	rm -rf .bench .bench_remote .bench_wire_ab .bench_crypto_ab \
		.bench_commit_rule_ab .race_explore_lowdepth \
		.race_explore_multileader .sim_commit_rule \
		.sim_crypto_ab .sim_wire_capture .pytest_cache .ci-artifacts
