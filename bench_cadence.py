#!/usr/bin/env python3
"""Round-cadence microbenchmark: the Core's header→vote→cert round-trip.

The r09 cert→commit attribution showed 97-98% of commit latency is protocol
cadence — `primary.round_advance_seconds` × commit depth — so this bench
isolates ONE round of that cadence through a live Core event loop: own
header in → own vote → 2f peer votes → our certificate assembled → 2f
peer certificates → parent quorum out.  Two arms, interleaved A/B per
iteration (ISSUE r10):

- **fast** — the vote fast path (`Core(fast_path=True)`, the default):
  header store records buffered via ``Store.write_deferred`` and flushed
  ONCE per drained burst before the staged votes leave, per-burst GC,
  cached committee address lists.
- **legacy** — ``Core(fast_path=False)``: one writev per header on the
  processing path, votes sent per header (the pre-r10 behavior; GC and
  address caching stay, so the arms isolate the persist/vote coalescing).

Honesty notes: signature batch verification is STUBBED (always-true mask)
— this measures cadence machinery, not crypto (the ed25519 cost is
measured by bench_crypto.py and identical in both arms); the network is a
null sender (loopback TCP would time the kernel, not the Core); the store
log lives on tmpfs when available (same reasoning as local_bench).  What
remains is exactly the per-round critical path the round period is made
of: queue hops, sanitize/replay, store persists, aggregation.

    python bench_cadence.py --sizes 4 20 50 --rounds 40 --iters 5 \
        --artifact artifacts/cadence_bench.json

``--gate`` turns on the CI regression gate: the fast arm's median
seconds-per-round must not exceed the legacy arm's by more than
``--gate-max-slowdown`` (default 1.15) at any committee size.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import statistics
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

from bench_consensus import make_committee  # noqa: E402
from narwhal_tpu.crypto import Signature, SignatureService  # noqa: E402
from narwhal_tpu.primary.core import AtomicRound, Core  # noqa: E402
from narwhal_tpu.primary.messages import (  # noqa: E402
    Certificate,
    Header,
    Vote,
    genesis,
)
from narwhal_tpu.primary.synchronizer import Synchronizer  # noqa: E402
from narwhal_tpu.store import Store  # noqa: E402


class NullSender:
    """Stands in for ReliableSender: the bench times the Core, not TCP.
    Returns never-completing futures so cancel_handlers bookkeeping (and
    its GC) costs exactly what it costs live."""

    def __init__(self) -> None:
        self.sent = 0

    def _fut(self):
        return asyncio.get_running_loop().create_future()

    def send(self, address, message):
        self.sent += 1
        return self._fut()

    def broadcast(self, addresses, message):
        self.sent += len(addresses)
        return [self._fut() for _ in addresses]

    def close(self) -> None:
        pass


def prebuild_rounds(committee, kps, me_kp, rounds: int):
    """Pre-create every message OUTSIDE the timed region (construction +
    hashing is identical for both arms; signatures are dummy bytes since
    the batch verify is stubbed).  Per round: (own header, peer votes for
    it, peer certificates of the same round)."""
    dummy = Signature(bytes(64))
    me = me_kp.name
    others = [kp.name for kp in kps if kp.name != me]
    quorum = committee.quorum_threshold()
    names = sorted(committee.authorities.keys())
    parents = {c.digest() for c in genesis(committee)}
    out = []
    for r in range(1, rounds + 1):
        header = Header(author=me, round=r, payload={}, parents=set(parents))
        header.id = header.compute_digest()
        header.signature = dummy
        # Own vote (cast inline by the Core) counts 1; top up to quorum.
        votes = [
            Vote(id=header.id, round=r, origin=me, author=name, signature=dummy)
            for name in others[: quorum - 1]
        ]
        my_cert_digest = Certificate(header=header).digest()
        peer_certs = []
        for name in others:
            oh = Header(author=name, round=r, payload={}, parents=set(parents))
            oh.id = oh.compute_digest()
            oh.signature = dummy
            cert_votes = [
                (v, dummy) for v in names if v != name
            ][: quorum]
            peer_certs.append(Certificate(header=oh, votes=cert_votes))
        parents = {my_cert_digest} | {c.digest() for c in peer_certs}
        out.append((header, votes, peer_certs))
    return out


async def run_arm(committee, kps, me_kp, prebuilt, fast_path: bool, store_path: str):
    """Drive the prebuilt rounds through a live Core.run() loop; returns
    wall seconds per round (header in → parent quorum out)."""
    from narwhal_tpu.crypto import backend as crypto_backend

    real = crypto_backend.averify_batch_mask

    async def stub(msgs, keys, sigs, site="other"):
        return [True] * len(msgs)

    crypto_backend.averify_batch_mask = stub
    store = Store(store_path)
    qs = {
        name: asyncio.Queue()
        for name in (
            "primaries", "header_sync", "cert_sync", "header_loop",
            "cert_loop", "proposer_in", "consensus", "proposer_out",
        )
    }
    synchronizer = Synchronizer(
        me_kp.name, committee, store, qs["header_sync"], qs["cert_sync"]
    )
    core = Core(
        me_kp.name,
        committee,
        store,
        synchronizer,
        SignatureService(me_kp),
        AtomicRound(),
        gc_depth=50,
        rx_primaries=qs["primaries"],
        rx_header_waiter=qs["header_loop"],
        rx_certificate_waiter=qs["cert_loop"],
        rx_proposer=qs["proposer_in"],
        tx_consensus=qs["consensus"],
        tx_proposer=qs["proposer_out"],
        fast_path=fast_path,
    )
    core.network = NullSender()
    task = asyncio.get_running_loop().create_task(core.run())
    try:
        t0 = time.perf_counter()
        for header, votes, peer_certs in prebuilt:
            await qs["proposer_in"].put(header)
            # The Core must adopt the header before its votes are valid.
            while core.current_header is not header:
                await asyncio.sleep(0)
            for v in votes:
                qs["primaries"].put_nowait(("vote", v))
            for c in peer_certs:
                qs["primaries"].put_nowait(("certificate", c))
            await qs["proposer_out"].get()  # parent quorum for this round
        dt = time.perf_counter() - t0
    finally:
        # Restore the backend FIRST: store.close() can raise (it flushes
        # deferred records), and a leaked always-true verify stub would
        # silently poison every later arm in this process.
        crypto_backend.averify_batch_mask = real
        task.cancel()
        await asyncio.gather(task, return_exceptions=True)
        store.close()
    if os.path.exists(store_path):
        os.remove(store_path)
    return dt / len(prebuilt)


def bench_size(n: int, rounds: int, iters: int, storedir: str):
    committee, kps = make_committee(n, return_keypairs=True)
    me_kp = kps[0]
    prebuilt = prebuild_rounds(committee, kps, me_kp, rounds)
    samples = {"fast": [], "legacy": []}
    # Interleaved A/B: one fast + one legacy run per iteration, so host
    # noise (thermal drift, background load) lands on both arms equally.
    for i in range(iters):
        for arm, fast in (("fast", True), ("legacy", False)):
            path = os.path.join(storedir, f"cadence-{n}-{arm}-{i}.log")
            s = asyncio.run(
                run_arm(committee, kps, me_kp, prebuilt, fast, path)
            )
            samples[arm].append(s)
    med = {arm: statistics.median(v) for arm, v in samples.items()}
    return {
        "committee": n,
        "rounds": rounds,
        "iters": iters,
        "seconds_per_round": {
            arm: {
                "median": med[arm],
                "min": min(v),
                "mean": statistics.fmean(v),
                "samples": v,
            }
            for arm, v in samples.items()
        },
        "fast_vs_legacy": (
            med["legacy"] / med["fast"] if med["fast"] > 0 else None
        ),
    }


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--sizes", type=int, nargs="+", default=[4, 20, 50])
    parser.add_argument("--rounds", type=int, default=40)
    parser.add_argument("--iters", type=int, default=5)
    parser.add_argument("--artifact", default=None)
    parser.add_argument(
        "--gate",
        action="store_true",
        help="fail (exit 1) if the fast arm's median is more than "
        "--gate-max-slowdown × the legacy arm's at any size",
    )
    parser.add_argument("--gate-max-slowdown", type=float, default=1.15)
    args = parser.parse_args()

    # Same tmpfs preference as local_bench: the store log's writev costs
    # should reflect page-cache appends, not a CI runner's disk.
    storedir = "/dev/shm" if os.path.isdir("/dev/shm") else None
    tmp = tempfile.mkdtemp(prefix="cadence_bench_", dir=storedir)
    try:
        results = []
        for n in args.sizes:
            r = bench_size(n, args.rounds, args.iters, tmp)
            results.append(r)
            f, l = (
                r["seconds_per_round"]["fast"]["median"],
                r["seconds_per_round"]["legacy"]["median"],
            )
            print(
                f"N={n:3d}: fast {1e6 * f:8.1f} us/round, "
                f"legacy {1e6 * l:8.1f} us/round, "
                f"ratio legacy/fast {r['fast_vs_legacy']:.2f}x"
            )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    artifact = {
        "bench": "cadence",
        "note": (
            "header->vote->cert round-trip through Core.run; signature "
            "batch verify stubbed (always true), network nulled — "
            "cadence machinery only.  Arms interleaved per iteration."
        ),
        "results": results,
    }
    if args.artifact:
        os.makedirs(os.path.dirname(args.artifact) or ".", exist_ok=True)
        with open(args.artifact, "w") as f:
            json.dump(artifact, f, indent=1)
        print(f"artifact written to {args.artifact}")

    if args.gate:
        for r in results:
            f = r["seconds_per_round"]["fast"]["median"]
            l = r["seconds_per_round"]["legacy"]["median"]
            if f > l * args.gate_max_slowdown:
                print(
                    f"GATE FAILED at N={r['committee']}: fast median "
                    f"{1e6 * f:.1f} us/round exceeds legacy "
                    f"{1e6 * l:.1f} us/round by more than "
                    f"{args.gate_max_slowdown:.2f}x",
                    file=sys.stderr,
                )
                return 1
        print("gate passed: fast arm within "
              f"{args.gate_max_slowdown:.2f}x of legacy at every size")
    return 0


if __name__ == "__main__":
    sys.exit(main())
