#!/usr/bin/env python3
"""KernelTusk microbenchmark: device-resident commit path vs golden Python.

The reference's commit rule does one `linked()` BFS per earlier leader per
commit attempt (consensus/src/lib.rs:224-259); KernelTusk collapses the
whole chain into one jitted scan over a device-resident dense window
(narwhal_tpu/ops/reachability.py).  This measures BOTH protocol phases for
both implementations over identical DAG state at committee sizes
N ∈ {4, 20, 50} and a gc_depth-50 window:

- **insert** — the certificate-arrival path.  Python: one dict insert.
  Kernel: one dict insert + an O(1) staging append (all window resolution
  is deferred to the commit opportunity).  Reported as the min wall time
  of inserting `span` PRE-CREATED full rounds over `--build-reps`
  interleaved passes — certificate construction/hashing is excluded (it
  is identical for both arms and an order of magnitude heavier than the
  arrival path, so timing it in-loop drowned the comparison in jitter).
- **commit** — one commit opportunity.  Python: `order_leaders` (the
  linked-BFS chain walk).  Kernel: flush the staged arrivals since the
  last opportunity (two rounds' worth — one `window_apply` scatter
  dispatch at steady state) + one `leader_commit_scan` dispatch + the
  W-bool committed-bitmap fetch.  The per-iteration re-staging makes the
  kernel number an honest STEADY-STATE cost, not an empty-pending fast
  path.
- **commit burst** (PR 4) — a full multi-leader commit: odd rounds
  delivered first so nothing commits until one trigger certificate
  flattens the ENTIRE chain in a single `process_certificate` call.
  Three arms over identical streams: the frozen r06 dict walk
  (`consensus/golden.py`, the equivalence oracle), the live indexed walk
  (`consensus/tusk.py` — digest-index parent resolution, incremental
  support, one GC sweep per burst), and the device kernel (whose burst
  pays the catch-up window flush).  The acceptance gate (ISSUE r09) is
  indexed ≥ 2× the dict walk at N ≥ 20 over a 50-round DAG.

Floor honesty: every kernel commit pays one device round trip for the
bitmap fetch.  On a tunneled/remote chip that fetch floor (~69 ms
measured in round 5) dominates; on a host-local device it is ~0.1 ms.
The artifact reports the measured floor, the raw speedup, and the
floor-subtracted speedup (the host-local-chip estimate) side by side —
the acceptance gate (ISSUE r06) is floor-subtracted commit speedup > 1
at N ≥ 20 AND kernel insert ≤ Python insert.

    python bench_consensus.py --sizes 4 20 50 --span 48 --iters 9 \
        --artifact artifacts/consensus_bench.json
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

from narwhal_tpu.config import (  # noqa: E402
    Authority,
    Committee,
    PrimaryAddresses,
    WorkerAddresses,
)
from narwhal_tpu.crypto import KeyPair  # noqa: E402
from narwhal_tpu.consensus.golden import GoldenTusk  # noqa: E402
from narwhal_tpu.consensus.golden_multileader import (  # noqa: E402
    GoldenMultiLeaderTusk,
)
from narwhal_tpu.consensus.tusk import MultiLeaderTusk, Tusk  # noqa: E402
from narwhal_tpu.primary.messages import Certificate, Header, genesis  # noqa: E402


def make_committee(n: int, return_keypairs: bool = False):
    """Seeded stake-1 loopback committee — the shared microbench fixture
    (bench_cadence.py imports this; keep the one construction site)."""
    kps = [
        KeyPair.generate(rng_seed=i.to_bytes(32, "little")) for i in range(n)
    ]
    auths = {}
    for kp in kps:
        auths[kp.name] = Authority(
            stake=1,
            primary=PrimaryAddresses("127.0.0.1:0", "127.0.0.1:0"),
            workers={0: WorkerAddresses("127.0.0.1:0", "127.0.0.1:0", "127.0.0.1:0")},
        )
    committee = Committee(auths)
    return (committee, kps) if return_keypairs else committee


def mock_certificate(origin, round_, parents) -> Certificate:
    header = Header(
        author=origin, round=round_, payload={}, parents=set(parents)
    )
    return Certificate(header=header, votes=[])


def make_dag_certs(committee: Committee, span: int):
    """Pre-create `span` full rounds of certificates (the densest, worst
    case) OUTSIDE any timed region: certificate construction and header
    hashing are identical for both implementations and an order of
    magnitude heavier than the arrival path itself — timing them alongside
    the inserts drowned the comparison in shared-core jitter.  Returns
    (certs_in_arrival_order, tail_certs) where tail_certs are the last two
    rounds — the re-staging unit for steady-state commit measurement."""
    names = sorted(committee.authorities.keys())
    parents = {c.digest() for c in genesis(committee)}
    certs, rounds = [], []
    for r in range(1, span + 1):
        nxt = set()
        this_round = []
        for name in names:
            cert = mock_certificate(name, r, parents)
            certs.append(cert)
            this_round.append(cert)
            nxt.add(cert.digest())
        rounds.append(this_round)
        parents = nxt
    tail = [c for rnd in rounds[-2:] for c in rnd]
    return certs, tail


def build_state(tusk: Tusk, certs) -> float:
    """Feed pre-created certificates through insert_certificate (the
    arrival path, commit rule bypassed); returns the wall seconds of the
    insert loop alone."""
    t0 = time.perf_counter()
    for cert in certs:
        tusk.insert_certificate(cert)
    return time.perf_counter() - t0


def find_anchor(tusk: Tusk, committee: Committee, span: int):
    anchor_round = span if span % 2 == 0 else span - 1
    n = len(committee.authorities)
    leader_name = tusk._sorted_keys[
        0 if tusk.fixed_coin else anchor_round % n
    ]
    return tusk.state.dag[anchor_round][leader_name][1]


def bench_pair(kernel_cls, committee, span, iters, build_reps):
    """Measure BOTH implementations with interleaved timed regions: on a
    shared-core host, back-to-back phases land in different scheduling
    windows and a ±5× jitter swamps the comparison (observed while
    building this bench); alternating python/kernel inside each rep makes
    both arms share the same noise."""
    # Absorb jit compiles / cache loads outside every timed region.
    kernel_cls(committee, gc_depth=50, fixed_coin=True).prewarm()
    certs, tail = make_dag_certs(committee, span)

    py_ins, ke_ins = [], []
    py = ke = None
    for rep in range(max(1, build_reps)):
        builds = [
            (Tusk, py_ins),
            (kernel_cls, ke_ins),
        ]
        if rep % 2:  # alternate order to cancel slow-window drift
            builds.reverse()
        for cls, sink in builds:
            tusk = cls(committee, gc_depth=50, fixed_coin=True)
            sink.append(build_state(tusk, certs))
            if cls is Tusk:
                py = tusk
            else:
                ke = tusk
    py_anchor = find_anchor(py, committee, span)
    ke_anchor = find_anchor(ke, committee, span)

    # First kernel call: flushes the ENTIRE span in chunked scatter
    # dispatches (the catch-up worst case); reported separately.
    t0 = time.perf_counter()
    ke_chain = ke.order_leaders(ke_anchor)
    first_call_s = time.perf_counter() - t0

    py_commit, ke_commit = [], []
    py_chain = None
    for _ in range(iters):
        t0 = time.perf_counter()
        py_chain = py.order_leaders(py_anchor)
        py_commit.append(time.perf_counter() - t0)
        # Steady state for the kernel: a commit opportunity arrives every
        # two rounds, so each measured call flushes two rounds' worth of
        # staged certificates (idempotent device scatter) before the scan.
        ke._pending.extend(tail)
        t0 = time.perf_counter()
        ke_chain = ke.order_leaders(ke_anchor)
        ke_commit.append(time.perf_counter() - t0)
    # Insert reports min-of-reps: the arms differ by one list append per
    # certificate, far below this host's scheduling jitter, and min is the
    # least-noise estimator for identical CPU-bound work.  Commit reports
    # the median of the interleaved iterations.
    return {
        "python": {
            "insert_s": min(py_ins),
            "commit_s": statistics.median(py_commit),
            "chain": [bytes(c.digest()) for c in py_chain],
        },
        "kernel": {
            "insert_s": min(ke_ins),
            "commit_s": statistics.median(ke_commit),
            "first_call_s": first_call_s,
            "chain": [bytes(c.digest()) for c in ke_chain],
        },
    }


def make_burst_certs(committee: Committee, rounds: int):
    """A multi-leader commit-burst stream: odd rounds delivered before
    even rounds, so NO arrival can trigger a commit (odd-round arrivals
    find no even-round leader yet; even-round arrivals never run the
    commit check) — until one final trigger certificate commits the
    ENTIRE chain of linked leaders in a single process_certificate call.
    This is the worst case for the golden walk's per-certificate
    ``State.update`` full sweep (quadratic in burst size) and the shape
    the indexed walk's batched sweep targets."""
    names = sorted(committee.authorities.keys())
    parents = {c.digest() for c in genesis(committee)}
    certs = []
    for r in range(1, rounds + 1):
        nxt = set()
        for name in names:
            cert = mock_certificate(name, r, parents)
            certs.append(cert)
            nxt.add(cert.digest())
        parents = nxt
    order = sorted(certs, key=lambda c: (c.round % 2 == 0, c.round))
    trigger = mock_certificate(names[0], rounds + 1, parents)
    return order, trigger


def bench_commit_burst(
    kernel_cls, committee: Committee, rounds: int, iters: int, floor_s: float
):
    """One multi-leader burst commit, measured per implementation arm:
    the frozen r06 dict walk (GoldenTusk — the oracle), the indexed walk
    (Tusk), and the device kernel.  State is rebuilt per iteration (the
    burst consumes it); only the trigger call is timed.  Arms interleave
    inside each iteration so shared-core scheduling noise hits all three
    equally (same rationale as bench_pair).  Returns median seconds per
    arm plus the burst size; asserts all arms commit byte-identical
    sequences."""
    order, trigger = make_burst_certs(committee, rounds)
    gc_depth = rounds + 4
    arms = [("dict_walk", GoldenTusk), ("indexed", Tusk)]
    if kernel_cls is not None:
        arms.append(("kernel", kernel_cls))
    times = {name: [] for name, _ in arms}
    chains = {}
    for rep in range(max(1, iters)):
        plan = list(arms)
        if rep % 2:  # alternate order to cancel slow-window drift
            plan.reverse()
        for name, cls in plan:
            tusk = cls(committee, gc_depth=gc_depth, fixed_coin=True)
            for cert in order:
                tusk.process_certificate(cert)
            t0 = time.perf_counter()
            seq = tusk.process_certificate(trigger)
            times[name].append(time.perf_counter() - t0)
            chains[name] = [bytes(x.digest()) for x in seq]
    want = chains["dict_walk"]
    assert want, "burst fixture committed nothing"
    for name, chain in chains.items():
        assert chain == want, (
            f"commit-burst sequences diverge: {name} emitted "
            f"{len(chain)} certs vs dict_walk {len(want)}"
        )
    out = {
        "burst_rounds": rounds,
        "burst_committed_certs": len(want),
        "dict_walk_ms": round(
            statistics.median(times["dict_walk"]) * 1e3, 3
        ),
        "indexed_ms": round(statistics.median(times["indexed"]) * 1e3, 3),
    }
    out["indexed_speedup_vs_dict"] = round(
        statistics.median(times["dict_walk"])
        / statistics.median(times["indexed"]),
        2,
    )
    if kernel_cls is not None:
        ke = statistics.median(times["kernel"])
        out["kernel_ms"] = round(ke * 1e3, 3)
        # Floor honesty, same policy as the steady-state commit phase:
        # the kernel burst pays one committed-bitmap fetch.
        out["kernel_ms_floor_subtracted"] = round(
            max(ke - floor_s, 0.0) * 1e3, 3
        )
    return out


def make_ml_burst_certs(committee: Committee, rounds: int):
    """A commit-burst stream for the MULTILEADER rule.  The classic burst
    shape (odd rounds first) does not defer multileader commits — every
    even round's slot anchors the moment its odd-round support quorum
    lands — so this stream starves the quorum instead: every round is
    delivered ascending, but each odd round ships only 2f stake of
    certificates (one short of the 2f+1 the direct anchor needs, and
    with zero non-support, so every slot stays UNDECIDED — never dead).
    Nothing can commit until one trigger certificate — the withheld
    round-(rounds-1) support cert — closes the top anchor's quorum and
    flattens the ENTIRE slot chain in a single process_certificate
    call."""
    names = sorted(committee.authorities.keys())
    quorum = committee.quorum_threshold()
    parents = {c.digest() for c in genesis(committee)}
    order, trigger = [], None
    for r in range(1, rounds + 1):
        nxt = set()
        stake = 0
        for name in names:
            cert = mock_certificate(name, r, parents)
            nxt.add(cert.digest())
            if r % 2 == 0:
                order.append(cert)
            elif stake + committee.stake(name) < quorum:
                order.append(cert)
                stake += committee.stake(name)
            elif trigger is None and r == rounds - 1:
                trigger = cert  # the quorum-closing support cert
        parents = nxt
    return order, trigger


def bench_commit_burst_multileader(committee: Committee, rounds: int, iters: int):
    """The multileader commit-burst arm (ISSUE r19).  The rule commits a
    DIFFERENT sequence than classic by design (slot anchors, cone-based
    indirect members), so it cannot be judged against the dict_walk arm:
    it gets its own oracle pair — the frozen naive walk
    (``golden_multileader.py``) vs the live indexed rule — interleaved
    exactly like the classic arms, asserted byte-identical to each
    other."""
    order, trigger = make_ml_burst_certs(committee, rounds)
    gc_depth = rounds + 4
    arms = [
        ("ml_dict_walk", GoldenMultiLeaderTusk),
        ("ml_indexed", MultiLeaderTusk),
    ]
    times = {name: [] for name, _ in arms}
    chains = {}
    for rep in range(max(1, iters)):
        plan = list(arms)
        if rep % 2:  # alternate order to cancel slow-window drift
            plan.reverse()
        for name, cls in plan:
            tusk = cls(committee, gc_depth=gc_depth, fixed_coin=True)
            for cert in order:
                tusk.process_certificate(cert)
            t0 = time.perf_counter()
            seq = tusk.process_certificate(trigger)
            times[name].append(time.perf_counter() - t0)
            chains[name] = [bytes(x.digest()) for x in seq]
    want = chains["ml_dict_walk"]
    assert want, "multileader burst fixture committed nothing"
    assert chains["ml_indexed"] == want, (
        "multileader commit-burst sequences diverge: indexed emitted "
        f"{len(chains['ml_indexed'])} certs vs its oracle {len(want)}"
    )
    return {
        "burst_rounds": rounds,
        "burst_committed_certs": len(want),
        "ml_dict_walk_ms": round(
            statistics.median(times["ml_dict_walk"]) * 1e3, 3
        ),
        "ml_indexed_ms": round(
            statistics.median(times["ml_indexed"]) * 1e3, 3
        ),
        "ml_indexed_speedup_vs_dict": round(
            statistics.median(times["ml_dict_walk"])
            / statistics.median(times["ml_indexed"]),
            2,
        ),
    }


def measure_fetch_floor():
    """Fixed device round-trip floor on this host: median wall time of a
    trivial jitted compute + result fetch.  On a tunneled/remote chip this
    floor (not the scan) dominates kernel commit time; on a host-local
    chip it is ~0.1 ms."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros(8, jnp.int32)
    np.asarray(f(x))
    ts = []
    for _ in range(7):
        t0 = time.perf_counter()
        np.asarray(f(x))
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[3]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", type=int, nargs="+", default=[4, 20, 50])
    ap.add_argument("--span", type=int, default=48)
    ap.add_argument("--iters", type=int, default=9)
    ap.add_argument("--build-reps", type=int, default=3)
    ap.add_argument(
        "--burst-rounds",
        type=int,
        default=50,
        help="Rounds in the multi-leader commit-burst phase (odd rounds "
        "delivered first; one trigger commits the whole chain).  Must be "
        "even — the trigger at rounds+1 only fires the commit rule from "
        "an odd round; odd values are rounded up.",
    )
    ap.add_argument("--burst-iters", type=int, default=5)
    ap.add_argument("--artifact", type=str, default=None)
    args = ap.parse_args()
    if args.burst_rounds % 2:
        args.burst_rounds += 1  # see --burst-rounds help: must be even

    import jax

    from narwhal_tpu.ops.reachability import KernelTusk

    floor_s = measure_fetch_floor()
    rtt_floor_ms = round(floor_s * 1e3, 3)
    print(json.dumps({"device_roundtrip_floor_ms": rtt_floor_ms}))

    results = []
    for n in args.sizes:
        committee = make_committee(n)
        burst = bench_commit_burst(
            KernelTusk, committee, args.burst_rounds, args.burst_iters,
            floor_s,
        )
        ml_burst = bench_commit_burst_multileader(
            committee, args.burst_rounds, args.burst_iters
        )
        pair = bench_pair(
            KernelTusk, committee, args.span, args.iters, args.build_reps
        )
        py, ke = pair["python"], pair["kernel"]
        assert py["chain"] == ke["chain"], (
            f"commit chains diverge at N={n}: "
            f"python {len(py['chain'])} vs kernel {len(ke['chain'])}"
        )
        ke_commit_floorsub = max(ke["commit_s"] - floor_s, 0.0)
        # When the separately-measured floor swallows the whole commit time
        # the floor-subtracted estimate is degenerate (dividing by ~0 would
        # print an absurd speedup and could spuriously pass the acceptance
        # gate); report null and let acceptance fall back to the raw ratio.
        fs_speedup = (
            round(py["commit_s"] / ke_commit_floorsub, 2)
            if ke_commit_floorsub > 0.1 * ke["commit_s"]
            else None
        )
        row = {
            "committee": n,
            "span_rounds": args.span,
            "leaders_in_chain": len(py["chain"]),
            # arrival path (insert loop over span rounds, min of build-reps)
            "python_insert_ms": round(py["insert_s"] * 1e3, 2),
            "kernel_insert_ms": round(ke["insert_s"] * 1e3, 2),
            # commit path (per opportunity, steady state)
            "python_commit_ms": round(py["commit_s"] * 1e3, 3),
            "kernel_commit_ms": round(ke["commit_s"] * 1e3, 3),
            "kernel_commit_ms_floor_subtracted": round(
                ke_commit_floorsub * 1e3, 3
            ),
            # catch-up worst case: first call flushes the whole span
            "kernel_full_span_flush_ms": round(ke["first_call_s"] * 1e3, 2),
            "commit_speedup_raw": round(py["commit_s"] / ke["commit_s"], 2),
            "commit_speedup_floor_subtracted": fs_speedup,
            "insert_overhead_pct": round(
                (ke["insert_s"] / py["insert_s"] - 1) * 100, 1
            ),
            # Multi-leader commit burst (PR 4): r06 dict walk vs the
            # indexed walk (vs the kernel's catch-up flush) on one
            # trigger committing the whole chain.
            "commit_burst": burst,
            # Multileader burst (ISSUE r19): the live multileader rule vs
            # ITS frozen oracle — the sequences differ from classic by
            # design, so this arm pair is judged internally.
            "commit_burst_multileader": ml_burst,
        }
        results.append(row)
        print(json.dumps(row))

    # Gate on the floor-subtracted ratio where it's meaningful, else the
    # raw one (fetch-bound regime: the raw number IS the honest cost).
    def gate_speedup(r):
        fs = r["commit_speedup_floor_subtracted"]
        return fs if fs is not None else r["commit_speedup_raw"]

    acceptance = {
        "commit_speedup_floor_subtracted_gt1_at_n_ge_20": all(
            gate_speedup(r) > 1 for r in results if r["committee"] >= 20
        ),
        "kernel_insert_not_worse_than_python": all(
            r["kernel_insert_ms"] <= r["python_insert_ms"]
            for r in results
        ),
        # PR 4 gate: the indexed walk at least doubles the dict walk on
        # the multi-leader burst at committee sizes ≥ 20.
        "indexed_burst_speedup_ge2_at_n_ge_20": all(
            r["commit_burst"]["indexed_speedup_vs_dict"] >= 2
            for r in results
            if r["committee"] >= 20
        ),
        # ISSUE r19 gate: the live multileader rule at least doubles ITS
        # frozen oracle on the slot-chain burst at committee sizes ≥ 20
        # (byte-identity to that oracle is asserted inside the arm).
        "multileader_burst_speedup_ge2_at_n_ge_20": all(
            r["commit_burst_multileader"]["ml_indexed_speedup_vs_dict"] >= 2
            for r in results
            if r["committee"] >= 20
        ),
    }
    print(json.dumps({"acceptance": acceptance}))

    if args.artifact:
        os.makedirs(os.path.dirname(args.artifact) or ".", exist_ok=True)
        with open(args.artifact, "w") as f:
            json.dump(
                {
                    "device": str(jax.devices()[0]),
                    "device_roundtrip_floor_ms": rtt_floor_ms,
                    "note": (
                        "kernel_commit_ms is the steady-state cost of one "
                        "commit opportunity: flush two staged rounds "
                        "(donated scatter) + one chain scan + the W-bool "
                        "committed-bitmap fetch — the only device round "
                        "trip on the path.  The floor-subtracted column "
                        "removes that fetch floor (dominant on a tunneled "
                        "chip, ~0.1 ms host-local) for the host-local-chip "
                        "estimate.  kernel_full_span_flush_ms is the "
                        "catch-up worst case (whole span staged at once)."
                    ),
                    "rows": results,
                    "acceptance": acceptance,
                },
                f,
                indent=2,
            )


if __name__ == "__main__":
    main()
