#!/usr/bin/env python3
"""KernelTusk microbenchmark: device leader-chain scan vs golden Python walk.

The reference's commit rule does one `linked()` BFS per earlier leader per
commit attempt (consensus/src/lib.rs:224-259); KernelTusk collapses the
whole chain into one jitted scan (narwhal_tpu/ops/reachability.py).  This
measures `order_leaders` wall time for both implementations over identical
DAG state at committee sizes N ∈ {4, 20, 50} and a gc_depth-50 window —
the "large-DAG scaling" duty from SURVEY.md §5.

Methodology: build `span` rounds of a full DAG (every authority, full
parent links — the densest, worst case), call order_leaders on the newest
anchor leader T times, report the median per-call time.  The kernel path
is prewarmed first (one static shape; persistent compile cache applies).

    python bench_consensus.py --sizes 4 20 50 --span 48 --iters 5 \
        --artifact artifacts/consensus_bench.json
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

from narwhal_tpu.config import (  # noqa: E402
    Authority,
    Committee,
    PrimaryAddresses,
    WorkerAddresses,
)
from narwhal_tpu.crypto import KeyPair  # noqa: E402
from narwhal_tpu.consensus.tusk import Tusk  # noqa: E402
from narwhal_tpu.primary.messages import Certificate, Header, genesis  # noqa: E402


def make_committee(n: int) -> Committee:
    auths = {}
    for i in range(n):
        kp = KeyPair.generate(rng_seed=i.to_bytes(32, "little"))
        auths[kp.name] = Authority(
            stake=1,
            primary=PrimaryAddresses("127.0.0.1:0", "127.0.0.1:0"),
            workers={0: WorkerAddresses("127.0.0.1:0", "127.0.0.1:0", "127.0.0.1:0")},
        )
    return Committee(auths)


def mock_certificate(origin, round_, parents) -> Certificate:
    header = Header(
        author=origin, round=round_, payload={}, parents=set(parents)
    )
    return Certificate(header=header, votes=[])


def build_state(tusk: Tusk, committee: Committee, span: int):
    """Fill the DAG with `span` full rounds WITHOUT committing (inserted
    via insert_certificate so KernelTusk maintains its dense window, but
    the commit rule is bypassed), then return the anchor leader
    certificate for order_leaders.  Returns (anchor, insert_seconds)."""
    names = sorted(committee.authorities.keys())
    parents = {c.digest() for c in genesis(committee)}
    t0 = time.perf_counter()
    for r in range(1, span + 1):
        nxt = set()
        for name in names:
            cert = mock_certificate(name, r, parents)
            tusk.insert_certificate(cert)
            nxt.add(cert.digest())
        parents = nxt
    insert_s = time.perf_counter() - t0
    # Anchor: leader of the last even round.
    anchor_round = span if span % 2 == 0 else span - 1
    leader_name = tusk._sorted_keys[0 if tusk.fixed_coin else anchor_round % len(names)]
    anchor = tusk.state.dag[anchor_round][leader_name][1]
    return anchor, insert_s


def bench_one(cls, committee, span, iters, prewarm=False):
    tusk = cls(committee, gc_depth=50, fixed_coin=True)
    if prewarm and hasattr(tusk, "prewarm"):
        tusk.prewarm()
    anchor, insert_s = build_state(tusk, committee, span)
    times = []
    chain_len = None
    for _ in range(iters):
        t0 = time.perf_counter()
        chain = tusk.order_leaders(anchor)
        times.append(time.perf_counter() - t0)
        chain_len = len(chain)
    # Insert time is reported ALONGSIDE the order_leaders comparison (as
    # python_insert_ms / kernel_insert_ms columns), not folded into the
    # speedup: the kernel's incremental window maintenance happens on the
    # certificate-arrival path, the scan on the commit path.
    return statistics.median(times), chain_len, insert_s


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", type=int, nargs="+", default=[4, 20, 50])
    ap.add_argument("--span", type=int, default=48)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--artifact", type=str, default=None)
    args = ap.parse_args()

    from narwhal_tpu.ops.reachability import KernelTusk

    # Fixed device round-trip floor on this host: median wall time of a
    # trivial jitted compute + result fetch.  On a tunneled/remote chip this
    # floor (not the scan) dominates kernel_ms; on a host-local chip it is
    # ~0.1 ms and the scan wins at large committees.
    import jax
    import jax.numpy as jnp
    import numpy as _np

    _f = jax.jit(lambda x: x + 1)
    _x = jnp.zeros(8, jnp.int32)
    _np.asarray(_f(_x))
    _ts = []
    for _ in range(7):
        _t0 = time.perf_counter()
        _np.asarray(_f(_x))
        _ts.append(time.perf_counter() - _t0)
    rtt_floor_ms = round(sorted(_ts)[3] * 1e3, 2)
    print(json.dumps({"device_roundtrip_floor_ms": rtt_floor_ms}))

    results = []
    for n in args.sizes:
        committee = make_committee(n)
        py_t, py_chain, py_ins = bench_one(Tusk, committee, args.span, args.iters)
        k_t, k_chain, k_ins = bench_one(
            KernelTusk, committee, args.span, args.iters, prewarm=True
        )
        assert py_chain == k_chain, (py_chain, k_chain)
        row = {
            "committee": n,
            "span_rounds": args.span,
            "leaders_in_chain": py_chain,
            "python_ms": round(py_t * 1e3, 2),
            "kernel_ms": round(k_t * 1e3, 2),
            "speedup": round(py_t / k_t, 2),
            "python_insert_ms": round(py_ins * 1e3, 2),
            "kernel_insert_ms": round(k_ins * 1e3, 2),
        }
        results.append(row)
        print(json.dumps(row))

    if args.artifact:
        os.makedirs(os.path.dirname(args.artifact) or ".", exist_ok=True)
        with open(args.artifact, "w") as f:
            json.dump(
                {
                    "device": str(jax.devices()[0]),
                    "device_roundtrip_floor_ms": rtt_floor_ms,
                    "note": (
                        "kernel_ms includes one device round trip per "
                        "order_leaders call; when the floor above dominates "
                        "kernel_ms, the scan itself is round-trip-bound "
                        "(tunneled chip), not compute-bound — subtract the "
                        "floor for the host-local-chip estimate"
                    ),
                    "rows": results,
                },
                f,
                indent=2,
            )


if __name__ == "__main__":
    main()
