"""GarbageCollector: track the consensus round and clean up the workers.

Reference primary/src/garbage_collector.rs (72 LoC): consume committed
certificates from consensus, bump the shared consensus round, and broadcast
Cleanup(round) to our own workers.
"""

from __future__ import annotations

import asyncio
import logging

from ..config import Committee
from ..crypto import PublicKey
from ..messages import encode_cleanup
from ..network import SimpleSender
from .core import AtomicRound

log = logging.getLogger("narwhal.primary")


class GarbageCollector:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        consensus_round: AtomicRound,
        rx_consensus: asyncio.Queue,  # committed certificates
    ) -> None:
        self.consensus_round = consensus_round
        self.rx_consensus = rx_consensus
        self.sender = SimpleSender()
        self.worker_addresses = [
            a.primary_to_worker
            for a in committee.authorities[name].workers.values()
        ]

    async def run(self) -> None:
        last_committed_round = 0
        while True:
            certificate = await self.rx_consensus.get()
            round = certificate.round
            if round > last_committed_round:
                last_committed_round = round
                self.consensus_round.value = round
                for address in self.worker_addresses:
                    self.sender.send(
                        address, encode_cleanup(round), msg_type="cleanup"
                    )
