from .messages import Certificate, Header, Vote, genesis
from .primary import Primary

__all__ = ["Certificate", "Header", "Vote", "genesis", "Primary"]
