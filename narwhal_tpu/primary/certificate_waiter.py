"""CertificateWaiter: park certificates until all their parents are stored.

Reference primary/src/certificate_waiter.rs (86 LoC): try_join_all of
notify_read over the parents, then loop the certificate back to the Core.
No network side — the HeaderWaiter does the fetching (the embedded header's
processing triggers it).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, Tuple

from ..crypto import Digest
from ..messages import Round
from ..store import Store
from .core import AtomicRound
from .messages import Certificate
from ..utils.tasks import spawn

log = logging.getLogger("narwhal.primary")


class CertificateWaiter:
    def __init__(
        self,
        store: Store,
        consensus_round: AtomicRound,
        gc_depth: Round,
        rx_synchronizer: asyncio.Queue,  # parked certificates
        tx_core: asyncio.Queue,
    ) -> None:
        self.store = store
        self.consensus_round = consensus_round
        self.gc_depth = gc_depth
        self.rx_synchronizer = rx_synchronizer
        self.tx_core = tx_core
        self.pending: Dict[Digest, Tuple[Round, asyncio.Task]] = {}

    async def run(self) -> None:
        try:
            while True:
                certificate = await self.rx_synchronizer.get()
                digest = certificate.digest()
                if digest not in self.pending:
                    task = spawn(self._wait(certificate))
                    self.pending[digest] = (certificate.round, task)
                self._gc()
        finally:
            for _, task in self.pending.values():
                task.cancel()
            self.pending.clear()

    async def _wait(self, certificate: Certificate) -> None:
        await asyncio.gather(
            *(
                self.store.notify_read(bytes(d))
                for d in certificate.header.parents
            )
        )
        self.pending.pop(certificate.digest(), None)
        await self.tx_core.put(certificate)

    def _gc(self) -> None:
        round = self.consensus_round.value
        if round <= self.gc_depth:
            return
        gc_round = round - self.gc_depth
        for d in [d for d, (r, _) in self.pending.items() if r <= gc_round]:
            _, task = self.pending.pop(d)
            task.cancel()
