"""Primary wiring: receivers, channels, and the eight protocol tasks.

Reference primary/src/primary.rs (275 LoC): builds the channels, spawns
network receivers for primary↔primary (WAN) and worker→primary (LAN)
traffic, and wires Core, GarbageCollector, PayloadReceiver, HeaderWaiter,
CertificateWaiter, Proposer and Helper around the shared store and the
atomic consensus round.
"""

from __future__ import annotations

import asyncio
import logging
from typing import List

from .. import metrics
from ..config import Committee, Parameters
from ..crypto import KeyPair, SchemeMismatch, SignatureService
from ..messages import (
    WORKER_PRIMARY_FRAME_TYPES,
    decode_worker_primary_message,
    frame_classifier,
    set_wire_committee,
)
from ..network import Receiver, Writer
from ..network.clocksync import stamp_ack
from ..store import Store
from ..utils.env import env_int
from ..utils.tasks import spawn
from .certificate_waiter import CertificateWaiter
from .core import AtomicRound, Core
from .garbage_collector import GarbageCollector
from .header_waiter import HeaderWaiter
from .helper import Helper
from .messages import PRIMARY_FRAME_TYPES, decode_primary_message
from .payload_receiver import PayloadReceiver
from .proposer import Proposer
from .synchronizer import Synchronizer

log = logging.getLogger("narwhal.primary")

CHANNEL_CAPACITY = 1_000


class PrimaryReceiverHandler:
    """primary↔primary plane: ACK, then route to Core or Helper
    (reference primary.rs:224-243)."""

    def __init__(self, tx_primaries: asyncio.Queue, tx_helper: asyncio.Queue) -> None:
        self.tx_primaries = tx_primaries
        self.tx_helper = tx_helper

    async def dispatch(self, writer: Writer, message: bytes) -> None:
        try:
            decoded = decode_primary_message(message)
        except SchemeMismatch as e:
            # A certificate from a peer running the OTHER cert-sig
            # scheme: counted into primary.invalid_signatures (its
            # signature material is unreadable here, which is what the
            # invalid_signature health rule should fire on), named
            # loudly — a mixed committee is an operator error, not
            # line noise.
            metrics.counter("primary.invalid_signatures").inc()
            log.warning("Dropping cross-scheme primary message: %s", e)
            return
        except ValueError as e:
            log.warning("Dropping malformed primary message: %s", e)
            return
        await writer.send(stamp_ack())
        if decoded[0] == "certificates_request":
            await self.tx_helper.put((decoded[1], decoded[2]))
        else:
            await self.tx_primaries.put(decoded)


class WorkerReceiverHandler:
    """worker→primary LAN plane: OurBatch → Proposer, OthersBatch →
    PayloadReceiver (reference primary.rs:246-261)."""

    def __init__(self, tx_our_digests: asyncio.Queue, tx_others_digests: asyncio.Queue) -> None:
        self.tx_our_digests = tx_our_digests
        self.tx_others_digests = tx_others_digests

    async def dispatch(self, writer: Writer, message: bytes) -> None:
        try:
            decoded = decode_worker_primary_message(message)
        except ValueError as e:
            log.warning("Dropping malformed worker message: %s", e)
            return
        if decoded.ours:
            await self.tx_our_digests.put((decoded.digest, decoded.worker_id))
        else:
            await self.tx_others_digests.put((decoded.digest, decoded.worker_id))


class Primary:
    def __init__(self) -> None:
        self.tasks: List[asyncio.Task] = []
        self.receivers: List[Receiver] = []
        self.senders: List = []
        self.tx_consensus: asyncio.Queue | None = None
        self.rx_consensus: asyncio.Queue | None = None

    @classmethod
    async def spawn(
        cls,
        keypair: KeyPair,
        committee: Committee,
        parameters: Parameters,
        store: Store,
        tx_consensus: asyncio.Queue,
        rx_consensus: asyncio.Queue,
        benchmark: bool = False,
        fault_plan=None,
    ) -> "Primary":
        """`tx_consensus` carries fresh certificates to the consensus task;
        `rx_consensus` brings committed certificates back for GC.

        ``fault_plan`` (a ``narwhal_tpu.faults.byzantine.ByzantinePlan``)
        swaps the Proposer/Core pair for their Byzantine wrappers — the
        fault-injection suite's adversary wiring; None (the default) is
        the honest node."""
        self = cls()
        name = keypair.name
        loop = asyncio.get_running_loop()
        # Wire v2 key-index space: the committee roster, installed before
        # any codec runs (store replay, receivers, proposer).
        set_wire_committee(committee)
        cap = env_int("NARWHAL_CHANNEL_CAPACITY", CHANNEL_CAPACITY)
        q = lambda ch: metrics.InstrumentedQueue(cap, channel=ch)  # noqa: E731

        tx_primaries = q("primary.primaries")  # network → core
        tx_helper = q("primary.helper")
        rx_our_digests = q("primary.our_digests")  # workers → proposer
        rx_others_digests = q("primary.others_digests")  # workers → payload receiver
        tx_headers_sync = q("primary.headers_sync")  # synchronizer → header waiter
        tx_certs_sync = q("primary.certs_sync")  # synchronizer → certificate waiter
        tx_headers_loopback = q("primary.header_waiter")  # header waiter → core
        tx_certs_loopback = q("primary.cert_waiter")  # certificate waiter → core
        tx_own_headers = q("primary.own_headers")  # proposer → core
        # NOTE: no core → proposer queue anymore — parents are delivered
        # via Proposer.deliver_parents, a synchronous same-loop callback
        # (skips the queue round-trip on the round-cadence critical path).

        # Queue-depth gauges, polled only at snapshot/scrape time.  One
        # literal call per name (no loop) so the metric-name-drift lint
        # rule can see every registered name statically.
        metrics.gauge_fn("primary.queue.primaries", tx_primaries.qsize)
        metrics.gauge_fn("primary.queue.helper", tx_helper.qsize)
        metrics.gauge_fn("primary.queue.our_digests", rx_our_digests.qsize)
        metrics.gauge_fn(
            "primary.queue.others_digests", rx_others_digests.qsize
        )
        metrics.gauge_fn(
            "primary.queue.header_waiter", tx_headers_loopback.qsize
        )
        metrics.gauge_fn("primary.queue.cert_waiter", tx_certs_loopback.qsize)
        metrics.gauge_fn("primary.queue.own_headers", tx_own_headers.qsize)
        metrics.gauge_fn("primary.queue.consensus", tx_consensus.qsize)

        consensus_round = AtomicRound()
        metrics.gauge_fn(
            "primary.consensus_round", lambda: consensus_round.value
        )
        signature_service = SignatureService(keypair)
        synchronizer = Synchronizer(
            name, committee, store, tx_headers_sync, tx_certs_sync
        )

        addrs = committee.primary(name)
        self.receivers.append(
            await Receiver.spawn(
                addrs.primary_to_primary,
                PrimaryReceiverHandler(tx_primaries, tx_helper),
                classify=frame_classifier(PRIMARY_FRAME_TYPES),
            )
        )
        self.receivers.append(
            await Receiver.spawn(
                addrs.worker_to_primary,
                WorkerReceiverHandler(rx_our_digests, rx_others_digests),
                classify=frame_classifier(WORKER_PRIMARY_FRAME_TYPES),
            )
        )

        # The Proposer is built first so the Core can hand it parent
        # quorums directly (deliver_parents) instead of through a queue.
        # A fault plan swaps in the Byzantine wrappers (same wiring, same
        # channels — the adversary acts only at the network boundary).
        proposer_cls, core_cls = Proposer, Core
        extra: tuple = ()
        if fault_plan is not None and fault_plan.primary_behaviors():
            from ..faults.byzantine import ByzantineCore, ByzantineProposer

            proposer_cls, core_cls = ByzantineProposer, ByzantineCore
            extra = (fault_plan,)
        proposer = proposer_cls(
            *extra,
            name,
            committee,
            signature_service,
            parameters.header_size,
            parameters.max_header_delay,
            rx_core=None,  # parents arrive via deliver_parents
            rx_workers=rx_our_digests,
            tx_core=tx_own_headers,
            benchmark=benchmark,
            min_header_delay_ms=parameters.min_header_delay,
            header_linger_ms=parameters.header_linger,
        )
        core = core_cls(
            *extra,
            name,
            committee,
            store,
            synchronizer,
            signature_service,
            consensus_round,
            parameters.gc_depth,
            rx_primaries=tx_primaries,
            rx_header_waiter=tx_headers_loopback,
            rx_certificate_waiter=tx_certs_loopback,
            rx_proposer=tx_own_headers,
            tx_consensus=tx_consensus,
            parents_cb=proposer.deliver_parents,
            # Late-parent forwarding only matters while a linger window
            # can be open; leave it unwired otherwise so the post-quorum
            # certificate path stays zero-cost.
            late_parents_cb=(
                proposer.deliver_late_parent
                if parameters.header_linger > 0
                else None
            ),
        )
        garbage_collector = GarbageCollector(
            name, committee, consensus_round, rx_consensus
        )
        payload_receiver = PayloadReceiver(store, rx_others_digests)
        header_waiter = HeaderWaiter(
            name,
            committee,
            store,
            consensus_round,
            parameters.gc_depth,
            parameters.sync_retry_delay,
            parameters.sync_retry_nodes,
            rx_synchronizer=tx_headers_sync,
            tx_core=tx_headers_loopback,
        )
        certificate_waiter = CertificateWaiter(
            store,
            consensus_round,
            parameters.gc_depth,
            rx_synchronizer=tx_certs_sync,
            tx_core=tx_certs_loopback,
        )
        helper = Helper(committee, store, tx_helper)

        for runner in (
            core,
            garbage_collector,
            payload_receiver,
            header_waiter,
            certificate_waiter,
            proposer,
            helper,
        ):
            self.tasks.append(
                spawn(runner.run(), name=type(runner).__name__.lower())
            )
        self.senders = [
            core.network,
            garbage_collector.sender,
            header_waiter.sender,
            helper.sender,
        ]

        log.info(
            "Primary %r successfully booted on %s",
            name,
            addrs.primary_to_primary.rsplit(":", 1)[0],
        )
        return self

    async def shutdown(self) -> None:
        for task in self.tasks:
            task.cancel()
        for sender in self.senders:
            sender.close()
        for receiver in self.receivers:
            await receiver.shutdown()
        await asyncio.gather(*self.tasks, return_exceptions=True)
