"""PayloadReceiver: record which batches our workers hold for other authors.

Reference primary/src/payload_receiver.rs (29 LoC): write a
(digest ‖ worker_id) → ∅ marker so header validation can check payload
availability (see synchronizer.payload_key for the attack this prevents).
"""

from __future__ import annotations

import asyncio

from ..store import Store
from .synchronizer import payload_key


class PayloadReceiver:
    def __init__(self, store: Store, rx_workers: asyncio.Queue) -> None:
        self.store = store
        self.rx_workers = rx_workers

    async def run(self) -> None:
        while True:
            digest, worker_id = await self.rx_workers.get()
            self.store.write(payload_key(digest, worker_id), b"")
