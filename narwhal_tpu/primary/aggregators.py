"""Stake-weighted aggregation of votes and certificates.

Reference primary/src/aggregators.rs (85 LoC).  Both emit exactly once at
2f+1 stake (weight reset to 0 on quorum, aggregators.rs:38,74), and both
reject authority reuse.
"""

from __future__ import annotations

from typing import List, Optional, Set

from ..config import Committee
from ..crypto import Digest, PublicKey, aggregate_votes
from ..crypto.aggregate import scheme as cert_sig_scheme
from .errors import AuthorityReuse
from .messages import Certificate, Header, Vote


class VotesAggregator:
    """Aggregates votes for our current header into a certificate."""

    def __init__(self) -> None:
        self.weight = 0
        self.votes = []
        self.used: Set[PublicKey] = set()

    def append(
        self, vote: Vote, committee: Committee, header: Header
    ) -> Optional[Certificate]:
        if vote.author in self.used:
            raise AuthorityReuse(repr(vote.author))
        self.used.add(vote.author)
        self.votes.append((vote.author, vote.signature))
        self.weight += committee.stake(vote.author)
        if self.weight >= committee.quorum_threshold():
            self.weight = 0  # ensures quorum is only reached once
            if cert_sig_scheme() == "halfagg":
                # Fold the quorum into ONE aggregate at assembly time
                # (ROADMAP item 2): every vote signed this certificate's
                # digest, so the digest the aggregate binds is known
                # before the votes are attached.
                certificate = Certificate(header=header)
                signers, agg = aggregate_votes(
                    bytes(certificate.digest()), self.votes
                )
                certificate.agg_signers = signers
                certificate.agg = agg
                return certificate
            return Certificate(header=header, votes=list(self.votes))
        return None


class CertificatesAggregator:
    """Aggregates certificates per round; emits the parent list that lets the
    proposer advance — the round-advance trigger."""

    def __init__(self) -> None:
        self.weight = 0
        self.certificates: List[Digest] = []
        self.used: Set[PublicKey] = set()

    def append(
        self, certificate: Certificate, committee: Committee
    ) -> Optional[List[Digest]]:
        origin = certificate.origin
        if origin in self.used:
            return None
        self.used.add(origin)
        self.certificates.append(certificate.digest())
        self.weight += committee.stake(origin)
        if self.weight >= committee.quorum_threshold():
            self.weight = 0  # ensures quorum is only reached once
            out, self.certificates = self.certificates, []
            return out
        return None
