"""Core: the DAG state machine.

Reference primary/src/core.rs (412 LoC): one select loop over peer messages,
waiter loopbacks and own proposals.  process_header (dedupe → parents present
+ quorum of round-1 → payload present → persist → vote once per (round,
author)); process_vote (aggregate → broadcast certificate at quorum);
process_certificate (ensure header processed, ancestors delivered, persist,
feed CertificatesAggregator → advance round, forward to consensus).
Sanitizers verify signatures and round bounds; per-round maps are GC'd from
the shared consensus round.

Round-cadence fast path (ISSUE r10).  The r09 attribution showed 97-98% of
commit latency is protocol cadence (round period × commit depth), so the
header→vote→cert round-trip is pipelined here:

- **Vote fast path**: a valid header's vote decision (the once-per-(round,
  author) rule) and signature happen immediately, but the header's store
  record is buffered (``Store.write_deferred``) and the vote send is
  staged; one flush per drained burst appends every buffered record in a
  single writev and THEN releases the staged votes.  Persist-before-vote
  is preserved — no vote leaves the node before its header is logged —
  but the log syscall is paid once per burst, not once per header.
  ``NARWHAL_VOTE_FAST_PATH=0`` (or ``fast_path=False``) restores the
  per-header persist+send for A/B measurement (bench_cadence.py).
- **Direct parent delivery**: when the certificate quorum for a round
  completes, the parents are handed to the Proposer via a synchronous
  callback (``parents_cb``) instead of a queue put → event-loop wakeup →
  queue get round-trip.
- **Per-burst GC**: the per-round-map GC sweep runs once per drained
  burst, not once per message (mirrors the r09 consensus gc-per-burst).
- **Cached address lists**: the committee is static per run, so broadcast
  address lists and the per-author primary address map are computed once
  at init instead of per header/vote/certificate.

Verify-batch window (ISSUE r19, ROADMAP item 1).  With
``NARWHAL_VERIFY_BATCH_WINDOW_MS > 0`` the peer-message arm of the main
loop stops verifying inline: drained bursts are forwarded to a
pipelined ``_verify_loop`` task that coalesces cross-message-type
signature claims (headers, votes, certificates) from several drains —
up to ``NARWHAL_VERIFY_BATCH_MAX`` messages or the window, whichever
closes first — into ONE backend dispatch, then replays in arrival
order.  The device round trip runs off the event loop (the backend's
dispatch thread), and run() keeps servicing the proposer/waiter sources
and draining the network throughout, so consecutive rounds pipeline
behind the verify instead of stalling — and the arrivals during a
dispatch deepen the next batch.  The window is the knob that turns the
r12 mean burst of 3.6 claims into device-sized batches for the
``jax``/``tpu`` backend (crypto/backend.py).
"""

from __future__ import annotations

import asyncio
import logging
import hashlib
from typing import Callable, Dict, List, Optional, Set, Tuple

from .. import metrics
from ..config import Committee
from ..crypto import Digest, PublicKey, SignatureService
from ..messages import Round
from ..network import ReliableSender
from ..store import Store
from ..utils.clock import loop_now
from ..utils.env import env_flag, env_float, env_int
from ..utils.serde import Writer
from .aggregators import CertificatesAggregator, VotesAggregator
from .errors import (
    DagError,
    HeaderRequiresQuorum,
    InvalidSignature,
    MalformedHeader,
    TooOld,
    UnexpectedVote,
)
from .messages import (
    Certificate,
    Header,
    Vote,
    encode_primary_message,
)
from .synchronizer import Synchronizer

log = logging.getLogger("narwhal.primary")


class AtomicRound:
    """Shared consensus-round cell (the reference's AtomicU64 with Relaxed
    ordering, primary.rs:89 — plain attribute suffices on one event loop)."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value: Round = 0


class Core:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        store: Store,
        synchronizer: Synchronizer,
        signature_service: SignatureService,
        consensus_round: AtomicRound,
        gc_depth: Round,
        rx_primaries: asyncio.Queue,
        rx_header_waiter: asyncio.Queue,
        rx_certificate_waiter: asyncio.Queue,
        rx_proposer: asyncio.Queue,
        tx_consensus: asyncio.Queue,
        tx_proposer: Optional[asyncio.Queue] = None,
        parents_cb: Optional[Callable[[List[Digest], Round], None]] = None,
        late_parents_cb: Optional[Callable[[Digest, Round], None]] = None,
        fast_path: Optional[bool] = None,
        verify_window_ms: Optional[float] = None,
        verify_batch_max: Optional[int] = None,
    ) -> None:
        self.name = name
        self.committee = committee
        self.store = store
        self.synchronizer = synchronizer
        self.signature_service = signature_service
        self.consensus_round = consensus_round
        self.gc_depth = gc_depth
        self.rx_primaries = rx_primaries
        self.rx_header_waiter = rx_header_waiter
        self.rx_certificate_waiter = rx_certificate_waiter
        self.rx_proposer = rx_proposer
        self.tx_consensus = tx_consensus
        self.tx_proposer = tx_proposer
        # Direct (synchronous, same-event-loop) parent delivery to the
        # Proposer; falls back to the tx_proposer queue when unset.
        # At least one must exist, or every parent quorum would be
        # silently discarded and the proposer never advance past round 1.
        if parents_cb is None and tx_proposer is None:
            raise ValueError(
                "Core needs a parent-quorum sink: pass parents_cb "
                "(Proposer.deliver_parents) or a tx_proposer queue"
            )
        self.parents_cb = parents_cb
        # Post-quorum parent forwarding (the proposer's header_linger
        # window): a FRESH certificate of a round whose 2f+1 parent list
        # already went out is offered to the Proposer as a late parent.
        # Only wired when the linger is on — with no window open the
        # callback would be pure per-certificate overhead.
        self.late_parents_cb = late_parents_cb
        # Rounds whose parent quorum has emitted (Dict so the _gc_sweep
        # map loop collects it like the other per-round state).
        self._parents_emitted: Dict[Round, None] = {}
        # Vote fast path (coalesced persist-before-vote); the env knob is
        # the A/B arm selector for bench_cadence.py.
        if fast_path is None:
            fast_path = env_flag("NARWHAL_VOTE_FAST_PATH")
        self.fast_path = fast_path
        # Verify-batch accumulation window (ROADMAP item 1): >0 routes
        # drained peer messages through a pipelined verify task that
        # coalesces claims from MULTIPLE bursts (headers, votes, certs
        # alike) arriving within the window into one backend dispatch —
        # the knob that turns the r12 mean batch of 3.6 into device-
        # sized batches.  0 (default) keeps the pre-r19 inline behavior:
        # one averify per drained burst, replay before the next drain.
        if verify_window_ms is None:
            verify_window_ms = env_float("NARWHAL_VERIFY_BATCH_WINDOW_MS")
        self.verify_window_s = max(0.0, float(verify_window_ms) / 1000.0)
        if verify_batch_max is None:
            verify_batch_max = env_int("NARWHAL_VERIFY_BATCH_MAX")
        self.verify_batch_max = max(1, int(verify_batch_max))
        # Bounded hand-off into the verify pipeline: run() blocks on put
        # when the pipeline is behind, so rx_primaries (and through it
        # the network receiver) keeps its backpressure.
        self._verify_q: Optional[asyncio.Queue] = (
            metrics.InstrumentedQueue(
                max(256, 2 * self.verify_batch_max),
                channel="primary.verify_window",
            )
            if self.verify_window_s > 0
            else None
        )

        self.gc_round: Round = 0
        self.last_voted: Dict[Round, Set[PublicKey]] = {}
        self.processing: Dict[Round, Set[Digest]] = {}
        self.current_header: Header = Header(
            author=name, round=0, payload={}, parents=set()
        )
        self.votes_aggregator = VotesAggregator()
        self.certificates_aggregators: Dict[Round, CertificatesAggregator] = {}
        self.network = ReliableSender()
        self.cancel_handlers: Dict[Round, List[asyncio.Future]] = {}
        # The committee is static per run: compute the broadcast list and
        # the author → primary-address map ONCE instead of per message.
        self.others_addresses: List[str] = [
            a.primary_to_primary
            for _, a in committee.others_primaries(name)
        ]
        self.primary_addresses: Dict[PublicKey, str] = {
            n: a.primary.primary_to_primary
            for n, a in committee.authorities.items()
        }
        # Votes staged by the fast path, released by _flush_pending after
        # the burst's single store flush: (round, author, encoded vote).
        # Only votes for OTHER authors' headers are staged — our own vote
        # never leaves the node, and deferring it past the next
        # process_own_header would mis-aggregate it against the replaced
        # current_header, so it stays inline.
        self._pending_votes: List[Tuple[Round, PublicKey, bytes]] = []
        # Which header id we voted for per (round, author): the witness
        # that turns a second, different header for the same slot into a
        # PROVEN equivocation (the fault-injection detection plane reads
        # the counter; the `equivocation` health rule fires on it).
        self.voted_ids: Dict[Round, Dict[PublicKey, Digest]] = {}
        # Our own header id per round, while the round is within the GC
        # window: the attribution witness for the per-peer vote counters.
        # A received vote only counts as "peer X voted for us" if it names
        # a header we actually proposed — a validly self-signed vote for a
        # fabricated id must not keep a withholding peer's counter warm.
        self.own_header_ids: Dict[Round, Digest] = {}
        # Peers already counted per round: one vote per (round, author)
        # reaches the counter, so re-sending one old genuine vote over and
        # over cannot simulate ongoing participation either.
        self.counted_votes: Dict[Round, Set[PublicKey]] = {}
        # Conflicting header ids already counted as equivocations, per
        # round: retransmissions and sync re-sends re-enter
        # process_header, and each distinct twin must count ONCE — not
        # once per delivery — or the counter misreports attack magnitude.
        self.equivocation_ids: Dict[Round, Set[Tuple[PublicKey, Digest]]] = {}
        # First VERIFIED header id seen per (round, author) — recorded at
        # receipt, before any dependency sync.  Two validly-signed
        # headers for one slot are a proven equivocation the moment both
        # signatures check out; waiting for process_header's vote
        # decision (the original witness) let a paired payload-plane
        # attack mask the proof — the conflicting headers parked in the
        # waiters on exactly the batches the same adversary's worker was
        # withholding, and the fuzzed equivocate+withhold/garbage
        # compositions sailed past the `equivocation` rule at N≥10
        # (sim sweep points 7023/7024/7034/7035).
        self.seen_header_ids: Dict[Round, Dict[PublicKey, Digest]] = {}
        self._m_headers_in = metrics.counter("primary.headers_processed")
        self._m_votes_in = metrics.counter("primary.votes_received")
        self._m_votes_out = metrics.counter("primary.votes_sent")
        self._m_certs_formed = metrics.counter("primary.certificates_formed")
        self._m_certs_in = metrics.counter("primary.certificates_processed")
        self._m_dag_errors = metrics.counter("primary.dag_errors")
        self._m_stale = metrics.counter("primary.stale_messages")
        self._m_late_votes = metrics.counter("primary.late_votes")
        # FIFO cache of verified header/cert digests (see VERIFIED_CACHE).
        # Hits (re-deliveries that skipped crypto) and misses (fresh
        # messages that paid for verification) are both exported: hits ÷
        # (hits + misses) is the duplicate fraction of inbound traffic,
        # and hits × claims-per-message is verification work the cache
        # absorbed — the observability the PR 6 cache shipped without.
        self._verified_recent: Dict[bytes, None] = {}
        self._m_verify_cache_hits = metrics.counter(
            "primary.verify_cache_hits"
        )
        self._m_verify_cache_misses = metrics.counter(
            "primary.verify_cache_misses"
        )
        self._m_vote_flushes = metrics.counter("primary.vote_flushes")
        # Fault-detection plane (read by the NARWHAL_HEALTH rules):
        # proven header equivocations, signature-check rejections, and a
        # per-peer count of votes received from each validator.  The per-peer
        # counters are registered at boot (value 0) so the vote-silence
        # rule has a history series for every peer from the first sample.
        self._m_equivocations = metrics.counter(
            "primary.equivocations_detected"
        )
        self._m_invalid_sigs = metrics.counter("primary.invalid_signatures")
        self._peer_vote_counters: Dict[PublicKey, metrics.Counter] = {
            n: metrics.counter(f"primary.peer_votes.{a}")
            for n, a in self.primary_addresses.items()
            if n != name
        }
        # Quorum-straggler attribution (causal commit tracer): when a
        # vote quorum or a parent quorum completes, the authority whose
        # message CLOSED it is charged by primary address, and the span
        # from that quorum's first arrival to completion lands in a gap
        # histogram.  Both ride the loop clock — wall on a live node,
        # virtual (bit-reproducible) under the sim.  The emit-once
        # aggregator contract (weight reset at quorum, authority-reuse
        # rejection/dedupe) is what makes the charge exactly-once per
        # completion, duplicates and equivocations included.
        self._m_quorum_straggler = {
            n: metrics.counter(f"primary.quorum_straggler.{a}")
            for n, a in self.primary_addresses.items()
        }
        self._m_vote_quorum_gap = metrics.histogram(
            "primary.vote_quorum_gap_ms", metrics.LATENCY_MS_BUCKETS
        )
        self._m_parent_quorum_gap = metrics.histogram(
            "primary.parent_quorum_gap_ms", metrics.LATENCY_MS_BUCKETS
        )
        self._vote_first_ts: Optional[float] = None
        self._parent_first_ts: Dict[Round, float] = {}
        # Crypto-cost ledger, burst side: signature claims entering the
        # batched verify PER MESSAGE KIND.  The backend's per-site
        # instruments see the whole burst as "batch_burst"; these split
        # it back into protocol terms (a header contributes 1 claim, a
        # vote 1, a certificate 2f+2), which is what the bench's
        # protocol-arithmetic cross-check reads.
        self._m_burst_claims = {
            kind: metrics.counter(f"crypto.burst_claims.{kind}")
            for kind in ("header", "vote", "certificate")
        }
        # Wire-goodput ledger: empty vs payload-carrying own headers.
        # "Empty certs per committed byte" (ROADMAP item 3's
        # min_header_delay sub-question) needs the numerator counted at
        # the source: an idle-round header and the votes/certificate it
        # mints are pure control-plane overhead.
        self._m_headers_empty = metrics.counter("primary.own_headers_empty")
        self._m_headers_payload = metrics.counter(
            "primary.own_headers_payload"
        )
        self._mtrace = metrics.trace()
        self._rtrace = metrics.round_trace()

    # --- processing ---------------------------------------------------------

    def _broadcast_own_header(self, header: Header) -> List:
        """Ship our freshly minted header to every peer; returns the
        delivery handlers.  A dedicated seam so the Byzantine wrapper can
        split-cast or re-sign the wire copy without re-implementing
        own-header processing."""
        return self.network.broadcast(
            self.others_addresses, encode_primary_message(header),
            msg_type="header",
        )

    async def process_own_header(self, header: Header) -> None:
        self.current_header = header
        self.own_header_ids[header.round] = header.id
        if header.payload:
            self._m_headers_payload.inc()
        else:
            self._m_headers_empty.inc()
        self.votes_aggregator = VotesAggregator()
        self._vote_first_ts = None  # fresh quorum, fresh first-arrival
        handlers = self._broadcast_own_header(header)
        self._rtrace.mark(str(header.round), "header_broadcast")
        self.cancel_handlers.setdefault(header.round, []).extend(handlers)
        await self.process_header(header)

    async def process_header(self, header: Header) -> None:
        log.debug("Processing %r", header)
        self._m_headers_in.inc()
        self.processing.setdefault(header.round, set()).add(header.id)

        # Ensure we have all parents; otherwise the HeaderWaiter will gather
        # them and loop the header back to us.
        parents = await self.synchronizer.get_parents(header)
        if not parents:
            log.debug("Processing of %r suspended: missing parent(s)", header.id)
            return

        # Parents must form a quorum, all from the previous round.
        stake = 0
        for parent in parents:
            if parent.round + 1 != header.round:
                raise MalformedHeader(repr(header.id))
            stake += self.committee.stake(parent.origin)
        if stake < self.committee.quorum_threshold():
            raise HeaderRequiresQuorum(repr(header.id))

        # Ensure we have the payload; otherwise our workers fetch it and the
        # header comes back through the waiter.
        if await self.synchronizer.missing_payload(header):
            log.debug("Processing of %r suspended: missing payload", header.id)
            return

        # Store the header.  Fast path: the record is buffered (memory and
        # notify_read waiters see it immediately) and the log append is
        # coalesced into the burst's single flush — which happens before
        # any staged vote leaves the node (persist-before-vote).
        w = Writer()
        header.encode(w)
        if self.fast_path:
            self.store.write_deferred(bytes(header.id), w.finish())
        else:
            self.store.write(bytes(header.id), w.finish())

        # Vote at most once per (round, author).  The decision (and the
        # last_voted record) is made HERE, at processing time — staging the
        # send cannot double-vote.
        voted = self.last_voted.setdefault(header.round, set())
        if header.author not in voted:
            voted.add(header.author)
            self.voted_ids.setdefault(header.round, {})[header.author] = (
                header.id
            )
            # lint: allow-interleave(the vote decision and its witnesses (last_voted add, voted_ids record) are complete in the sync block ABOVE this first yield — a second root replaying the same header while Vote.new awaits takes the else-branch and cannot double-vote; the callee chain's later writes only ever ADD other (round, author) entries)
            vote = await Vote.new(header, self.name, self.signature_service)
            self._m_votes_out.inc()
            log.debug("Created %r", vote)
            # lint: allow-interleave(equivocation_ids mutates only in the sync else-branch below (setdefault+add before any yield); a cross-root suspension here can at most interleave ANOTHER author's counting, and each distinct twin still counts exactly once)
            await self._dispatch_vote(vote, header)
        else:
            prev_id = self.voted_ids.get(header.round, {}).get(header.author)
            if prev_id is not None and prev_id != header.id:
                # Two validly-signed headers from one author for one round:
                # a PROVEN equivocation (we hold both signed statements).
                # We already voted for the first — the once-per-slot rule
                # keeps safety — but the protocol silently tolerating it is
                # exactly what the fault suite must not: count it so the
                # `equivocation` health rule names the author.  Each
                # distinct twin counts once, however many times it is
                # re-delivered.
                twin = (header.author, header.id)
                counted = self.equivocation_ids.setdefault(
                    header.round, set()
                )
                if twin not in counted:
                    counted.add(twin)
                    self._m_equivocations.inc()
                    log.warning(
                        "Equivocation by %r at round %d: voted for %r, "
                        "now offered %r",
                        header.author, header.round, prev_id, header.id,
                    )

    async def _dispatch_vote(self, vote: Vote, header: Header) -> None:
        """Send (or locally apply) one freshly created vote.  A dedicated
        seam so the Byzantine wrapper can withhold votes for targeted
        authors without re-implementing header processing."""
        if vote.origin == self.name:
            # lint: allow-interleave(_pending_votes/cancel_handlers are append-only lists consumed by the subset-safe _flush_pending / the monotonic GC sweep — a cross-root append or early flush while this own-vote processing is suspended releases staged votes EARLIER behind their already-buffered store records, never out of persist order)
            await self.process_vote(vote)
        elif self.fast_path:
            self._pending_votes.append(
                (header.round, header.author, encode_primary_message(vote))
            )
        else:
            address = self.primary_addresses[header.author]
            handler = self.network.send(
                address, encode_primary_message(vote), msg_type="vote"
            )
            self.cancel_handlers.setdefault(header.round, []).append(handler)

    def _flush_pending(self) -> None:
        """Release the burst's staged votes: ONE coalesced log flush for
        every header buffered this burst, then the staged sends.  Called
        once per drained burst (the flush alone also covers headers that
        were buffered but produced no vote, e.g. equivocations)."""
        self.store.flush_deferred()
        if not self._pending_votes:
            return
        self._m_vote_flushes.inc()
        staged, self._pending_votes = self._pending_votes, []
        for round, author, body in staged:
            handler = self.network.send(
                self.primary_addresses[author], body, msg_type="vote"
            )
            self.cancel_handlers.setdefault(round, []).append(handler)

    def _note_peer_vote(self, vote: Vote) -> None:
        """Per-peer vote accounting: a validator that stops voting for
        our headers while rounds keep advancing is withholding — the
        `peer_vote_silence` rule reads these rates.  Counted at RECEIPT
        (before the current-header match in sanitize_vote): an
        honest-but-slow peer whose votes consistently land one round
        late — after we propose the next header — is still voting, and
        must not read as silent.  Only signature-backed votes reach
        here (the burst path verifies votes down to one round late;
        farther-late votes skip crypto AND counting), and the vote must
        name the header we actually proposed for its round, at most once
        per (round, peer) — so neither a forged vote, a validly
        self-signed vote for a fabricated header id, nor a replayed old
        genuine vote can keep a withholding peer's counter warm."""
        if (
            vote.author != self.name
            and vote.origin == self.name
            and self.own_header_ids.get(vote.round) == vote.id
        ):
            counted = self.counted_votes.setdefault(vote.round, set())
            if vote.author not in counted:
                counted.add(vote.author)
                peer_votes = self._peer_vote_counters.get(vote.author)
                if peer_votes is not None:
                    peer_votes.inc()

    async def process_vote(self, vote: Vote) -> None:
        log.debug("Processing %r", vote)
        self._m_votes_in.inc()
        self._rtrace.mark(str(vote.round), "first_vote")
        if self._vote_first_ts is None:
            self._vote_first_ts = loop_now()
        certificate = self.votes_aggregator.append(
            vote, self.committee, self.current_header
        )
        if certificate is not None:
            log.debug("Assembled %r", certificate)
            self._m_certs_formed.inc()
            self._rtrace.mark(str(certificate.round), "vote_quorum")
            # This vote CLOSED the quorum: charge its author and record
            # the first-arrival→completion gap (usually our own instant
            # self-vote opens the window, so the gap prices how long the
            # 2f+1-th validator made the certificate wait).
            self._m_vote_quorum_gap.observe(
                1000.0 * (loop_now() - self._vote_first_ts)
            )
            straggler = self._m_quorum_straggler.get(vote.author)
            if straggler is not None:
                straggler.inc()
            # Stage trace: OUR header just got certified — the payload
            # digests it carries cross the header→certificate boundary.
            for digest in certificate.header.payload:
                self._mtrace.mark(bytes(digest).hex(), "cert")
            # Defensive: our certificate must never leave the node before
            # its header's (possibly still buffered) record is logged.
            self.store.flush_deferred()
            handlers = self.network.broadcast(
                self.others_addresses, encode_primary_message(certificate),
                msg_type="certificate",
            )
            self._rtrace.mark(str(certificate.round), "cert_broadcast")
            self.cancel_handlers.setdefault(certificate.round, []).extend(handlers)
            await self.process_certificate(certificate)

    async def process_certificate(self, certificate: Certificate) -> None:
        log.debug("Processing %r", certificate)
        self._m_certs_in.inc()

        # Process the embedded header if we haven't (certified ⇒ its data is
        # retrievable, so processing may proceed regardless).
        if certificate.header.id not in self.processing.get(
            certificate.header.round, ()
        ):
            # lint: allow-interleave(the verify pipeline adds a second root (run + _verify_loop) that can replay this certificate concurrently from the waiter loopback — safely: CertificatesAggregator.append dedupes by origin (a double replay appends nothing), VotesAggregator raises AuthorityReuse into the DagError handler, `processing`/`last_voted`/`voted_ids` mutate in sync blocks before any yield (take-before-yield), and the store writes are idempotent by key)
            await self.process_header(certificate.header)

        # All ancestors must be delivered before consensus sees this.
        if not await self.synchronizer.deliver_certificate(certificate):
            log.debug("Processing of %r suspended: missing ancestors", certificate)
            return

        # Store the certificate.  Fast path: deferred like the headers —
        # nothing leaves the node ordered against this record before the
        # burst flush (our OWN cert broadcast happens in process_vote,
        # before this write, in both arms), and an immediate write here
        # would drain the deferred buffer per certificate, degenerating
        # the one-flush-per-burst coalescing under mixed bursts.  Deferred
        # records keep call order, so the header-then-cert log order the
        # reference guarantees is preserved inside the buffer too.
        if self.fast_path:
            self.store.write_deferred(
                bytes(certificate.digest()), certificate.serialize()
            )
        else:
            self.store.write(
                bytes(certificate.digest()), certificate.serialize()
            )

        # Enough certificates to advance the DAG round?
        aggregator = self.certificates_aggregators.setdefault(
            certificate.round, CertificatesAggregator()
        )
        if (
            certificate.origin not in aggregator.used
            and certificate.round not in self._parent_first_ts
        ):
            # First FRESH certificate of this round's parent quorum
            # (origin-dedupe means a re-delivery never opens the window).
            self._parent_first_ts[certificate.round] = loop_now()
        fresh = certificate.origin not in aggregator.used
        parents = aggregator.append(certificate, self.committee)
        if parents is not None:
            self._parents_emitted[certificate.round] = None
            self._rtrace.mark(str(certificate.round), "parent_quorum")
            first_ts = self._parent_first_ts.get(certificate.round)
            if first_ts is not None:
                self._m_parent_quorum_gap.observe(
                    1000.0 * (loop_now() - first_ts)
                )
            # This certificate CLOSED the round's parent quorum.
            straggler = self._m_quorum_straggler.get(certificate.origin)
            if straggler is not None:
                straggler.inc()
            if self.parents_cb is not None:
                # Synchronous hand-off to the Proposer: the round advances
                # at quorum time, not a queue round-trip later.
                self.parents_cb(parents, certificate.round)
            elif self.tx_proposer is not None:
                await self.tx_proposer.put((parents, certificate.round))
        elif (
            fresh
            and self.late_parents_cb is not None
            and certificate.round in self._parents_emitted
        ):
            # Quorum already emitted for this round: a fresh straggler
            # can still be cited if the proposer's linger window is open.
            self.late_parents_cb(certificate.digest(), certificate.round)

        await self.tx_consensus.put(certificate)

    # --- sanitization -------------------------------------------------------
    #
    # State checks run at processing time, in arrival order, exactly like
    # the reference's sanitize_* (core.rs:306-346); the CRYPTO part of
    # sanitization is hoisted out: every drained message's signature claims
    # are verified in ONE backend batch before the replay (SURVEY.md §7
    # "accumulate → batch-verify → replay"), so the device sees one large
    # dispatch instead of per-message calls.  `sig_ok=None` means "not
    # pre-verified" (waiter loopbacks, own proposals) and keeps the
    # reference's inline verification.

    def sanitize_header(self, header: Header, sig_ok=None) -> None:
        if header.round < self.gc_round:
            raise TooOld(f"header {header.id!r} round {header.round}")
        if sig_ok is None:
            header.verify(self.committee)
        else:
            header.verify_structure(self.committee)
            if not sig_ok:
                raise InvalidSignature(f"header {header.id!r}")

    def sanitize_vote(self, vote: Vote, sig_ok=None) -> None:
        if vote.round < self.current_header.round:
            raise TooOld(f"vote {vote.digest()!r} round {vote.round}")
        if not (
            vote.id == self.current_header.id
            and vote.origin == self.current_header.author
            and vote.round == self.current_header.round
        ):
            raise UnexpectedVote(repr(vote.id))
        if sig_ok is None:
            vote.verify(self.committee)
        else:
            vote.verify_structure(self.committee)
            if not sig_ok:
                raise InvalidSignature(f"vote {vote.digest()!r}")

    def sanitize_certificate(self, certificate: Certificate, sig_ok=None) -> None:
        if certificate.round < self.gc_round:
            raise TooOld(f"certificate {certificate.digest()!r}")
        if sig_ok is None:
            certificate.verify(self.committee)
        else:
            certificate.verify_structure(self.committee)
            if not sig_ok:
                raise InvalidSignature(
                    f"certificate {certificate.digest()!r}"
                )

    # --- main loop ----------------------------------------------------------

    def _note_header_seen(self, header) -> None:
        """Receipt-time equivocation witness: called with a header whose
        author signature has just been verified (directly, or as part of
        its certificate).  Recording the first id per (round, author) —
        and counting any different verified id against it — needs no
        payload/parent sync, so a Byzantine worker plane starving the
        waiters cannot delay the proof past the scenario window.  Shares
        ``equivocation_ids`` with the vote-time witness, so however many
        paths observe one twin it counts exactly once."""
        seen = self.seen_header_ids.setdefault(header.round, {})
        prev = seen.setdefault(header.author, header.id)
        if prev == header.id:
            return
        twin = (header.author, header.id)
        counted = self.equivocation_ids.setdefault(header.round, set())
        if twin not in counted:
            counted.add(twin)
            self._m_equivocations.inc()
            log.warning(
                "Equivocation by %r at round %d: first saw %r, now "
                "offered %r (both validly signed)",
                header.author, header.round, prev, header.id,
            )

    async def _handle(self, source: str, item, sig_ok=None) -> None:
        try:
            if source == "primaries":
                kind = item[0]
                if kind == "header":
                    self.sanitize_header(item[1], sig_ok)
                    self._note_header_seen(item[1])
                    # lint: allow-interleave(window mode runs _handle from two roots — run() for waiter/proposer sources, _verify_loop for peer messages — over the per-round maps and aggregators: every decision+record pair (vote-once via last_voted/voted_ids, equivocation counting, aggregator append) happens in one sync block BEFORE any yield, the aggregators dedupe by authority, and sanitize_* re-checks round state at replay time, so a cross-root suspension can reorder processing but never tear an invariant)
                    await self.process_header(item[1])
                elif kind == "vote":
                    if sig_ok is not False:  # exclude known-forged votes
                        # lint: allow-interleave(same two-root discipline as above: _note_peer_vote completes its read-check-count sync before process_vote's first yield, and own_header_ids is only ever written by process_own_header in a sync prefix — a concurrent own-header replacement changes FUTURE counting, never the completed one)
                        self._note_peer_vote(item[1])
                    self.sanitize_vote(item[1], sig_ok)
                    await self.process_vote(item[1])
                elif kind == "certificate":
                    self.sanitize_certificate(item[1], sig_ok)
                    # The embedded header's signature is one of the
                    # certificate's verified claims — a twin-voter whose
                    # directly-received twin is still parked on payload
                    # sync proves the equivocation HERE, when the real
                    # header's certificate arrives.
                    self._note_header_seen(item[1].header)
                    await self.process_certificate(item[1])
                else:
                    log.warning("Unexpected core message %r", kind)
            elif source == "header_waiter":
                await self.process_header(item)
            elif source == "certificate_waiter":
                await self.process_certificate(item)
            elif source == "proposer":
                await self.process_own_header(item)
        except TooOld as e:
            if (
                source == "primaries"
                and item[0] == "vote"
                and item[1].round >= self.gc_round
            ):
                # A within-GC-window vote for a header we already
                # replaced is LATE, not a replay: routine on a busy
                # committee (the peer's vote raced our next proposal).
                # Keeping it out of stale_messages is what lets the
                # stale_replay rule fire on true replay floods without
                # false-positiving a clean run.  Votes from BELOW the GC
                # horizon are replay material like headers/certificates
                # — they stay in stale_messages so a replayed ancient
                # vote flood still trips the rule.
                self._m_late_votes.inc()
            else:
                self._m_stale.inc()
            log.debug("%s", e)
        except InvalidSignature as e:
            # Counted separately from generic DAG errors: a forged or
            # rogue-key signature never occurs in a healthy committee, so
            # the `invalid_signature` health rule can fire on count > 0.
            self._m_invalid_sigs.inc()
            self._m_dag_errors.inc()
            log.warning("%s", e)
        except DagError as e:
            self._m_dag_errors.inc()
            log.warning("%s", e)

    def _gc_sweep(self) -> None:
        """GC internal per-round state from the shared consensus round.
        Hoisted out of the per-message path: one sweep per drained burst
        (the sweep iterates every per-round map — per-message it was
        O(burst × rounds), pure event-loop stall)."""
        round = self.consensus_round.value
        if round > self.gc_depth:
            gc_round = round - self.gc_depth
            if gc_round <= self.gc_round:
                return  # nothing new to collect
            for m in (
                self.last_voted,
                self.voted_ids,
                self.seen_header_ids,
                self.own_header_ids,
                self.counted_votes,
                self.equivocation_ids,
                self.processing,
                self.certificates_aggregators,
                self._parent_first_ts,
                self._parents_emitted,
            ):
                for k in [k for k in m if k < gc_round]:
                    del m[k]
            for k in [k for k in self.cancel_handlers if k < gc_round]:
                for fut in self.cancel_handlers[k]:
                    fut.cancel()
                del self.cancel_handlers[k]
            self.gc_round = gc_round

    # Max messages drained per wakeup: bounds the batch the device verifies
    # and the latency added ahead of the first message's processing.
    DRAIN_LIMIT = 128
    # Recently-verified header/certificate digests whose re-deliveries
    # skip crypto.  Catch-up is where this matters: a node resyncing a
    # gap receives the same certificates several times over (sync-retry
    # responses race ReliableSender retransmissions), and at pure-Python
    # verify speeds paying full crypto per duplicate is what let the
    # re-request flood outrun verification in the partition-heal fault
    # scenario (100% CPU verifying duplicates, zero commits, 60+ s).
    VERIFIED_CACHE = 8192

    async def _handle_primaries_burst(self, items: List) -> None:
        """Batch-verify the signature claims of a drained burst in one
        backend call, then replay the messages in arrival order."""
        from ..crypto import backend as crypto_backend

        spans = []
        msgs: List[bytes] = []
        keys: List[PublicKey] = []
        sigs: List = []
        for item in items:
            kind = item[0]
            # Pre-filter obviously stale items so they never cost crypto:
            # the replay's sanitize_* raises TooOld on the same (monotone)
            # round checks before ever looking at sig_ok, so skipping the
            # claims here cannot change observable semantics — it only
            # removes a DoS amplification (paying 2f+1 verifications for a
            # certificate the reference rejects pre-crypto).
            # Votes: only FAR-late votes (2+ rounds behind) skip crypto.
            # A vote at current_header.round - 1 is the routine race — the
            # peer voted for the header we just replaced — and it IS
            # verified, so the receipt-time per-peer counter only ever
            # counts signature-backed votes (a forged late vote naming a
            # withholding accomplice cannot keep its counter warm and
            # suppress peer_vote_silence).  The verify cost is bounded by
            # the same argument as current-round votes: one signature per
            # message, no amplification.
            # lint: allow-interleave(current_header/gc_round may advance in the other root while this burst later awaits the backend — safely: both are monotone, so a pre-filter decision taken against an older value is only ever MORE permissive than replay-time sanitize_*, which re-checks the live state and raises TooOld itself; a filter that wrongly marks an item stale cannot happen because rounds never move backward)
            stale = (
                kind in ("header", "certificate")
                and item[1].round < self.gc_round
            ) or (
                kind == "vote"
                # lint: allow-interleave(same monotone-round argument as the pragma above: a stale verdict taken against an older current_header stays valid because rounds never move backward, and replay-time sanitize_vote re-checks the live header)
                and item[1].round + 1 < self.current_header.round
            )
            # Re-delivery of an already-verified header/certificate skips
            # crypto via the cache.  The cache key covers the SIGNATURE
            # bytes, not just the content digest: a re-sent copy whose
            # signatures were tampered (same header id / cert digest,
            # corrupted sig) must MISS the cache and pay full verification
            # — were the key digest-only, the tampered copy would ride
            # sig_ok=True into process_*, and its store.write would
            # replace the genuine record with bytes every syncing peer
            # rejects (a permanent sync hole).  Genuine retransmissions
            # are byte-identical, so they still hit.
            dedup_key = None
            if not stale and kind == "header":
                h = hashlib.sha256(b"h")
                h.update(bytes(item[1].id))
                h.update(bytes(item[1].signature))
                dedup_key = h.digest()
            elif not stale and kind == "certificate":
                h = hashlib.sha256(b"c")
                h.update(bytes(item[1].digest()))
                h.update(bytes(item[1].header.signature))
                for vn, vs in item[1].votes:
                    h.update(bytes(vn))
                    h.update(bytes(vs))
                # halfagg: the signer list and aggregate blob are the
                # signature material — same tamper argument as votes (a
                # re-sent copy with a corrupted aggregate must MISS).
                if item[1].agg is not None:
                    for vn in item[1].agg_signers:
                        h.update(bytes(vn))
                    h.update(bytes(item[1].agg))
                dedup_key = h.digest()
            # lint: allow-interleave(_handle_primaries_burst is single-flight by mode exclusivity: with the window off _verify_loop is never spawned and only run() calls it; with the window on run() forwards peer messages instead of handling them, so only _verify_loop calls it — the cache read→await→insert window is therefore never concurrent with another burst's insert)
            seen = dedup_key is not None and dedup_key in self._verified_recent
            if seen:
                self._m_verify_cache_hits.inc()
            elif dedup_key is not None:
                self._m_verify_cache_misses.inc()
            claims = (
                item[1].signature_claims()
                if not stale and not seen
                and kind in ("header", "vote", "certificate")
                else []
            )
            if claims:
                self._m_burst_claims[kind].inc(len(claims))
            spans.append((len(msgs), len(claims), stale, seen, dedup_key))
            for m, k, s in claims:
                msgs.append(m)
                keys.append(k)
                sigs.append(s)
        mask = (
            await crypto_backend.averify_batch_mask(
                msgs, keys, sigs, site="batch_burst"
            )
            if msgs
            else []
        )
        for item, (off, count, stale, seen, dedup_key) in zip(items, spans):
            # Fail CLOSED on stale-filtered items: they carry zero verified
            # claims, so `all([])` would hand them sig_ok=True.  Today the
            # replay raises TooOld on the same round checks before ever
            # consulting sig_ok, but any future drift between this
            # pre-filter and sanitize_* must not skip the signature gate.
            sig_ok = (not stale) and (seen or all(mask[off : off + count]))
            if dedup_key is not None and sig_ok and not seen:
                self._verified_recent[dedup_key] = None
                if len(self._verified_recent) > self.VERIFIED_CACHE:
                    self._verified_recent.pop(
                        next(iter(self._verified_recent))
                    )
            await self._handle("primaries", item, sig_ok)

    async def _verify_loop(self) -> None:
        """Pipelined verify stage (active when the batch window is on):
        collect peer messages forwarded by run() until the window
        closes or the batch cap is hit, then one backend dispatch +
        in-order replay.  While a dispatch's device round trip is in
        flight (off the event loop), run() keeps draining the next
        bursts into the queue — so round N+1's network/proposer work
        pipelines behind round N's verify instead of stalling, and the
        backlog naturally deepens the next batch."""
        queue = self._verify_q
        loop = asyncio.get_running_loop()
        while True:
            items = [await queue.get()]
            deadline = loop.time() + self.verify_window_s
            while len(items) < self.verify_batch_max:
                try:
                    items.append(queue.get_nowait())
                    continue
                except asyncio.QueueEmpty:
                    pass
                remaining = deadline - loop.time()
                if remaining <= 0:
                    break
                try:
                    items.append(
                        await asyncio.wait_for(queue.get(), remaining)
                    )
                except asyncio.TimeoutError:
                    break
            # lint: allow-interleave(run() may flush/sweep after a waiter/proposer burst while this replay is suspended — safely: _flush_pending is subset-safe (flush_deferred appends EVERY buffered store record before releasing any staged vote, so persist-before-vote holds for an early flush of a partial burst) and _gc_sweep is monotonic-guarded (gc_round only advances; a concurrent sweep makes this one a no-op))
            await self._handle_primaries_burst(items)
            # Same per-burst epilogue as run(): one coalesced log flush
            # releasing the staged votes, then the per-round-map sweep.
            self._flush_pending()
            self._gc_sweep()

    async def _forward_to_verify(self, items, verify_task) -> None:
        """Forward a drained burst into the verify pipeline.  Each
        blocked put races the verify task: if the pipeline's sole
        consumer has crashed, a full queue would otherwise block run()
        forever with the failure never surfaced — here the crash
        re-raises out of run() instead."""
        for item in items:
            if not self._verify_q.full() and not verify_task.done():
                self._verify_q.put_nowait(item)
                continue
            put = asyncio.ensure_future(self._verify_q.put(item))
            await asyncio.wait(
                {put, verify_task}, return_when=asyncio.FIRST_COMPLETED
            )
            if verify_task.done():
                put.cancel()
                await asyncio.gather(put, return_exceptions=True)
                verify_task.result()  # re-raises the stage's exception
                raise RuntimeError("core verify loop exited unexpectedly")
            await put

    async def run(self) -> None:
        sources = {
            "primaries": self.rx_primaries,
            "header_waiter": self.rx_header_waiter,
            "certificate_waiter": self.rx_certificate_waiter,
            "proposer": self.rx_proposer,
        }
        loop = asyncio.get_running_loop()
        gets = {
            name: loop.create_task(q.get(), name=f"core-{name}")
            for name, q in sources.items()
        }
        verify_task = (
            loop.create_task(self._verify_loop(), name="core-verify")
            if self._verify_q is not None
            else None
        )
        try:
            while True:
                # The verify task rides in the wait set so its death
                # wakes an otherwise-idle run() immediately; its crash
                # re-raises here instead of wedging the primary.
                wait_set = set(gets.values())
                if verify_task is not None:
                    wait_set.add(verify_task)
                done, _ = await asyncio.wait(
                    wait_set, return_when=asyncio.FIRST_COMPLETED
                )
                if verify_task is not None and verify_task.done():
                    verify_task.result()  # surface a crashed verify stage
                    raise RuntimeError(
                        "core verify loop exited unexpectedly"
                    )
                for name, task in list(gets.items()):
                    if task not in done:
                        continue
                    burst = [task.result()]
                    # Drain whatever else is already queued so the crypto
                    # batch is as large as the backlog allows.
                    queue = sources[name]
                    while len(burst) < self.DRAIN_LIMIT:
                        try:
                            burst.append(queue.get_nowait())
                        except asyncio.QueueEmpty:
                            break
                    gets[name] = loop.create_task(
                        queue.get(), name=f"core-{name}"
                    )
                    if name == "primaries":
                        if self._verify_q is not None:
                            # Window mode: hand the burst to the verify
                            # pipeline and return to draining — the
                            # proposer/waiter sources stay serviced
                            # while the batch accumulates/verifies.
                            await self._forward_to_verify(
                                burst, verify_task
                            )
                        else:
                            # lint: allow-interleave(mode exclusivity: this arm only runs with the window OFF, where _verify_loop was never spawned — the "other root" the static merge sees cannot exist at runtime; the shared epilogue below is additionally subset-safe/monotonic as pragma'd in _verify_loop)
                            await self._handle_primaries_burst(burst)
                    else:
                        for item in burst:
                            await self._handle(name, item)
                    # Once per burst: release the staged votes behind one
                    # coalesced log flush, then sweep the per-round maps.
                    self._flush_pending()
                    self._gc_sweep()
        finally:
            for task in gets.values():
                task.cancel()
            if verify_task is not None:
                verify_task.cancel()
                await asyncio.gather(verify_task, return_exceptions=True)
