"""DAG error model (reference primary/src/error.rs, 59 LoC)."""

from __future__ import annotations


class DagError(Exception):
    pass


class InvalidSignature(DagError):
    pass


class InvalidHeaderId(DagError):
    pass


class UnknownAuthority(DagError):
    pass


class AuthorityReuse(DagError):
    pass


class MalformedHeader(DagError):
    pass


class HeaderRequiresQuorum(DagError):
    pass


class CertificateRequiresQuorum(DagError):
    pass


class UnexpectedVote(DagError):
    pass


class TooOld(DagError):
    """Message round is below the garbage-collection horizon; logged at
    debug level and dropped (reference core.rs:392-398)."""
