"""Protocol messages of the DAG: Header, Vote, Certificate.

Reference primary/src/messages.rs (256 LoC).  Deterministic hashing rules
(SHA-512/32B over canonical field bytes, maps/sets iterated sorted — BTreeMap
semantics):
- Header.id   = H(author ‖ round_le64 ‖ {digest ‖ worker_id_le32}* ‖ parents*)
  (messages.rs:70-84)
- Vote digest = H(header_id ‖ round_le64 ‖ origin)              (messages.rs:145-153)
- Certificate digest = H(header_id ‖ round_le64 ‖ origin)       (messages.rs:226-234)

Vote digest and certificate digest coincide by construction: every vote signs
exactly the digest of the certificate it will be folded into, which is what
makes quorum verification a single batched check over one message — the TPU
vmap target (SURVEY.md §3.3).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..config import Committee, WorkerId
from ..crypto import (
    AggregateSignature,
    Digest,
    PublicKey,
    Signature,
    digest32,
    verify,
    verify_aggregate,
    verify_batch,
)
from ..crypto.aggregate import SCHEMES, SchemeMismatch, scheme as cert_sig_scheme
from ..messages import Round, read_key_ref, skip_key_ref, write_key_ref
from ..network import wirev2
from ..utils.serde import Reader, Writer
from .errors import (
    AuthorityReuse,
    CertificateRequiresQuorum,
    InvalidHeaderId,
    InvalidSignature,
    UnknownAuthority,
)

# --- Header ------------------------------------------------------------------


@dataclass
class Header:
    author: PublicKey
    round: Round
    payload: Dict[Digest, WorkerId]
    parents: Set[Digest]
    id: Digest = field(default_factory=Digest.zero)
    signature: Signature = field(default_factory=Signature.default)

    @classmethod
    async def new(cls, author, round, payload, parents, signature_service) -> "Header":
        header = cls(author=author, round=round, payload=payload, parents=set(parents))
        header.id = header.compute_digest()
        header.signature = await signature_service.request_signature(
            header.id, site="header"
        )
        return header

    def compute_digest(self) -> Digest:
        w = Writer()
        w.raw(self.author)
        w.u64(self.round)
        for digest in sorted(self.payload):
            w.raw(digest)
            w.u32(self.payload[digest])
        for parent in sorted(self.parents):
            w.raw(parent)
        return digest32(w.finish())

    def verify_structure(self, committee: Committee) -> None:
        """All non-crypto checks of verify() (reference messages.rs:48-63)."""
        if self.id != self.compute_digest():
            raise InvalidHeaderId(f"header {self.id!r} id mismatch")
        if committee.stake(self.author) <= 0:
            raise UnknownAuthority(repr(self.author))

    def signature_claims(self) -> List[Tuple[bytes, PublicKey, Signature]]:
        """(message, key, signature) triples this message's validity rests
        on — the unit the Core accumulates into one batched device verify
        (SURVEY.md §7 'accumulate → batch-verify → replay')."""
        return [(bytes(self.id), self.author, self.signature)]

    def verify(self, committee: Committee) -> None:
        """Reference messages.rs:48-67."""
        self.verify_structure(committee)
        if not verify(
            bytes(self.id), self.author, self.signature, site="header"
        ):
            raise InvalidSignature(f"header {self.id!r}")

    def encode(self, w: Writer) -> None:
        # Wire v2 (NARWHAL_WIRE_V2, the default): committee-index key
        # refs and varint rounds/counts — the header's only raw 32-byte
        # material is its digests, which the per-connection dictionary
        # then back-references.  The legacy body is the =0 A/B arm.
        # Hashing preimages (compute_digest) are NOT touched by either:
        # ids are flag-invariant.
        if wirev2.enabled():
            write_key_ref(w, self.author)
            w.uvarint(self.round)
            w.uvarint(len(self.payload))
            for digest in sorted(self.payload):
                w.raw(digest)
                w.uvarint(self.payload[digest])
            w.uvarint(len(self.parents))
        else:
            w.raw(self.author)
            w.u64(self.round)
            w.u32(len(self.payload))
            for digest in sorted(self.payload):
                w.raw(digest)
                w.u32(self.payload[digest])
            w.u32(len(self.parents))
        for parent in sorted(self.parents):
            w.raw(parent)
        w.raw(self.id)
        w.raw(self.signature)

    @classmethod
    def decode(cls, r: Reader) -> "Header":
        if wirev2.enabled():
            author = read_key_ref(r)
            round = r.uvarint()
            payload = {}
            for _ in range(r.uvarint()):
                d = Digest(r.raw(32))
                payload[d] = r.uvarint()
            n_parents = r.uvarint()
        else:
            author = PublicKey(r.raw(32))
            round = r.u64()
            payload = {}
            for _ in range(r.u32()):
                d = Digest(r.raw(32))
                payload[d] = r.u32()
            n_parents = r.u32()
        parents = {Digest(r.raw(32)) for _ in range(n_parents)}
        id_ = Digest(r.raw(32))
        signature = Signature(r.raw(64))
        return cls(author, round, payload, parents, id_, signature)

    def __repr__(self) -> str:
        return f"{self.id!r}: B{self.round}({self.author!r})"

    def __eq__(self, other) -> bool:
        return isinstance(other, Header) and self.id == other.id

    def __hash__(self) -> int:
        return hash(self.id)


# --- Vote --------------------------------------------------------------------


@dataclass
class Vote:
    id: Digest  # header id being voted for
    round: Round
    origin: PublicKey  # header author
    author: PublicKey  # voter
    signature: Signature = field(default_factory=Signature.default)

    @classmethod
    async def new(cls, header: Header, author: PublicKey, signature_service) -> "Vote":
        vote = cls(id=header.id, round=header.round, origin=header.author, author=author)
        vote.signature = await signature_service.request_signature(
            vote.digest(), site="vote"
        )
        return vote

    def digest(self) -> Digest:
        w = Writer()
        w.raw(self.id)
        w.u64(self.round)
        w.raw(self.origin)
        return digest32(w.finish())

    def verify_structure(self, committee: Committee) -> None:
        if committee.stake(self.author) <= 0:
            raise UnknownAuthority(repr(self.author))

    def signature_claims(self) -> List[Tuple[bytes, PublicKey, Signature]]:
        return [(bytes(self.digest()), self.author, self.signature)]

    def verify(self, committee: Committee) -> None:
        self.verify_structure(committee)
        if not verify(
            bytes(self.digest()), self.author, self.signature, site="vote"
        ):
            raise InvalidSignature(f"vote by {self.author!r}")

    def encode(self, w: Writer) -> None:
        w.raw(self.id)
        if wirev2.enabled():
            w.uvarint(self.round)
            write_key_ref(w, self.origin)
            write_key_ref(w, self.author)
        else:
            w.u64(self.round)
            w.raw(self.origin)
            w.raw(self.author)
        w.raw(self.signature)

    @classmethod
    def decode(cls, r: Reader) -> "Vote":
        if wirev2.enabled():
            return cls(
                Digest(r.raw(32)),
                r.uvarint(),
                read_key_ref(r),
                read_key_ref(r),
                Signature(r.raw(64)),
            )
        return cls(
            Digest(r.raw(32)),
            r.u64(),
            PublicKey(r.raw(32)),
            PublicKey(r.raw(32)),
            Signature(r.raw(64)),
        )

    def __repr__(self) -> str:
        return f"{self.digest()!r}: V{self.round}({self.author!r}, {self.id!r})"


# --- Certificate -------------------------------------------------------------


# Certificate wire scheme byte (first byte after the embedded header):
# which certificate-signature scheme the votes section is encoded under.
# Voteless (genesis) certificates always write 0 — they carry no
# signature material, so they are scheme-neutral.  An unknown byte or a
# scheme-bearing byte that differs from this process's scheme refuses
# loudly at decode (SchemeMismatch — the checkpoint-magic pattern):
# silently parsing the other scheme's bytes would misread signature
# material, and a pre-scheme stored certificate misreads its vote count
# as an unknown scheme byte, which is exactly the loud refusal we want.
CERT_SCHEME_INDIVIDUAL = 0
CERT_SCHEME_HALFAGG = 1


@dataclass
class Certificate:
    header: Header
    votes: List[Tuple[PublicKey, Signature]] = field(default_factory=list)
    # --cert-sig-scheme halfagg: the sorted signer quorum plus ONE
    # half-aggregated blob instead of 2f+1 (name, sig) pairs.  Exactly
    # one of votes / (agg_signers, agg) is populated on a non-genesis
    # certificate; genesis has neither.
    agg_signers: List[PublicKey] = field(default_factory=list)
    agg: Optional[AggregateSignature] = None

    @property
    def round(self) -> Round:
        return self.header.round

    @property
    def origin(self) -> PublicKey:
        return self.header.author

    @property
    def scheme(self) -> str:
        """The scheme this certificate's signature material is under
        ("individual" for genesis: no material, scheme-neutral)."""
        return "halfagg" if self.agg is not None else "individual"

    def voters(self) -> List[PublicKey]:
        """The authorities whose signatures back this certificate,
        scheme-independent — the stake/reuse checks run over this."""
        if self.agg is not None:
            return list(self.agg_signers)
        return [name for name, _ in self.votes]

    def digest(self) -> Digest:
        # Memoized: H(header_id ‖ round ‖ origin) never changes after
        # construction (votes do not participate), and the commit path
        # asks for it ~10× per certificate per node — at committee scale
        # the recomputation was a measured top-10 cost.
        d = getattr(self, "_digest", None)
        if d is None:
            w = Writer()
            w.raw(self.header.id)
            w.u64(self.round)
            w.raw(self.origin)
            d = self._digest = digest32(w.finish())
        return d

    def verify_structure(self, committee: Committee) -> None:
        """Quorum + reuse + authority checks (reference messages.rs:189-213,
        everything before the batched signature verification)."""
        if self in genesis(committee):
            return
        self.header.verify_structure(committee)
        if self.agg is not None and len(self.agg) != 32 * (
            len(self.agg_signers) + 1
        ):
            # Signer list and blob width must agree BEFORE stake math: a
            # blob carrying more commitments than named signers (or
            # fewer) is malformed, not merely unverifiable.
            raise InvalidSignature(
                f"certificate {self.digest()!r}: aggregate width "
                f"{len(self.agg)} does not match "
                f"{len(self.agg_signers)} signers"
            )
        weight = 0
        used = set()
        for name in self.voters():
            if name in used:
                raise AuthorityReuse(repr(name))
            stake = committee.stake(name)
            if stake <= 0:
                raise UnknownAuthority(repr(name))
            used.add(name)
            weight += stake
        if weight < committee.quorum_threshold():
            raise CertificateRequiresQuorum(repr(self.digest()))

    def signature_claims(self) -> List[Tuple[bytes, PublicKey, Signature]]:
        """Header signature + this certificate's vote material over its
        digest.  ``individual``: 2f+2 claims joining the Core's
        accumulated device batch.  ``halfagg``: exactly TWO claims — the
        header signature plus one aggregate claim (signer tuple +
        AggregateSignature in the key/sig slots), which the backend seam
        prices as ONE verify op at the ``certificate_agg`` site."""
        if self.agg is not None:
            return self.header.signature_claims() + [
                (bytes(self.digest()), tuple(self.agg_signers), self.agg)
            ]
        if not self.votes:  # genesis
            return []
        d = bytes(self.digest())
        return self.header.signature_claims() + [
            (d, name, sig) for name, sig in self.votes
        ]

    def verify(self, committee: Committee) -> None:
        """Quorum + batched signature check (reference messages.rs:189-215).
        The batched call is the #1 crypto hot loop — the TPU backend verifies
        all 2f+1 signatures in one device dispatch; under ``halfagg`` the
        whole quorum is ONE aggregate equation instead."""
        if self in genesis(committee):
            return
        self.verify_structure(committee)
        self.header.verify(committee)
        if self.agg is not None:
            if not verify_aggregate(
                bytes(self.digest()), self.agg_signers, self.agg
            ):
                raise InvalidSignature(f"certificate {self.digest()!r}")
            return
        if not verify_batch(
            self.digest(),
            [n for n, _ in self.votes],
            [s for _, s in self.votes],
            site="certificate",
        ):
            raise InvalidSignature(f"certificate {self.digest()!r}")

    def encode(self, w: Writer) -> None:
        self.header.encode(w)
        # Scheme-versioned votes section (CERT_SCHEME_* rationale above).
        # individual/v2: vote pubkeys ride as committee indices — ~1 byte
        # instead of 32 per vote, 64-byte signatures remain.  halfagg:
        # the signer refs plus ONE 32·(q+1) aggregate blob (length
        # implied by the signer count) — the ROADMAP item 2 collapse.
        if self.agg is not None:
            w.u8(CERT_SCHEME_HALFAGG)
            if wirev2.enabled():
                w.uvarint(len(self.agg_signers))
                for name in self.agg_signers:
                    write_key_ref(w, name)
            else:
                w.u32(len(self.agg_signers))
                for name in self.agg_signers:
                    w.raw(name)
            w.raw(self.agg)
            return
        w.u8(CERT_SCHEME_INDIVIDUAL)
        if wirev2.enabled():
            w.uvarint(len(self.votes))
            for name, sig in self.votes:
                write_key_ref(w, name)
                w.raw(sig)
        else:
            w.u32(len(self.votes))
            for name, sig in self.votes:
                w.raw(name)
                w.raw(sig)

    @classmethod
    def decode(cls, r: Reader) -> "Certificate":
        header = Header.decode(r)
        scheme_byte = r.u8()
        if scheme_byte not in (CERT_SCHEME_INDIVIDUAL, CERT_SCHEME_HALFAGG):
            raise ValueError(
                f"unknown certificate scheme byte {scheme_byte} (known "
                f"schemes: {SCHEMES}; a pre-scheme store must be wiped or "
                "replayed by the version that wrote it)"
            )
        ours = cert_sig_scheme()
        if scheme_byte == CERT_SCHEME_HALFAGG:
            if ours != "halfagg":
                raise SchemeMismatch(
                    "certificate was encoded under cert-sig scheme "
                    f"'halfagg' but this node runs {ours!r}; refusing to "
                    "decode — run the whole committee (and its stores) "
                    "under one --cert-sig-scheme"
                )
            if wirev2.enabled():
                n = r.uvarint()
                signers = [read_key_ref(r) for _ in range(n)]
            else:
                n = r.u32()
                signers = [PublicKey(r.raw(32)) for _ in range(n)]
            if n == 0:
                raise ValueError("halfagg certificate with zero signers")
            agg = AggregateSignature(r.raw(32 * (n + 1)))
            return cls(header, agg_signers=signers, agg=agg)
        votes = []
        if wirev2.enabled():
            for _ in range(r.uvarint()):
                votes.append((read_key_ref(r), Signature(r.raw(64))))
        else:
            for _ in range(r.u32()):
                votes.append((PublicKey(r.raw(32)), Signature(r.raw(64))))
        if votes and ours != "individual":
            raise SchemeMismatch(
                "certificate carries individually-signed votes but this "
                f"node runs cert-sig scheme {ours!r}; refusing to decode "
                "— run the whole committee (and its stores) under one "
                "--cert-sig-scheme"
            )
        return cls(header, votes)

    def serialize(self) -> bytes:
        # Memoized like digest(): the same certificate is re-serialized
        # for the store write, the audit insert, and helper re-serves.
        # Votes are final by the time anything serializes a certificate
        # (the aggregator builds the object once, complete).
        wire = getattr(self, "_wire", None)
        if wire is None:
            w = Writer()
            self.encode(w)
            wire = self._wire = w.finish()
        return wire

    @classmethod
    def deserialize(cls, data: bytes) -> "Certificate":
        # Same single-process memo as decode_primary_message: the
        # dependency checks deserialize a header's ~N stored parents on
        # every process_header, and in a simulated committee the same
        # stored bytes recur across all N nodes.
        if _DECODE_CACHE_ON:
            key = (b"C", data)
            cert = _DECODE_CACHE.get(key)
            if cert is not None:
                return cert
            cert = cls._deserialize(data)
            if len(_DECODE_CACHE) >= _DECODE_CACHE_CAP:
                _DECODE_CACHE.clear()
            _DECODE_CACHE[key] = cert
            return cert
        return cls._deserialize(data)

    @classmethod
    def _deserialize(cls, data: bytes) -> "Certificate":
        r = Reader(data)
        cert = cls.decode(r)
        r.expect_done()
        return cert

    def __repr__(self) -> str:
        return f"{self.digest()!r}: C{self.round}({self.origin!r}, {self.header.id!r})"

    def __eq__(self, other) -> bool:
        # Round and origin MUST participate: genesis certificates have a
        # zero header id and no votes, so id+votes alone would let a forged
        # non-zero-round certificate compare equal to genesis and skip
        # verification entirely (reference messages.rs:249-256 compares
        # round() and origin() for exactly this reason).
        return (
            isinstance(other, Certificate)
            and self.header == other.header
            and self.round == other.round
            and self.origin == other.origin
            and self.votes == other.votes
            # Aggregate material participates for the same reason votes
            # do: a forged voteless-but-aggregated certificate must not
            # compare equal to genesis and skip verification.
            and self.agg_signers == other.agg_signers
            and self.agg == other.agg
        )


_GENESIS_CACHE: "weakref.WeakKeyDictionary[Committee, List[Certificate]]" = None  # type: ignore


def genesis(committee: Committee) -> List[Certificate]:
    """One unsigned certificate per authority at round 0
    (reference messages.rs:175-187).  Memoized per committee object:
    ``Certificate.verify_structure`` consults this list for EVERY
    certificate sanitized, and rebuilding N certificates (each hashing
    its header) per call was a measured top-5 cost of a simulated N=20
    committee.  Callers treat the result as immutable."""
    global _GENESIS_CACHE
    if _GENESIS_CACHE is None:
        import weakref

        _GENESIS_CACHE = weakref.WeakKeyDictionary()
    cached = _GENESIS_CACHE.get(committee)
    if cached is None:
        cached = _GENESIS_CACHE[committee] = [
            Certificate(
                header=Header(
                    author=name, round=0, payload={}, parents=set()
                )
            )
            for name in committee.authorities
        ]
    return cached


# --- primary ↔ primary wire frames ------------------------------------------

PM_HEADER = 0
PM_VOTE = 1
PM_CERTIFICATE = 2
PM_CERTIFICATES_REQUEST = 3

# Wire-type names for the goodput ledger (see narwhal_tpu/messages.py
# frame_classifier): the primary↔primary plane's tag space.
PRIMARY_FRAME_TYPES = {
    PM_HEADER: "header",
    PM_VOTE: "vote",
    PM_CERTIFICATE: "certificate",
    PM_CERTIFICATES_REQUEST: "cert_request",
}


def encode_primary_message(obj) -> bytes:
    w = Writer()
    if isinstance(obj, Header):
        w.u8(PM_HEADER)
        obj.encode(w)
    elif isinstance(obj, Vote):
        w.u8(PM_VOTE)
        obj.encode(w)
    elif isinstance(obj, Certificate):
        w.u8(PM_CERTIFICATE)
        obj.encode(w)
    else:
        raise TypeError(type(obj))
    return w.finish()


def encode_certificates_request(digests: List[Digest], requestor: PublicKey) -> bytes:
    w = Writer()
    w.u8(PM_CERTIFICATES_REQUEST)
    if wirev2.enabled():
        w.uvarint(len(digests))
        for d in digests:
            w.raw(d)
        write_key_ref(w, requestor)
    else:
        w.u32(len(digests))
        for d in digests:
            w.raw(d)
        w.raw(requestor)
    return w.finish()


# Frame-decode memo for single-process committees (the simulation
# harness): a broadcast header/certificate frame arrives at N-1 in-process
# receivers (and again via helper re-serves), and each arrival would
# repeat the full field-by-field parse — the measured #1 wall cost of an
# N=20 sim round.  Decoded messages are treated immutably everywhere
# (receivers never write into a decoded Header/Certificate; aggregators
# build their own state), so sharing one decoded object per distinct
# frame is safe.  OFF by default: a multi-process node sees each frame
# once and the memo would only hold dead objects.
_DECODE_CACHE: dict = {}  # bytes frame → decoded tuple; (b"C", bytes) → Certificate
_DECODE_CACHE_CAP = 16_384
_DECODE_CACHE_ON = False


def set_decode_cache(enabled: bool) -> None:
    """Enable/disable the frame-decode memo (simulation harness only);
    disabling also drops the cached objects."""
    global _DECODE_CACHE_ON
    _DECODE_CACHE_ON = bool(enabled)
    _DECODE_CACHE.clear()


def decode_primary_message(data: bytes):
    """Returns ("header", Header) | ("vote", Vote) | ("certificate", Certificate)
    | ("certificates_request", digests, requestor)."""
    if _DECODE_CACHE_ON:
        out = _DECODE_CACHE.get(data)
        if out is not None:
            return out
        out = _decode_primary_message(data)
        if len(_DECODE_CACHE) >= _DECODE_CACHE_CAP:
            _DECODE_CACHE.clear()  # wholesale: entries age together
        _DECODE_CACHE[data] = out
        return out
    return _decode_primary_message(data)


def _decode_primary_message(data: bytes):
    r = Reader(data)
    tag = r.u8()
    if tag == PM_HEADER:
        out = ("header", Header.decode(r))
    elif tag == PM_VOTE:
        out = ("vote", Vote.decode(r))
    elif tag == PM_CERTIFICATE:
        out = ("certificate", Certificate.decode(r))
    elif tag == PM_CERTIFICATES_REQUEST:
        if wirev2.enabled():
            digests = [Digest(r.raw(32)) for _ in range(r.uvarint())]
            requestor = read_key_ref(r)
        else:
            digests = [Digest(r.raw(32)) for _ in range(r.u32())]
            requestor = PublicKey(r.raw(32))
        out = ("certificates_request", digests, requestor)
    else:
        raise ValueError(f"unknown PrimaryMessage tag {tag}")
    r.expect_done()
    return out


# --- wire-v2 digest-span walkers (primary plane) -----------------------------
#
# Offsets of the 32-byte dictionary material in each v2-encoded frame,
# for the per-connection reference compression (wirev2.register_spans;
# best-effort by contract — a parse error means "no spans", never
# corruption).  This is where the cert-broadcast repetition pays off: a
# round's certificate re-carries its header's parents/payload digests
# and id, all of which the same connection just shipped in the header
# frame.


def _header_body_spans(r: Reader, spans: List[int]) -> None:
    skip_key_ref(r, spans)  # author (literal only for unknown keys)
    r.uvarint()  # round
    for _ in range(r.uvarint()):  # payload
        spans.append(r.tell())
        r.raw(32)
        r.uvarint()
    for _ in range(r.uvarint()):  # parents
        spans.append(r.tell())
        r.raw(32)
    spans.append(r.tell())  # id
    r.raw(32)
    r.raw(64)  # signature: not dictionary material


def _header_spans(data: bytes) -> List[int]:
    r = Reader(data)
    r.u8()
    spans: List[int] = []
    _header_body_spans(r, spans)
    return spans


def _vote_spans(data: bytes) -> List[int]:
    r = Reader(data)
    r.u8()
    spans = [r.tell()]  # header id
    r.raw(32)
    r.uvarint()  # round
    skip_key_ref(r, spans)  # origin
    skip_key_ref(r, spans)  # author
    return spans


def _certificate_spans(data: bytes) -> List[int]:
    r = Reader(data)
    r.u8()
    spans: List[int] = []
    _header_body_spans(r, spans)
    scheme_byte = r.u8()
    if scheme_byte == CERT_SCHEME_HALFAGG:
        n = r.uvarint()
        for _ in range(n):  # signer refs
            skip_key_ref(r, spans)
        r.raw(32 * (n + 1))  # aggregate blob: nonces never repeat
    else:
        for _ in range(r.uvarint()):  # votes
            skip_key_ref(r, spans)
            r.raw(64)
    return spans


# cert_request frames ride SimpleSender (header_waiter), whose
# connections stay on legacy framing — no walker registered for them.
wirev2.register_spans("header", _header_spans)
wirev2.register_spans("vote", _vote_spans)
wirev2.register_spans("certificate", _certificate_spans)
