"""HeaderWaiter: park suspended headers until their dependencies arrive.

Reference primary/src/header_waiter.rs (293 LoC): on SyncBatches, command our
workers to fetch the missing batches (PrimaryWorkerMessage::Synchronize); on
SyncParents, request the missing certificates from the header author's
primary; park the header on notify_read of every missing store key and loop
it back to the Core once they all land.  A 1 s timer escalates overdue parent
requests to `sync_retry_nodes` random primaries; per-round state is GC'd from
the shared consensus round.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, List, Tuple

from ..config import Committee
from ..crypto import Digest, PublicKey
from ..messages import Round, encode_synchronize
from ..network import SimpleSender
from ..store import Store
from .core import AtomicRound
from .messages import Header, encode_certificates_request
from .synchronizer import payload_key
from ..utils.clock import loop_now
from ..utils.tasks import spawn

log = logging.getLogger("narwhal.primary")

TIMER_RESOLUTION = 1.0  # seconds


class HeaderWaiter:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        store: Store,
        consensus_round: AtomicRound,
        gc_depth: Round,
        sync_retry_delay_ms: int,
        sync_retry_nodes: int,
        rx_synchronizer: asyncio.Queue,  # ("sync_batches"|"sync_parents", ...)
        tx_core: asyncio.Queue,  # resumed headers
    ) -> None:
        self.name = name
        self.committee = committee
        self.store = store
        self.consensus_round = consensus_round
        self.gc_depth = gc_depth
        self.sync_retry_delay = sync_retry_delay_ms / 1000.0
        self.sync_retry_nodes = sync_retry_nodes
        self.rx_synchronizer = rx_synchronizer
        self.tx_core = tx_core
        self.sender = SimpleSender()

        # header id → (round, parked task)
        self.pending: Dict[Digest, Tuple[Round, asyncio.Task]] = {}
        # missing certificate digest → (round, last request time)
        self.parent_requests: Dict[Digest, Tuple[Round, float]] = {}

    async def run(self) -> None:
        timer = spawn(self._timer(), name="header-waiter-timer")
        try:
            while True:
                message = await self.rx_synchronizer.get()
                kind = message[0]
                if kind == "sync_batches":
                    _, missing, header = message
                    await self._sync_batches(missing, header)
                elif kind == "sync_parents":
                    _, missing, header = message
                    await self._sync_parents(missing, header)
                self._gc()
        finally:
            timer.cancel()
            for _, task in self.pending.values():
                task.cancel()
            self.pending.clear()

    # --- handlers -----------------------------------------------------------

    async def _sync_batches(self, missing: Dict[Digest, int], header: Header) -> None:
        if header.id in self.pending:
            return
        # Ask our own workers (grouped by worker id) to fetch the batches
        # from the header author's workers.
        by_worker: Dict[int, List[Digest]] = {}
        for digest, worker_id in missing.items():
            by_worker.setdefault(worker_id, []).append(digest)
        our_workers = self.committee.authorities[self.name].workers
        for worker_id, digests in by_worker.items():
            addrs = our_workers.get(worker_id)
            if addrs is None:
                log.warning("Header references unknown worker id %d", worker_id)
                continue
            self.sender.send(
                addrs.primary_to_worker,
                encode_synchronize(digests, header.author),
                msg_type="synchronize",
            )
        keys = [payload_key(d, w) for d, w in missing.items()]
        self._park(header, keys)

    async def _sync_parents(self, missing: List[Digest], header: Header) -> None:
        if header.id in self.pending:
            return
        # Optimistically ask the header author; the timer escalates later.
        now = loop_now()
        to_request = []
        for digest in missing:
            if digest not in self.parent_requests:
                self.parent_requests[digest] = (header.round, now)
                to_request.append(digest)
        if to_request:
            address = self.committee.primary(header.author).primary_to_primary
            self.sender.send(
                address,
                encode_certificates_request(to_request, self.name),
                msg_type="cert_request",
            )
        self._park(header, [bytes(d) for d in missing])

    def _park(self, header: Header, keys: List[bytes]) -> None:
        task = spawn(self._wait(header, keys))
        self.pending[header.id] = (header.round, task)

    async def _wait(self, header: Header, keys: List[bytes]) -> None:
        await asyncio.gather(*(self.store.notify_read(k) for k in keys))
        self.pending.pop(header.id, None)
        for digest in header.parents:
            self.parent_requests.pop(digest, None)
        await self.tx_core.put(header)

    # --- timer + GC ---------------------------------------------------------

    async def _timer(self) -> None:
        while True:
            await asyncio.sleep(TIMER_RESOLUTION)
            now = loop_now()
            overdue = []
            for d, (_, t) in list(self.parent_requests.items()):
                if now - t < self.sync_retry_delay:
                    continue
                if self.store.read(bytes(d)) is not None:
                    # Satisfied while overdue (the parked header's
                    # notify_read fired; the batch entry clears only when
                    # the whole header unparks): a landed certificate must
                    # fall out of the retry broadcast HERE, because every
                    # re-request makes sync_retry_nodes peers re-send it,
                    # and on a catching-up node that duplicate flood
                    # outruns signature verification — the runaway the
                    # partition-heal fault scenario exposed (the node
                    # verified duplicates at 100% CPU for 60+ s and never
                    # committed again).
                    del self.parent_requests[d]
                    continue
                overdue.append(d)
            if overdue:
                addresses = [
                    a.primary_to_primary
                    for _, a in self.committee.others_primaries(self.name)
                ]
                message = encode_certificates_request(overdue, self.name)
                self.sender.lucky_broadcast(
                    addresses, message, self.sync_retry_nodes,
                    msg_type="cert_request",
                )
                for d in overdue:
                    r, _ = self.parent_requests[d]
                    self.parent_requests[d] = (r, now)
            self._gc()

    def _gc(self) -> None:
        round = self.consensus_round.value
        if round <= self.gc_depth:
            return
        gc_round = round - self.gc_depth
        for hid in [h for h, (r, _) in self.pending.items() if r <= gc_round]:
            _, task = self.pending.pop(hid)
            task.cancel()
        for d in [d for d, (r, _) in self.parent_requests.items() if r <= gc_round]:
            del self.parent_requests[d]
