"""Proposer: owns the round counter and mints signed headers.

Reference primary/src/proposer.rs (155 LoC): starts at round 1 with genesis
parents; creates a header whenever it has parents AND (payload ≥ header_size
OR max_header_delay elapsed); round advances when the Core delivers a quorum
of certificates for the current round.

Two cadence extensions beyond the reference (ISSUE r10):

- **min_header_delay** (Sui-style): when > 0, a parent quorum plus ANY
  payload proposes as soon as min_header_delay has elapsed since the last
  header, instead of riding max_header_delay waiting for header_size bytes
  of digests.  Empty rounds still wait for the max delay, so an idle
  committee does not spin headers at wire speed.  0 disables the knob and
  keeps reference behavior exactly.
- **direct parent delivery**: the Core calls :meth:`deliver_parents`
  synchronously when the certificate quorum forms, instead of a queue
  put → event-loop wakeup → queue get round-trip.  The round advances (and
  ``primary.round_advance_seconds`` observes) at quorum time; a wake event
  nudges the run loop to mint the next header.  The queue path (rx_core)
  is kept for harnesses that wire the Proposer standalone.

And a third one (ISSUE r19, the multileader commit rule's proposer-side
half):

- **header_linger** — when > 0, a round advance arms a linger deadline
  and the fast mint paths (payload-ready, full header) hold until it
  passes; certificates of the just-advanced round that land AFTER the
  2f+1 quorum are merged into the pending parent set via
  :meth:`deliver_late_parent` (the Core forwards them while the round is
  current).  Without it every header cites exactly the FIRST 2f+1
  certificates of its round, so each commit-rule leader slot is cited
  with probability ≈ 2/3 and slot support hovers at the quorum
  borderline.  max_header_delay still caps the round; 0 disables the
  window and keeps prior behavior bit-for-bit.
"""

from __future__ import annotations

import asyncio
import logging
from typing import List, Optional, Tuple

from ..utils.env import env_flag

_TRACE = env_flag("NARWHAL_TRACE")

from .. import metrics
from ..config import Committee, WorkerId
from ..crypto import Digest, PublicKey, SignatureService
from ..messages import Round
from .messages import Header, genesis

log = logging.getLogger("narwhal.primary")


class Proposer:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        signature_service: SignatureService,
        header_size: int,
        max_header_delay_ms: int,
        rx_core: Optional[asyncio.Queue],  # (parent digests, round); None
        # when parents arrive solely via deliver_parents (Primary wiring)
        rx_workers: asyncio.Queue,  # (digest, worker_id)
        tx_core: asyncio.Queue,  # Header
        benchmark: bool = False,
        min_header_delay_ms: int = 0,
        header_linger_ms: int = 0,
    ) -> None:
        self.name = name
        self.signature_service = signature_service
        self.header_size = header_size
        self.max_header_delay = max_header_delay_ms / 1000.0
        # min is a FLOOR under the max deadline; a min above the max would
        # make payload rounds cycle slower than empty ones (which still
        # mint at the max) — clamp loudly instead.
        if min_header_delay_ms / 1000.0 > self.max_header_delay:
            log.warning(
                "min_header_delay (%d ms) exceeds max_header_delay "
                "(%d ms); clamping to the max",
                min_header_delay_ms, max_header_delay_ms,
            )
        self.min_header_delay = min(
            min_header_delay_ms / 1000.0, self.max_header_delay
        )
        # Linger is likewise bounded by the max deadline: a window the max
        # timer always truncates would silently never run full length.
        if header_linger_ms / 1000.0 > self.max_header_delay:
            log.warning(
                "header_linger (%d ms) exceeds max_header_delay "
                "(%d ms); clamping to the max",
                header_linger_ms, max_header_delay_ms,
            )
        self.header_linger = min(
            header_linger_ms / 1000.0, self.max_header_delay
        )
        self.rx_core = rx_core
        self.rx_workers = rx_workers
        self.tx_core = tx_core
        self.benchmark = benchmark

        self.round: Round = 1
        self.last_parents: List[Digest] = [c.digest() for c in genesis(committee)]
        self.digests: List[Tuple[Digest, WorkerId]] = []
        self.payload_size = 0
        # Set by deliver_parents (the Core's direct, queue-skipping path)
        # to nudge the run loop out of its queue wait.
        self._wake = asyncio.Event()
        # Armed by _advance when header_linger > 0; the fast mint paths
        # hold until it passes so late parents can still be cited.
        self._linger_deadline = 0.0
        self._m_headers = metrics.counter("primary.headers_proposed")
        self._m_late_parents = metrics.counter("primary.late_parents_cited")
        self._m_payload_digests = metrics.counter("primary.payload_digests")
        self._m_round = metrics.gauge("primary.round")
        # Round period: seconds between consecutive round advances.  The
        # cert→commit attribution (PR 4) shows commit latency is
        # dominated by protocol cadence — this histogram is the cadence
        # denominator (cert_inserted→commit_trigger ≈ commit depth ×
        # this), so a slow commit path reads directly as either a slow
        # round period (look here) or a starved commit rule (look at
        # consensus.commit_lag_rounds).  The per-round sub-stage trace
        # (metrics.ROUND_STAGES) decomposes it.
        self._m_round_advance = metrics.histogram(
            "primary.round_advance_seconds"
        )
        self._last_advance: Optional[float] = None
        self._mtrace = metrics.trace()
        self._rtrace = metrics.round_trace()

    def deliver_parents(self, parents: List[Digest], round: Round) -> None:
        """Direct (same-event-loop, synchronous) parent delivery from the
        Core: the round advances HERE, at certificate-quorum time, and the
        run loop is woken to mint the next header — no queue round-trip on
        the cadence critical path."""
        self._advance(parents, round)
        self._wake.set()

    def deliver_late_parent(self, digest: Digest, round: Round) -> None:
        """Merge a post-quorum certificate of the CURRENT round's parent
        round into the pending parent set (Core forwards these only while
        a linger window can still be open).  A stale round, an
        already-consumed parent set, or a duplicate digest are all
        silently dropped — the certificate is already in the DAG either
        way, this only widens the citation."""
        if round + 1 != self.round or not self.last_parents:
            return
        if digest in self.last_parents:
            return
        self.last_parents.append(digest)
        self._m_late_parents.inc()
        if _TRACE:
            log.info("TRACE late parent cited %r for round %d", digest, self.round)

    def _advance(self, parents: List[Digest], round: Round) -> bool:
        """Apply a parent quorum for ``round``; returns True if the round
        advanced.  Observes ``round_advance_seconds`` exactly once per
        advance (stale re-deliveries for old rounds are dropped)."""
        if round < self.round:
            return False
        self.round = round + 1
        self._m_round.set(self.round)
        now = asyncio.get_running_loop().time()
        if self._last_advance is not None:
            self._m_round_advance.observe(now - self._last_advance)
        self._last_advance = now
        self._linger_deadline = now + self.header_linger
        # Round-cadence trace: round `round`'s lifecycle ends here.
        self._rtrace.mark(str(round), "round_advance")
        metrics.flight_event("round_advance", round=self.round)
        log.debug("Dag moved to round %d", self.round)
        self.last_parents = parents
        return True

    async def _make_header(self) -> None:
        payload = dict(self.digests)
        self.digests = []
        parents, self.last_parents = self.last_parents, []
        header = await Header.new(
            self.name, self.round, payload, parents, self.signature_service
        )
        log.debug("Created %r", header)
        self._m_headers.inc()
        self._m_payload_digests.inc(len(payload))
        self._rtrace.mark(str(header.round), "header_proposed")
        for digest in payload:
            self._mtrace.mark(bytes(digest).hex(), "header")
        if self.benchmark:
            for digest in header.payload:
                # Parsed by the benchmark log parser to attribute batches to
                # rounds (reference proposer.rs:93-97).
                log.info("Created B%d(%r) -> %r", header.round, header.id, digest)
        await self.tx_core.put(header)

    async def run(self) -> None:
        log.debug("Dag starting at round %d", self.round)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.max_header_delay
        min_deadline = loop.time()  # min delay trivially elapsed at boot
        core_get = (
            loop.create_task(self.rx_core.get())
            if self.rx_core is not None
            else None
        )
        workers_get = loop.create_task(self.rx_workers.get())
        wake_get = loop.create_task(self._wake.wait())
        try:
            while True:
                now = loop.time()
                timer_expired = now >= deadline
                min_expired = now >= min_deadline
                enough_digests = self.payload_size >= self.header_size
                # "Ready" payload: a full header, or — with the min-delay
                # cadence enabled — any payload at all.
                ready = enough_digests or (
                    self.min_header_delay > 0 and bool(self.digests)
                )
                # The linger window holds the fast paths only; the max
                # deadline is an unconditional ceiling.
                linger_ok = now >= self._linger_deadline
                if self.last_parents and (
                    timer_expired or (min_expired and linger_ok and ready)
                ):
                    await self._make_header()
                    self.payload_size = 0
                    now = loop.time()
                    deadline = now + self.max_header_delay
                    min_deadline = now + self.min_header_delay

                # With no parent quorum the timers are irrelevant (we cannot
                # propose anyway) — wait purely on the queues instead of
                # busy-spinning on an already-expired deadline.  With
                # parents, wait only until the deadline that can actually
                # trigger: the min one if payload is ready, else the max.
                if not self.last_parents:
                    timeout = None
                elif ready:
                    # Wake at whichever gate still holds the fast path —
                    # min delay or linger — but never past the max
                    # deadline, which mints unconditionally.
                    gate = max(min_deadline, self._linger_deadline)
                    timeout = max(0.0, min(deadline, gate) - now)
                else:
                    timeout = max(0.0, deadline - now)
                waits = {workers_get, wake_get}
                if core_get is not None:
                    waits.add(core_get)
                done, _ = await asyncio.wait(
                    waits,
                    timeout=timeout,
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if wake_get in done:
                    # deliver_parents already advanced the round; just
                    # rearm the event and fall through to the mint check.
                    self._wake.clear()
                    wake_get = loop.create_task(self._wake.wait())
                if core_get is not None and core_get in done:
                    parents, round = core_get.result()
                    core_get = loop.create_task(self.rx_core.get())
                    # lint: allow-interleave(round/last_parents ARE written mid-mint by Core's synchronous deliver_parents callback while _make_header awaits Header.new — safely: _advance only ever replaces last_parents with a NEWER quorum and bumps round monotonically, _make_header consumed the previous quorum into locals before its first yield, and every loop iteration re-reads both fresh before the next mint decision)
                    self._advance(parents, round)
                if workers_get in done:
                    digest, worker_id = workers_get.result()
                    workers_get = loop.create_task(self.rx_workers.get())
                    if _TRACE:
                        log.info("TRACE payload arrived %r", digest)
                    self._mtrace.mark(bytes(digest).hex(), "digest_at_primary")
                    self.payload_size += len(digest)
                    self.digests.append((digest, worker_id))
        finally:
            if core_get is not None:
                core_get.cancel()
            workers_get.cancel()
            wake_get.cancel()
