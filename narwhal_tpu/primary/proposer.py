"""Proposer: owns the round counter and mints signed headers.

Reference primary/src/proposer.rs (155 LoC): starts at round 1 with genesis
parents; creates a header whenever it has parents AND (payload ≥ header_size
OR max_header_delay elapsed); round advances when the Core delivers a quorum
of certificates for the current round.
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import List, Optional, Tuple

_TRACE = bool(os.environ.get("NARWHAL_TRACE"))

from .. import metrics
from ..config import Committee, WorkerId
from ..crypto import Digest, PublicKey, SignatureService
from ..messages import Round
from .messages import Header, genesis

log = logging.getLogger("narwhal.primary")


class Proposer:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        signature_service: SignatureService,
        header_size: int,
        max_header_delay_ms: int,
        rx_core: asyncio.Queue,  # (parent digests, round)
        rx_workers: asyncio.Queue,  # (digest, worker_id)
        tx_core: asyncio.Queue,  # Header
        benchmark: bool = False,
    ) -> None:
        self.name = name
        self.signature_service = signature_service
        self.header_size = header_size
        self.max_header_delay = max_header_delay_ms / 1000.0
        self.rx_core = rx_core
        self.rx_workers = rx_workers
        self.tx_core = tx_core
        self.benchmark = benchmark

        self.round: Round = 1
        self.last_parents: List[Digest] = [c.digest() for c in genesis(committee)]
        self.digests: List[Tuple[Digest, WorkerId]] = []
        self.payload_size = 0
        self._m_headers = metrics.counter("primary.headers_proposed")
        self._m_payload_digests = metrics.counter("primary.payload_digests")
        self._m_round = metrics.gauge("primary.round")
        # Round period: seconds between consecutive round advances.  The
        # cert→commit attribution (PR 4) shows commit latency is
        # dominated by protocol cadence — this histogram is the cadence
        # denominator (cert_inserted→commit_trigger ≈ commit depth ×
        # this), so a slow commit path reads directly as either a slow
        # round period (look here) or a starved commit rule (look at
        # consensus.commit_lag_rounds).
        self._m_round_advance = metrics.histogram(
            "primary.round_advance_seconds"
        )
        self._last_advance: Optional[float] = None
        self._mtrace = metrics.trace()

    async def _make_header(self) -> None:
        payload = dict(self.digests)
        self.digests = []
        parents, self.last_parents = self.last_parents, []
        header = await Header.new(
            self.name, self.round, payload, parents, self.signature_service
        )
        log.debug("Created %r", header)
        self._m_headers.inc()
        self._m_payload_digests.inc(len(payload))
        for digest in payload:
            self._mtrace.mark(bytes(digest).hex(), "header")
        if self.benchmark:
            for digest in header.payload:
                # Parsed by the benchmark log parser to attribute batches to
                # rounds (reference proposer.rs:93-97).
                log.info("Created B%d(%r) -> %r", header.round, header.id, digest)
        await self.tx_core.put(header)

    async def run(self) -> None:
        log.debug("Dag starting at round %d", self.round)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.max_header_delay
        core_get = loop.create_task(self.rx_core.get())
        workers_get = loop.create_task(self.rx_workers.get())
        try:
            while True:
                timer_expired = loop.time() >= deadline
                enough_digests = self.payload_size >= self.header_size
                if (timer_expired or enough_digests) and self.last_parents:
                    await self._make_header()
                    self.payload_size = 0
                    deadline = loop.time() + self.max_header_delay

                # With no parent quorum the timer is irrelevant (we cannot
                # propose anyway) — wait purely on the queues instead of
                # busy-spinning on an already-expired deadline.
                timeout = (
                    max(0.0, deadline - loop.time()) if self.last_parents else None
                )
                done, _ = await asyncio.wait(
                    {core_get, workers_get},
                    timeout=timeout,
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if core_get in done:
                    parents, round = core_get.result()
                    core_get = loop.create_task(self.rx_core.get())
                    if round >= self.round:
                        # Advance to the next round.
                        self.round = round + 1
                        self._m_round.set(self.round)
                        now = loop.time()
                        if self._last_advance is not None:
                            self._m_round_advance.observe(
                                now - self._last_advance
                            )
                        self._last_advance = now
                        log.debug("Dag moved to round %d", self.round)
                        self.last_parents = parents
                if workers_get in done:
                    digest, worker_id = workers_get.result()
                    workers_get = loop.create_task(self.rx_workers.get())
                    if _TRACE:
                        log.info("TRACE payload arrived %r", digest)
                    self._mtrace.mark(bytes(digest).hex(), "digest_at_primary")
                    self.payload_size += len(digest)
                    self.digests.append((digest, worker_id))
        finally:
            core_get.cancel()
            workers_get.cancel()
