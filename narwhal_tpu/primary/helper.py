"""Primary Helper: serve CertificatesRequests from our store.

Reference primary/src/helper.rs (71 LoC).
"""

from __future__ import annotations

import asyncio
import logging

from ..config import Committee
from ..network import SimpleSender
from ..utils.serde import Writer
from .messages import PM_CERTIFICATE

log = logging.getLogger("narwhal.primary")


class Helper:
    def __init__(
        self,
        committee: Committee,
        store,
        rx_primaries: asyncio.Queue,  # (digests, requestor)
    ) -> None:
        self.committee = committee
        self.store = store
        self.rx_primaries = rx_primaries
        self.sender = SimpleSender()

    async def run(self) -> None:
        while True:
            digests, requestor = await self.rx_primaries.get()
            try:
                address = self.committee.primary(requestor).primary_to_primary
            except Exception:
                log.warning("Certificates request from unknown authority")
                continue
            for digest in digests:
                raw = self.store.read(bytes(digest))
                if raw is not None:
                    # Stored bytes are the bare certificate; frame it as a
                    # PrimaryMessage::Certificate for the peer's receiver.
                    w = Writer()
                    w.u8(PM_CERTIFICATE)
                    w.raw(raw)
                    self.sender.send(
                        address, w.finish(), msg_type="certificate"
                    )
