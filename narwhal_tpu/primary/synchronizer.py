"""Primary Synchronizer: dependency checks with suspend-on-miss.

Reference primary/src/synchronizer.rs (138 LoC): `missing_payload` (keyed
digest‖worker_id — the comment at 58-68 documents the worker-id-binding
attack this prevents), `get_parents`, `deliver_certificate`.  On a miss the
relevant waiter is notified and the caller suspends processing; the waiter
loops the message back to the Core when the dependency lands in the store.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, List, Optional

from ..config import Committee
from ..crypto import Digest, PublicKey
from ..store import Store
from ..utils.env import env_flag
from .messages import Certificate, Header, genesis

log = logging.getLogger("narwhal.primary")
_TRACE = env_flag("NARWHAL_TRACE")


def payload_key(digest: Digest, worker_id: int) -> bytes:
    """Store key binding a batch digest to the worker id that served it."""
    return bytes(digest) + worker_id.to_bytes(4, "little")


class Synchronizer:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        store: Store,
        tx_header_waiter: asyncio.Queue,
        tx_certificate_waiter: asyncio.Queue,
    ) -> None:
        self.name = name
        self.store = store
        self.tx_header_waiter = tx_header_waiter
        self.tx_certificate_waiter = tx_certificate_waiter
        self.genesis = {c.digest(): c for c in genesis(committee)}

    async def missing_payload(self, header: Header) -> bool:
        """True if some payload batch is unavailable; schedules the fetch.
        We never store markers for our own workers' batches, so our own
        headers skip the check (reference synchronizer.rs:50-56)."""
        if header.author == self.name:
            return False
        missing: Dict[Digest, int] = {}
        for digest, worker_id in header.payload.items():
            if self.store.read(payload_key(digest, worker_id)) is None:
                missing[digest] = worker_id
        if not missing:
            return False
        if _TRACE:
            log.info("TRACE suspend header %r: %d payload missing",
                     header.id, len(missing))
        await self.tx_header_waiter.put(("sync_batches", missing, header))
        return True

    async def get_parents(self, header: Header) -> List[Certificate]:
        """All parent certificates, or [] after scheduling the fetch."""
        missing: List[Digest] = []
        parents: List[Certificate] = []
        for digest in header.parents:
            gen = self.genesis.get(digest)
            if gen is not None:
                parents.append(gen)
                continue
            raw = self.store.read(bytes(digest))
            if raw is None:
                missing.append(digest)
            else:
                parents.append(Certificate.deserialize(raw))
        if not missing:
            return parents
        if _TRACE:
            log.info("TRACE suspend header %r: %d parents missing",
                     header.id, len(missing))
        await self.tx_header_waiter.put(("sync_parents", missing, header))
        return []

    async def deliver_certificate(self, certificate: Certificate) -> bool:
        """True if all ancestors are in the store; else park the certificate
        with the CertificateWaiter (reference synchronizer.rs:122-137)."""
        for digest in certificate.header.parents:
            if digest in self.genesis:
                continue
            if self.store.read(bytes(digest)) is None:
                await self.tx_certificate_waiter.put(certificate)
                return False
        return True
