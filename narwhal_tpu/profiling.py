"""Always-on sampling profiler: whole-process CPU attribution, no probes.

Every perf number this repo trusts so far came from *hand-placed*
instrumentation — the stage/round traces, the crypto ledger's per-site
timers — which can only answer questions someone thought to ask.  The r10
cadence verdict ("72-75% of the round period is the two peer-verify
legs") took a PR of plumbing to establish; a sampling profiler reads the
same fact off the stacks in one bench run, and keeps answering for every
code path nobody instrumented.

Mechanism (:class:`SamplingProfiler`, armed by ``NARWHAL_PROFILE_HZ``,
default ~67 Hz, ``0`` = off):

- a daemon thread wakes ``hz`` times a second and snapshots **all**
  thread stacks via ``sys._current_frames()`` — the same facility the
  loop-stall watchdog uses for its one-shot captures, run continuously.
  67 Hz deliberately avoids aliasing with the protocol's 10/100 ms
  timers (a 100 Hz sampler strobes a 10 ms cadence loop);
- each stack folds into a ``module:function`` frame tuple and lands in a
  stack→count table — the *folded stack* format every flamegraph tool
  eats directly (``profile.folded`` in the snapshot detail);
- self-time per frame (samples where the frame is the leaf) and total
  time (samples where it appears anywhere) aggregate into the
  ``profile.top`` table — the general CPU attribution that must
  independently reproduce the ledger's "verify dominates" finding;
- samples whose leaf is an OS wait (select/epoll, lock waits,
  ``Event.wait``) are counted (``profile.idle_samples``) but excluded
  from self-time: a wall-clock sampler sees parked daemon threads as
  "running" their wait frame, and attributing CPU to ``epoll`` would
  bury the actual compute;
- the MAIN thread (the node's event loop) additionally feeds a bounded
  run-length-encoded timeline of leaf frames (``profile.timeline``:
  ``[start_ts, end_ts, samples, frame]`` runs) — what lets the trace
  exporter draw a poor-man's flame track on each node's Perfetto row,
  time-aligned with the protocol stages.

Cost: one ``sys._current_frames()`` + a fold per tick.  Measured on the
4-node committee A/B (artifacts/trace_profile_r16.json): within noise of
the unprofiled arm at 67 Hz, which is what makes "always on" honest.

Everything exports through the normal metrics registry, so snapshots,
``/metrics.json`` and the bench harnesses pick the series up with zero
extra plumbing; ``NARWHAL_METRICS=0`` disables the export (and
``install_from_env`` then declines to start the thread at all).
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import metrics
from .utils.env import env_float

log = logging.getLogger("narwhal.profiling")

# Leaf code-object names that mean "parked in the OS, not burning CPU".
# A wall-clock sampler charges blocked threads to their wait frame;
# excluding these from SELF time keeps `profile.top` a CPU table.  The
# full stacks still land in the folded output (wall-clock truth).
_IDLE_LEAVES = frozenset({
    "wait", "select", "poll", "epoll", "kqueue", "accept", "recv",
    "recv_into", "read", "readinto", "readline", "sleep", "settrace",
    "_wait_for_tstate_lock", "wait_for", "acquire", "getaddrinfo",
})

# (file basename, function) leaves that block inside a C call the
# sampler cannot see past: ThreadPoolExecutor workers park in the
# C-implemented SimpleQueue.get directly under `_worker`, so the leaf
# reads as the worker loop itself — measured at 52% of committee "self
# time" before this classification (artifacts/trace_profile_r16.json's
# first cut), all of it parked executor threads.
_IDLE_LEAF_SITES = frozenset({
    ("thread.py", "_worker"),
})

# Hard bound on distinct folded stacks kept; past it, new stacks count
# into profile.dropped_stacks instead of growing without bound (deep
# recursive workloads can mint unbounded distinct stacks).
_MAX_STACKS = 8192

_STACK_DEPTH = 48          # frames kept per folded stack (root-truncated)
_TIMELINE_CAP = 4096       # RLE runs kept for the main-thread leaf series


def _frame_label(code) -> str:
    """``file:function`` with the path collapsed to its basename — short
    enough to fold, unique enough to read (``core.py:sanitize_header``)."""
    return f"{os.path.basename(code.co_filename)}:{code.co_name}"


class SamplingProfiler:
    """Samples all thread stacks at ``hz`` from a daemon thread."""

    def __init__(
        self,
        hz: float,
        reg: Optional[metrics.Registry] = None,
    ) -> None:
        self.hz = hz
        self.interval_s = 1.0 / hz
        self.registry = reg if reg is not None else metrics.registry()
        # folded stack (root→leaf tuple of labels, thread-name prefixed)
        # -> sample count
        self._stacks: Dict[Tuple[str, ...], int] = {}
        # label -> [self_samples, total_samples] over NON-idle samples
        self._frames: Dict[str, List[int]] = {}
        # Main-thread leaf RLE: [start_ts, end_ts, samples, label]
        self._timeline: List[list] = []
        self._main_tid = threading.main_thread().ident
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        r = self.registry
        self._m_samples = r.counter("profile.samples")
        self._m_idle = r.counter("profile.idle_samples")
        self._m_dropped = r.counter("profile.dropped_stacks")
        self._m_threads = r.gauge("profile.threads")
        self._m_hz = r.gauge("profile.hz")
        self._m_hz.set(hz)
        r.detail_fn("profile.top", lambda: self.top_table())
        r.detail_fn("profile.folded", lambda: self.folded())
        r.detail_fn("profile.timeline", lambda: list(self._timeline))

    # -- sampling (daemon thread) ---------------------------------------------

    def start(self) -> "SamplingProfiler":
        self._thread = threading.Thread(
            target=self._run, name="sampling-profiler", daemon=True
        )
        self._thread.start()
        log.info("Sampling profiler armed at %.1f Hz", self.hz)
        return self

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval_s + 1)
            self._thread = None

    def _run(self) -> None:
        me = threading.get_ident()
        next_tick = time.monotonic()
        while not self._stop.is_set():
            next_tick += self.interval_s
            delay = next_tick - time.monotonic()
            if delay > 0:
                if self._stop.wait(delay):
                    break
            else:
                # Fell behind (suspended, loaded core): re-anchor rather
                # than burst-sample to catch up — bursts would weight one
                # instant as many ticks.
                next_tick = time.monotonic()
            try:
                self.sample_once(exclude={me})
            except Exception:
                # A racing thread teardown mid-introspection must never
                # kill the profiler for the rest of the run.
                log.exception("profiler sample failed")

    def sample_once(self, exclude: Optional[set] = None) -> None:
        """One sampling tick over every live thread (callable directly in
        tests; the daemon thread excludes itself)."""
        now = time.time()
        names = {t.ident: t.name for t in threading.enumerate()}
        frames = sys._current_frames()
        self._m_threads.set(len(frames) - (1 if exclude else 0))
        for tid, frame in frames.items():
            if exclude and tid in exclude:
                continue
            stack: List[str] = []
            depth = 0
            f = frame
            leaf_label = None
            while f is not None and depth < _STACK_DEPTH:
                label = _frame_label(f.f_code)
                if leaf_label is None:
                    leaf_label = label
                    leaf_name = f.f_code.co_name
                    leaf_file = os.path.basename(f.f_code.co_filename)
                stack.append(label)
                f = f.f_back
                depth += 1
            if leaf_label is None:
                continue
            stack.reverse()  # root → leaf, flamegraph orientation
            self._m_samples.inc()
            idle = (
                leaf_name in _IDLE_LEAVES
                or (leaf_file, leaf_name) in _IDLE_LEAF_SITES
            )
            if idle:
                self._m_idle.inc()
            key = (names.get(tid, f"tid-{tid}"), *stack)
            cnt = self._stacks.get(key)
            if cnt is not None:
                self._stacks[key] = cnt + 1
            elif len(self._stacks) < _MAX_STACKS:
                self._stacks[key] = 1
            else:
                self._m_dropped.inc()
            if not idle:
                seen = set()
                for label in stack:
                    if label in seen:
                        continue  # recursion: one total credit per sample
                    seen.add(label)
                    rec = self._frames.get(label)
                    if rec is None:
                        rec = self._frames[label] = [0, 0]
                    rec[1] += 1
                self._frames[leaf_label][0] += 1
            if tid == self._main_tid:
                self._timeline_push(now, leaf_label)

    def _timeline_push(self, now: float, label: str) -> None:
        tl = self._timeline
        if tl and tl[-1][3] == label:
            tl[-1][1] = now
            tl[-1][2] += 1
            return
        if len(tl) >= _TIMELINE_CAP:
            # FIFO: keep the most recent window (what a post-mortem trace
            # export wants to see).
            del tl[: _TIMELINE_CAP // 4]
        tl.append([now, now, 1, label])

    # -- export ---------------------------------------------------------------

    def folded(self, limit: int = 2000) -> str:
        """Folded-stack text (``thread;frame;frame… count`` per line) —
        pipe straight into flamegraph.pl / speedscope / inferno.  Top
        ``limit`` stacks by count."""
        rows = sorted(
            self._stacks.items(), key=lambda kv: kv[1], reverse=True
        )[:limit]
        return "\n".join(
            ";".join(stack) + f" {count}" for stack, count in rows
        )

    def top_table(self, n: int = 25) -> List[dict]:
        """Top-``n`` frames by self-time (non-idle samples where the frame
        is the leaf), with total (anywhere-on-stack) alongside — the
        sampling analog of a profiler's self/cumulative columns."""
        busy = max(
            1, self._m_samples.value - self._m_idle.value
        )
        rows = sorted(
            self._frames.items(), key=lambda kv: kv[1][0], reverse=True
        )[:n]
        return [
            {
                "frame": label,
                "self": self_n,
                "total": total_n,
                "self_frac": round(self_n / busy, 4),
            }
            for label, (self_n, total_n) in rows
            if self_n > 0
        ]


def install_from_env() -> Optional[SamplingProfiler]:
    """Arm the profiler when ``NARWHAL_PROFILE_HZ`` > 0 *and* the metrics
    registry is live (a stubbed registry would sample into no-ops —
    all cost, no data).  node/main.py calls this once per process."""
    hz = env_float("NARWHAL_PROFILE_HZ")
    if not hz or hz <= 0 or not metrics.registry().enabled:
        return None
    return SamplingProfiler(hz).start()
