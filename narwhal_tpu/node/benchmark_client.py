"""Open-loop benchmark load generator.

Reference node/src/benchmark_client.rs (158 LoC): send `rate` tx/s in
PRECISION(=20) bursts per second over one connection; the first tx of each
burst is a 'sample' (byte0=0 + u64 counter) logged for end-to-end latency
measurement, the rest are filler (byte0=1 + random u64), all zero-padded to
`size`.  Waits for all peer transaction sockets to accept before starting.

    python -m narwhal_tpu.node.benchmark_client 127.0.0.1:7001 \
        --size 512 --rate 50000 --nodes 127.0.0.1:7001 127.0.0.1:7006 ...
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import random
import sys
import time

from ..network.framing import parse_address, write_frame

log = logging.getLogger("narwhal.client")

PRECISION = 20  # bursts per second
BURST_DURATION = 1.0 / PRECISION


async def wait_for(nodes) -> None:
    """Block until every node's transaction socket accepts."""
    log.info("Waiting for all nodes to be online...")
    for address in nodes:
        host, port = parse_address(address)
        while True:
            try:
                _, w = await asyncio.open_connection(host, port)
                w.close()
                break
            except OSError:
                await asyncio.sleep(0.1)


async def send_load(target: str, size: int, rate: int, sample_offset: int = 0) -> None:
    if size < 9:
        raise ValueError("Transaction size must be at least 9 bytes")
    burst = max(1, rate // PRECISION)
    host, port = parse_address(target)
    _, writer = await asyncio.open_connection(host, port)
    log.info("Start sending transactions")
    log.info("Transactions size: %d B", size)
    log.info("Transactions rate: %d tx/s", rate)

    # Distinct offsets keep sample ids globally unique across clients so the
    # log parser's send→commit join is unambiguous.
    counter = sample_offset
    rng = random.Random(sample_offset)
    pad = bytes(size - 9)
    loop = asyncio.get_running_loop()
    deadline = loop.time() + BURST_DURATION
    while True:
        for x in range(burst):
            if x == 0:
                # One sample tx per burst — sent first so its logged send
                # time excludes the burst's own queueing (reference
                # benchmark_client.rs:258-271).
                tx = b"\x00" + counter.to_bytes(8, "little") + pad
                log.info("Sending sample transaction %d", counter)
            else:
                tx = b"\x01" + rng.getrandbits(64).to_bytes(8, "little") + pad
            await write_frame(writer, tx)
        counter += 1
        now = loop.time()
        if now > deadline:
            log.warning("Transaction rate too high for this client")
        else:
            await asyncio.sleep(deadline - now)
        deadline += BURST_DURATION


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="Narwhal benchmark client")
    parser.add_argument("target", help="ip:port of the worker tx socket")
    parser.add_argument("--size", type=int, required=True)
    parser.add_argument("--rate", type=int, required=True)
    parser.add_argument("--nodes", nargs="*", default=[])
    parser.add_argument("--sample-offset", type=int, default=0)
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s.%(msecs)03dZ %(levelname)s %(name)s %(message)s",
        datefmt="%Y-%m-%dT%H:%M:%S",
        stream=sys.stderr,
        force=True,
    )

    async def run() -> None:
        await wait_for(args.nodes or [args.target])
        await send_load(args.target, args.size, args.rate, args.sample_offset)

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
