"""Open-loop benchmark load generator.

Reference node/src/benchmark_client.rs (158 LoC): send `rate` tx/s in
PRECISION(=20) bursts per second over one connection; the first tx of each
burst is a 'sample' (byte0=0 + u64 counter) logged for end-to-end latency
measurement, the rest are filler (byte0=1 + random u64), all zero-padded to
`size`.  Waits for all peer transaction sockets to accept before starting.

    python -m narwhal_tpu.node.benchmark_client 127.0.0.1:7001 \
        --size 512 --rate 50000 --nodes 127.0.0.1:7001 127.0.0.1:7006 ...
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import struct
import sys

from ..network.framing import parse_address

log = logging.getLogger("narwhal.client")

PRECISION = 20  # bursts per second
BURST_DURATION = 1.0 / PRECISION


async def wait_for(nodes) -> None:
    """Block until every node's transaction socket accepts."""
    log.info("Waiting for all nodes to be online...")
    for address in nodes:
        host, port = parse_address(address)
        while True:
            try:
                _, w = await asyncio.open_connection(host, port)
                w.close()
                break
            except OSError:
                await asyncio.sleep(0.1)


async def send_load(target: str, size: int, rate: int, sample_offset: int = 0) -> None:
    if size < 9:
        raise ValueError("Transaction size must be at least 9 bytes")
    burst = max(1, rate // PRECISION)
    host, port = parse_address(target)
    from ..network.framing import STREAM_LIMIT, tune_writer

    _, writer = await asyncio.open_connection(host, port, limit=STREAM_LIMIT)
    tune_writer(writer)
    log.info("Start sending transactions")
    log.info("Transactions size: %d B", size)
    log.info("Transactions rate: %d tx/s", rate)

    # The whole burst is ONE pre-framed buffer, patched in place and written
    # with a single syscall: at 50k tx/s the per-tx Python path would eat
    # the core the committee shares.  Layout per tx: [u32 len][flag][u64][pad].
    # Distinct offsets keep sample ids globally unique across clients so the
    # log parser's send→commit join is unambiguous.
    import numpy as np

    stride = 4 + size
    template = bytearray(
        struct.pack("<I", size) + b"\x01" + bytes(8) + bytes(size - 9)
    ) * burst
    template[4] = 0  # tx 0 of every burst is the sample (byte0 = 0)
    buf = np.frombuffer(template, dtype=np.uint8)
    # Byte positions of every tx's u64 field (offset 5 within its slot).
    u64_pos = (
        np.arange(burst)[:, None] * stride + 5 + np.arange(8)[None, :]
    ).ravel()
    filler_pos = u64_pos[8:]  # tx 0's u64 holds the sample counter
    rng = np.random.default_rng(sample_offset or None)

    counter = sample_offset
    loop = asyncio.get_running_loop()
    deadline = loop.time() + BURST_DURATION
    while True:
        template[5:13] = counter.to_bytes(8, "little")
        if burst > 1:
            buf[filler_pos] = rng.integers(
                0, 256, size=filler_pos.size, dtype=np.uint8
            )
        # Sample-send log BEFORE the write, so its timestamp excludes the
        # burst's own queueing (reference benchmark_client.rs:258-262).
        log.info("Sending sample transaction %d", counter)
        try:
            writer.write(bytes(template))
            await writer.drain()
        except OSError:
            # The worker's tx socket went away — at a bench window's end
            # the harness tears the committee down before the clients, and
            # on a loaded host this client can observe the closed socket
            # before its own SIGTERM lands.  An open-loop load generator
            # outliving its server is a normal shutdown, not an error (a
            # traceback here would hard-fail the log parser's error scan).
            log.info("Worker connection closed; stopping load")
            return
        counter += 1
        now = loop.time()
        if now > deadline:
            log.warning("Transaction rate too high for this client")
        else:
            await asyncio.sleep(deadline - now)
        deadline += BURST_DURATION


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="Narwhal benchmark client")
    parser.add_argument("target", help="ip:port of the worker tx socket")
    parser.add_argument("--size", type=int, required=True)
    parser.add_argument("--rate", type=int, required=True)
    parser.add_argument("--nodes", nargs="*", default=[])
    parser.add_argument("--sample-offset", type=int, default=0)
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s.%(msecs)03dZ %(levelname)s %(name)s %(message)s",
        datefmt="%Y-%m-%dT%H:%M:%S",
        stream=sys.stderr,
        force=True,
    )

    async def run() -> None:
        await wait_for(args.nodes or [args.target])
        await send_load(args.target, args.size, args.rate, args.sample_offset)

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
