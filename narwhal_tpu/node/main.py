"""Node CLI (reference node/src/main.rs, 141 LoC).

    python -m narwhal_tpu.node generate_keys --filename keys.json
    python -m narwhal_tpu.node run --keys k.json --committee c.json \
        [--parameters p.json] --store db primary
    python -m narwhal_tpu.node run ... worker --id 0
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import signal
import sys

from ..analysis.watchdog import install_from_env as install_loop_watchdog
from ..config import Committee, Parameters, export_keypair, load_keypair
from ..crypto import KeyPair
from ..utils.env import env_flag, env_float, env_str
from ..utils.tasks import spawn
from .node import spawn_primary_node, spawn_worker_node


class JsonLogFormatter(logging.Formatter):
    """One-line-JSON log records: {ts, level, logger, msg, node} (+exc).

    ``ts`` is unix epoch seconds (float) so log events join directly
    against the metrics time-series and scraper timeline, which all use
    ``time.time()`` — no timestamp re-parsing.  ``node`` identifies the
    process in a committee-wide merged stream (role + worker id + key
    prefix).  HealthMonitor anomaly lines come through here too, which is
    the point: one machine-joinable event stream per node.
    """

    def __init__(self, node_id: str) -> None:
        super().__init__()
        self.node_id = node_id

    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "ts": round(record.created, 3),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
            "node": self.node_id,
        }
        if record.exc_info:
            entry["exc"] = self.formatException(record.exc_info)
        return json.dumps(entry)


def setup_logging(
    verbosity: int,
    level_name: str | None = None,
    json_logs: bool = False,
    node_id: str = "",
) -> None:
    # Explicit --log-level (or the NARWHAL_LOG env var) wins over -v; the
    # level is applied to the whole `narwhal.*` hierarchy — every module
    # logs under it (narwhal.worker, narwhal.primary, narwhal.consensus,
    # narwhal.network, narwhal.node, narwhal.client, narwhal.metrics).
    level_name = level_name or env_str("NARWHAL_LOG")
    if level_name:
        level = getattr(logging, level_name.upper(), None)
        if not isinstance(level, int):
            raise SystemExit(f"unknown log level {level_name!r}")
    else:
        level = [logging.ERROR, logging.INFO, logging.DEBUG][min(verbosity, 2)]
    # Millisecond timestamps: the benchmark log parser depends on them
    # (reference main.rs:54-55).  --log-json swaps the formatter for the
    # machine-joinable one-line-JSON form; the human format stays the
    # default (and is what the bench log parser requires).
    logging.basicConfig(
        level=level,
        format="%(asctime)s.%(msecs)03dZ %(levelname)s %(name)s %(message)s",
        datefmt="%Y-%m-%dT%H:%M:%S",
        stream=sys.stderr,
        force=True,
    )
    if json_logs:
        formatter = JsonLogFormatter(node_id)
        for handler in logging.getLogger().handlers:
            handler.setFormatter(formatter)
    logging.getLogger("narwhal").setLevel(level)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="narwhal-tpu-node",
        description="A TPU-native implementation of Narwhal and Tusk.",
    )
    parser.add_argument("-v", action="count", default=1, dest="verbosity")
    parser.add_argument(
        "--log-level",
        choices=["debug", "info", "warning", "error", "critical"],
        default=None,
        help="Log level for the whole narwhal.* hierarchy (overrides -v; "
        "the NARWHAL_LOG env var is the equivalent knob for harnesses "
        "that cannot edit the command line)",
    )
    parser.add_argument(
        "--log-json",
        action="store_true",
        default=False,
        help="Emit one-line-JSON log records ({ts, level, logger, msg, "
        "node}, ts = unix epoch) instead of the human format, so anomaly "
        "events and logs join machine-side with the metrics time-series. "
        "The bench log parser requires the human default.",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    gen = sub.add_parser("generate_keys", help="Print a fresh keypair to file")
    gen.add_argument("--filename", required=True)

    run = sub.add_parser("run", help="Run a node")
    run.add_argument("--keys", required=True)
    run.add_argument("--committee", required=True)
    run.add_argument("--parameters")
    run.add_argument("--store", required=True)
    run.add_argument("--benchmark", action="store_true", default=False)
    run.add_argument(
        "--experimental-consensus-kernel",
        action="store_true",
        default=False,
        help="EXPERIMENTAL: run Tusk's order_leaders on the JAX device "
        "kernel (device-resident window, W-bit commit fetch).  Correct "
        "(golden-tested cert-for-cert) but measured SLOWER than the "
        "Python walk end-to-end on every host benchmarked so far "
        "(artifacts/consensus_bench_r06.json) — excluded from the "
        "default benchmark flag set until a host-local chip measures a "
        "win; see README.md 'Consensus kernel'",
    )
    run.add_argument(
        "--crypto-backend",
        choices=["cpu", "tpu", "jax"],
        default=None,
        help="Signature verification backend: cpu (serial) or jax/tpu "
        "(the batched device verifier — `jax` runs on whatever platform "
        "JAX has, incl. jax-cpu).  Default: the NARWHAL_CRYPTO_BACKEND "
        "env knob, else cpu.  A jax/tpu request that cannot import "
        "fails AT BOOT unless NARWHAL_CRYPTO_BACKEND_STRICT=0.",
    )
    run.add_argument(
        "--cert-sig-scheme",
        choices=["individual", "halfagg"],
        default=None,
        help="Certificate signature scheme: individual (2f+1 ed25519 "
        "vote signatures per certificate, the default) or halfagg "
        "(ed25519 half-aggregation: the quorum folds into ONE 32*(q+1)-"
        "byte blob verified by a single multiexp equation — ~44%% fewer "
        "certificate signature bytes and 1 verify op per certificate "
        "instead of 2f+1).  Default: the NARWHAL_CERT_SIG_SCHEME env "
        "knob, else individual.  Committee-wide — a cross-scheme frame "
        "refuses at decode, a cross-scheme checkpoint refuses at boot.",
    )
    run.add_argument(
        "--commit-rule",
        choices=["classic", "lowdepth", "multileader"],
        default=None,
        help="Consensus commit rule: classic (Tusk, depth-3 commits on "
        "f+1 support), lowdepth (Mysticeti-style direct commit on "
        "2f+1 support one round after the leader), or multileader "
        "(Mysticeti multi-slot: 3 round-salted leader slots per even "
        "round, the commit anchors on the lowest supported slot) — each "
        "non-classic rule judged against its own golden oracle.  "
        "Default: the NARWHAL_COMMIT_RULE env knob, else classic.  "
        "Committee-wide — every node must run the same rule, and a "
        "checkpoint written under one rule refuses to restore under "
        "another.",
    )
    run.add_argument(
        "--metrics-path",
        default=None,
        help="Write a JSON metrics snapshot (atomic rewrite) to this path "
        "every --metrics-interval seconds, plus a final one at shutdown. "
        "Unset = no snapshot file.",
    )
    run.add_argument(
        "--metrics-interval",
        type=float,
        default=1.0,
        help="Seconds between metrics snapshot rewrites (default 1.0)",
    )
    run.add_argument(
        "--metrics-port",
        type=int,
        default=0,
        help="Serve Prometheus text metrics on this port (GET /metrics; "
        "GET /metrics.json for the snapshot form, ?trace=0 to omit the "
        "stage-trace table; GET /healthz for the 200/503 anomaly-rule "
        "verdict).  0 = disabled.",
    )
    run.add_argument(
        "--fault-plan",
        default=None,
        help="FAULT INJECTION: path to a Byzantine plan JSON "
        "({behaviors, seed, withhold_targets, replay_interval_ms, "
        "flood_interval_ms, garbage_bytes}).  On a primary it swaps the "
        "Proposer/Core for their Byzantine wrappers "
        "(narwhal_tpu/faults/byzantine.py); on a worker it swaps the "
        "BatchMaker/Helper and spawns the sync flooder "
        "(narwhal_tpu/faults/byzantine_worker.py) — each role acts only "
        "on its own plane's behaviors, so one plan file serves a whole "
        "authority.  The NARWHAL_FAULT_PLAN env var is the equivalent "
        "knob for harnesses.  Never set this on a node you care about: "
        "it makes the node ATTACK its committee.",
    )
    run.add_argument(
        "--health-interval",
        type=float,
        default=None,
        help="Seconds between health-rule evaluations (default 1.0, or "
        "the NARWHAL_HEALTH_INTERVAL env var).  NARWHAL_HEALTH=0 "
        "disables the monitor entirely; rule thresholds are tuned via "
        "NARWHAL_HEALTH_* env vars (see README 'Observability').",
    )
    runsub = run.add_subparsers(dest="role", required=True)
    runsub.add_parser("primary", help="Run a single primary")
    wrk = runsub.add_parser("worker", help="Run a single worker")
    wrk.add_argument("--id", type=int, required=True)

    warm = sub.add_parser(
        "prewarm",
        help="Compile the device kernels for a committee's shapes into the "
        "persistent XLA cache, then exit.  Run this once before launching "
        "TPU-flagged nodes: their boot-time warmup then loads from cache "
        "in seconds instead of compiling for minutes (and a bench harness "
        "never has to kill a node mid-compile — see the verify-skill "
        "gotcha about wedged chip grants).",
    )
    warm.add_argument("--committee", required=True)
    warm.add_argument(
        "--experimental-consensus-kernel",
        action="store_true",
        default=False,
    )
    warm.add_argument("--gc-depth", type=int, default=None)
    warm.add_argument(
        "--skip-verify",
        action="store_true",
        default=False,
        help="Skip the verify-kernel warmup (e.g. consensus-kernel-only "
        "runs keep CPU crypto and never touch that cache; each cold "
        "verify shape costs minutes of compile over a tunnel)",
    )

    args = parser.parse_args(argv)

    if args.command == "generate_keys":
        export_keypair(KeyPair.generate(), args.filename)
        return 0

    if args.command == "prewarm":
        setup_logging(args.verbosity, args.log_level)
        log = logging.getLogger("narwhal.node")
        committee = Committee.load(args.committee)
        if not args.skip_verify:
            from ..crypto import backend as crypto_backend
            from .node import derive_max_claims

            crypto_backend.set_backend("tpu")
            backend = crypto_backend.get_backend()
            log.info("Prewarming tpu verify backend...")
            backend.warmup(max_claims=derive_max_claims(committee))
            log.info("Verify backend ready")
        if args.experimental_consensus_kernel:
            from ..ops.reachability import KernelTusk

            gc_depth = (
                args.gc_depth
                if args.gc_depth is not None
                else Parameters().gc_depth
            )
            log.info("Prewarming consensus kernel...")
            KernelTusk(committee, gc_depth).prewarm()
            log.info("Consensus kernel ready")
        return 0

    # Keypair first: the JSON log formatter stamps every record with a
    # node id derived from it (role + worker id + key prefix).
    keypair = load_keypair(args.keys)
    node_id = f"{args.role}-{keypair.name.encode_base64()[:8]}"
    if args.role == "worker":
        node_id = f"{args.role}{args.id}-{keypair.name.encode_base64()[:8]}"
    setup_logging(
        args.verbosity, args.log_level, json_logs=args.log_json,
        node_id=node_id,
    )
    committee = Committee.load(args.committee)
    parameters = (
        Parameters.load(args.parameters) if args.parameters else Parameters()
    )
    parameters.log(logging.getLogger("narwhal.node"))
    # Crypto backend selection happens HERE, at boot (CLI flag, else the
    # NARWHAL_CRYPTO_BACKEND env knob, else cpu): a jax/tpu request whose
    # import fails raises NOW with the import error instead of deep in
    # the first verify burst (NARWHAL_CRYPTO_BACKEND_STRICT=0 downgrades
    # that to a logged cpu fallback).  The warmup that pre-compiles the
    # burst shapes runs in spawn_primary_node, against whatever backend
    # this call selected.
    from ..crypto import backend as crypto_backend

    requested = crypto_backend.set_backend_from_env(args.crypto_backend)
    logging.getLogger("narwhal.node").info(
        "Crypto backend: %s (requested %s)",
        crypto_backend.get_backend().name, requested,
    )
    # Commit rule resolves the same way (CLI > NARWHAL_COMMIT_RULE >
    # classic) and is logged at boot so a bench arm's logs prove which
    # rule actually ran; garbage raises HERE, before any socket binds.
    from ..consensus import resolve_commit_rule

    logging.getLogger("narwhal.node").info(
        "Commit rule: %s", resolve_commit_rule(args.commit_rule)
    )
    # Certificate-signature scheme: same precedence (CLI >
    # NARWHAL_CERT_SIG_SCHEME > individual), pinned process-wide before
    # any certificate is assembled or decoded; garbage raises here.
    from ..crypto import aggregate as cert_sig

    cert_sig.set_scheme(cert_sig.resolve_scheme(args.cert_sig_scheme))
    logging.getLogger("narwhal.node").info(
        "Certificate signature scheme: %s", cert_sig.scheme()
    )

    async def run_node() -> None:
        # Graceful SIGTERM: set the stop event from the loop (raising out of
        # a sync signal handler would interrupt arbitrary tasks and litter
        # the logs with spurious exceptions the bench parser flags).
        stop = asyncio.Event()
        asyncio.get_running_loop().add_signal_handler(signal.SIGTERM, stop.set)

        # Observability plane: periodic JSON snapshots and/or the
        # Prometheus endpoint.  Both read the same per-process registry.
        from .. import metrics as _metrics

        snapshot_task = None
        metrics_server = None
        health_task = None
        flight_task = None
        # Loop-stall watchdog (NARWHAL_LOOP_WATCHDOG_MS): measured proof
        # that no callback holds this node's event loop — the runtime
        # half of the narwhal-lint invariant suite.
        loop_watchdog = install_loop_watchdog()
        # Sampling profiler (NARWHAL_PROFILE_HZ, default ~67 Hz): all-
        # thread stack samples folded into the `profile.*` series —
        # general CPU attribution with no hand-placed probes.
        from .. import profiling as _profiling

        profiler_thread = _profiling.install_from_env()
        # Flight recorder: the registry-attached ring records landmarks
        # from everywhere; this process stamps its identity on it (dump
        # filenames + /debug/flight) and runs the per-tick delta sampler.
        flight = _metrics.registry().flight
        flight.node_id = node_id
        if flight.enabled:
            flight_task = spawn(flight.run(), name="flight-ticks")
        if args.metrics_path:
            snapshot_task = spawn(
                _metrics.SnapshotWriter(
                    _metrics.registry(),
                    args.metrics_path,
                    interval_s=args.metrics_interval,
                ).run(),
                name="metrics-snapshot",
            )
        # Live health: always on when metrics are (cost: one rule sweep
        # per interval).  Attached to the registry so snapshots carry a
        # `health` section and /healthz answers from it.
        if _metrics.registry().enabled and env_flag("NARWHAL_HEALTH"):
            monitor = _metrics.HealthMonitor(
                _metrics.registry(), interval_s=args.health_interval
            )
            _metrics.registry().health = monitor
            health_task = spawn(monitor.run(), name="health-monitor")
        if args.metrics_port:
            metrics_server = await _metrics.MetricsServer.spawn(
                _metrics.registry(), args.metrics_port
            )

        # One plan file serves a whole authority: each role acts only on
        # its own plane's behaviors (primary.py / worker.py filter via
        # primary_behaviors()/worker_behaviors()).
        fault_plan = None
        plan_path = args.fault_plan or env_str("NARWHAL_FAULT_PLAN")
        if plan_path:
            from ..faults.byzantine import ByzantinePlan

            fault_plan = ByzantinePlan.load(plan_path)
            active = (
                fault_plan.primary_behaviors()
                if args.role == "primary"
                else fault_plan.worker_behaviors()
            )
            if active:
                logging.getLogger("narwhal.node").warning(
                    "FAULT INJECTION ACTIVE: byzantine %s behaviors %s",
                    args.role, sorted(active),
                )

        if args.role == "primary":
            node = await spawn_primary_node(
                keypair,
                committee,
                parameters,
                store_path=f"{args.store}/store.log",
                benchmark=args.benchmark,
                use_kernel=args.experimental_consensus_kernel,
                fault_plan=fault_plan,
                commit_rule=args.commit_rule,
            )
        else:
            node = await spawn_worker_node(
                keypair,
                args.id,
                committee,
                parameters,
                store_path=f"{args.store}/store.log",
                benchmark=args.benchmark,
                fault_plan=fault_plan,
            )
        try:
            await stop.wait()  # run until SIGTERM/SIGINT
            # Logged BEFORE teardown: a node whose log simply stops is
            # indistinguishable from a wedged event loop — this line is
            # what tells a fault-suite post-mortem "shutdown was asked
            # for" from "the node went dark".
            logging.getLogger("narwhal.node").info(
                "Shutdown signal received; tearing down"
            )
            # SIGTERM is one of the flight recorder's dump triggers: the
            # ring written here is the node's own account of its last
            # seconds, independent of any scraper having been attached.
            flight.record("shutdown", signal="SIGTERM")
            flight.dump("sigterm")
        finally:
            await node.shutdown()
            if metrics_server is not None:
                await metrics_server.shutdown()
            if health_task is not None:
                health_task.cancel()
                await asyncio.gather(health_task, return_exceptions=True)
            if snapshot_task is not None:
                # Cancellation triggers the writer's final flush, so the
                # snapshot on disk covers the whole run.
                snapshot_task.cancel()
                await asyncio.gather(snapshot_task, return_exceptions=True)
            if flight_task is not None:
                flight_task.cancel()
                await asyncio.gather(flight_task, return_exceptions=True)
            if loop_watchdog is not None:
                await loop_watchdog.shutdown()
            if profiler_thread is not None:
                profiler_thread.shutdown()

    # NARWHAL_FAULTHANDLER_S=<seconds>: C-level watchdog that dumps every
    # thread's stack to stderr each interval — it fires even when the
    # event loop is wedged in CPU-bound Python (where nothing above the
    # loop can log), which is exactly the state a fault-suite post-mortem
    # needs to see.  Debug aid; off by default.
    interval = env_float("NARWHAL_FAULTHANDLER_S")
    if interval and interval > 0:
        import faulthandler

        faulthandler.dump_traceback_later(interval, repeat=True)

    # NARWHAL_PROFILE=<dir>: cProfile the whole node, dumping stats on
    # SIGTERM (the harness sends SIGTERM before SIGKILL for this reason).
    profile_dir = env_str("NARWHAL_PROFILE")
    profiler = None
    if profile_dir:
        import cProfile

        profiler = cProfile.Profile()
        profiler.enable()

    try:
        asyncio.run(run_node())
    except KeyboardInterrupt:
        pass
    finally:
        if profiler is not None:
            profiler.disable()
            os.makedirs(profile_dir, exist_ok=True)
            role = args.role if args.command == "run" else "node"
            profiler.dump_stats(
                os.path.join(profile_dir, f"{role}-{os.getpid()}.prof")
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
