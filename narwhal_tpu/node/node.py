"""Node assembly: wire Primary + Consensus (+ application sink), or a Worker.

Reference node/src/main.rs:69-141: `run … primary` spawns the Primary and
the Consensus task joined by channels (the consensus output loops back to the
primary's GarbageCollector); `run … worker --id N` spawns a Worker;
`analyze()` is the application layer stub that consumes committed
certificates.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable, List, Optional

from .. import metrics
from ..config import Committee, Parameters, WorkerId
from ..utils.env import env_int, env_str
from ..utils.tasks import spawn
from ..consensus import Consensus
from ..crypto import KeyPair
from ..primary import Primary
from ..store import Store
from ..worker import Worker

log = logging.getLogger("narwhal.node")

CHANNEL_CAPACITY = 1_000


def derive_max_claims(committee: Committee) -> int:
    """Largest claim batch a Core burst can produce: the max messages
    per verify dispatch (DRAIN_LIMIT, or NARWHAL_VERIFY_BATCH_MAX when
    the accumulation window coalesces several drains into one dispatch),
    each a certificate carrying its header claim plus one quorum of vote
    claims.  Worst case is the LARGEST vote set that can form a quorum
    (smallest stakes first), not the smallest.  Shared between node boot
    and the bench harness's device pre-warm step so both compile exactly
    the same pad shapes."""
    from ..primary.core import Core
    from ..utils.env import env_float, env_int

    max_items = Core.DRAIN_LIMIT
    if env_float("NARWHAL_VERIFY_BATCH_WINDOW_MS") > 0:
        max_items = max(max_items, env_int("NARWHAL_VERIFY_BATCH_MAX"))
    stakes = sorted(a.stake for a in committee.authorities.values())
    acc, worst_votes = 0, 0
    for s in stakes:
        acc += s
        worst_votes += 1
        if acc >= committee.quorum_threshold():
            break
    return max_items * (worst_votes + 1)


class PrimaryNode:
    def __init__(self) -> None:
        self.primary: Optional[Primary] = None
        self.tasks: List[asyncio.Task] = []
        self.store: Optional[Store] = None
        # The Consensus instance, retained so in-process harnesses (the
        # simulation committee) can flush/close its audit segment at
        # quiesce — a subprocess node does this on SIGTERM instead.
        self.consensus = None

    async def shutdown(self) -> None:
        for task in self.tasks:
            task.cancel()
        if self.primary is not None:
            await self.primary.shutdown()
        await asyncio.gather(*self.tasks, return_exceptions=True)
        if self.store is not None:
            self.store.close()


async def spawn_primary_node(
    keypair: KeyPair,
    committee: Committee,
    parameters: Parameters,
    store_path: Optional[str] = None,
    benchmark: bool = False,
    on_commit: Optional[Callable] = None,
    use_kernel: bool = False,
    fault_plan=None,
    audit_path: Optional[str] = None,
    store: Optional[Store] = None,
    consensus_cls=None,
    replay_persisted: bool = False,
    channel_capacity: Optional[int] = None,
    commit_rule: Optional[str] = None,
) -> PrimaryNode:
    """Primary + Consensus pair with the GC feedback loop.  `on_commit`
    (sync callable) is the application layer — the reference's `analyze()`
    stub (main.rs:137-141).

    ``fault_plan`` wires the Byzantine Proposer/Core wrappers (fault
    suite); ``audit_path`` (default: the ``NARWHAL_CONSENSUS_AUDIT`` env
    var) makes Consensus append its insert/commit audit segment for the
    golden-oracle safety replay.

    Injectable wiring for in-process harnesses (the simulation committee
    boots dozens of these on one loop): ``store`` hands the node an
    existing Store object (a sim crash/restart preserves the in-memory
    store across incarnations the way a SIGKILL preserves the on-disk
    one); ``consensus_cls`` swaps the Consensus runner (planted-mutation
    arms); ``replay_persisted`` forces the boot-time certificate replay
    even without a ``store_path`` (the retained-store restart needs it)."""
    node = PrimaryNode()
    if audit_path is None:
        audit_path = env_str("NARWHAL_CONSENSUS_AUDIT") or None
    loop = asyncio.get_running_loop()
    node.store = Store(store_path) if store is None else store

    # If the TPU verify backend is selected, compile/cache-load the kernel
    # for the live burst shapes BEFORE joining the committee: the first
    # device call can cost tens of seconds of XLA compile, which must not
    # land on the first certificate's critical path.
    from ..crypto import backend as crypto_backend

    backend = crypto_backend.get_backend()
    if hasattr(backend, "warmup"):
        # Warm every pad shape up to the worst-case burst so no live burst
        # hits XLA compile (sizing rationale in derive_max_claims).
        log.info("Warming up %s verify backend...", backend.name)
        backend.warmup(max_claims=derive_max_claims(committee))
        log.info("Verify backend %s ready", backend.name)

    # One capacity for all three channels: the env knob (declared
    # NARWHAL_CHANNEL_CAPACITY, sweepable by the knee matrix) unless the
    # harness passed an explicit override.  Before the knob existed,
    # tx_new_certificates silently ignored ``channel_capacity`` by
    # reading the module constant instead of ``cap``.
    cap = (
        env_int("NARWHAL_CHANNEL_CAPACITY", CHANNEL_CAPACITY)
        if channel_capacity is None
        else channel_capacity
    )
    tx_new_certificates = metrics.InstrumentedQueue(
        cap, channel="node.tx_new_certificates"
    )
    tx_feedback = metrics.InstrumentedQueue(cap, channel="node.tx_feedback")
    tx_output = metrics.InstrumentedQueue(cap, channel="node.tx_output")

    # Same for the consensus kernel: compile its one static window shape
    # before the primary joins the committee (KernelTusk.prewarm docstring),
    # which is why the Consensus is built before Primary.spawn logs the
    # boot banner the harness waits on.
    consensus = (consensus_cls or Consensus)(
        committee,
        parameters.gc_depth,
        rx_primary=tx_new_certificates,
        tx_primary=tx_feedback,
        tx_output=tx_output,
        benchmark=benchmark,
        use_kernel=use_kernel,
        # Committed-frontier crash recovery (beyond reference parity):
        # a small atomically-rewritten file next to the store log, so a
        # restarted primary's ordering anchors at its old frontier and
        # replayed history can't re-enter the commit sequence (rationale
        # in Consensus.__init__).  Memory-only nodes (store_path=None,
        # tests/benches) skip it.
        checkpoint_path=(
            store_path + ".consensus.ckpt" if store_path else None
        ),
        audit_path=audit_path,
        # None defers to NARWHAL_COMMIT_RULE inside Consensus; the CLI
        # value (node run --commit-rule) arrives here already resolved.
        commit_rule=commit_rule,
    )
    if hasattr(consensus.tusk, "prewarm"):
        log.info("Warming up consensus kernel...")
        consensus.tusk.prewarm()
        log.info("Consensus kernel ready")
    node.consensus = consensus

    node.primary = await Primary.spawn(
        keypair,
        committee,
        parameters,
        node.store,
        tx_consensus=tx_new_certificates,
        rx_consensus=tx_feedback,
        benchmark=benchmark,
        fault_plan=fault_plan,
    )
    node.tasks.append(spawn(consensus.run(), name="consensus"))

    async def analyze() -> None:
        while True:
            certificate = await tx_output.get()
            if on_commit is not None:
                on_commit(certificate)

    node.tasks.append(spawn(analyze(), name="analyze"))

    # Far-frontier restore, second half (found by the crash/restart fault
    # scenario): the checkpoint anchors the committed FRONTIER, but the
    # DAG between the frontier and the pre-crash head lives only in the
    # persisted store — and on a store-preserving restart those
    # certificates never reach consensus again (peers' deliveries pass
    # their dependency checks against the store, so nothing re-routes the
    # history), leaving a permanent HOLE in this node's commit sequence
    # where every healthy peer committed.  Re-seed consensus from the
    # store: every parseable certificate above the restored per-author
    # frontier, oldest round first.  Runs as a task after the Primary is
    # up so the consensus GC feedback loop is already draining.
    if store_path is not None or replay_persisted:
        node.tasks.append(
            spawn(
                _replay_persisted_certificates(
                    node.store, consensus.tusk.state, tx_new_certificates
                ),
                name="certificate-replay",
            )
        )
    return node


async def _replay_persisted_certificates(
    store: Store, state, tx_consensus: asyncio.Queue
) -> None:
    """Feed certificates persisted by a previous incarnation back into
    the commit rule.  Values that are not certificates (headers fail the
    decode, payload markers are empty) are skipped; certificates at or
    below the restored frontier can never commit again (order_dag's ≥
    skip) and are dropped here instead of costing queue slots.

    Certificates persisted under the OTHER cert-sig scheme refuse to
    decode (SchemeMismatch); they are counted and reported in one loud
    warning naming both schemes rather than silently skipped — the
    consensus checkpoint refuses the cross-scheme boot outright, but a
    checkpoint-less store must not quietly drop its history."""
    from ..crypto import SchemeMismatch
    from ..primary.messages import Certificate

    certs = []
    cross_scheme = 0
    cross_scheme_detail = ""
    for i, value in enumerate(store.values()):
        if i % 256 == 0 and i:
            # The scan runs on the freshly booted node's event loop while
            # peers are already retrying against it — yield so sync
            # requests, votes and /healthz stay answerable throughout.
            await asyncio.sleep(0)
        if len(value) < 140:  # smaller than any vote-carrying certificate
            continue
        try:
            cert = Certificate.deserialize(value)
        except SchemeMismatch as e:
            cross_scheme += 1
            cross_scheme_detail = str(e)
            continue
        except Exception:
            continue  # a header or foreign record
        if not cert.votes and cert.agg is None:
            continue
        if cert.round <= state.last_committed.get(cert.origin, 0):
            continue
        certs.append(cert)
    if cross_scheme:
        metrics.counter("primary.invalid_signatures").inc(cross_scheme)
        log.warning(
            "Persisted store holds %d certificate(s) from the other "
            "cert-sig scheme; they cannot re-enter consensus (%s)",
            cross_scheme,
            cross_scheme_detail,
        )
    if not certs:
        return
    certs.sort(key=lambda c: c.round)
    for cert in certs:
        await tx_consensus.put(cert)
    log.info(
        "Replayed %d persisted certificates into consensus "
        "(restored frontier round %d)",
        len(certs),
        state.last_committed_round,
    )


class WorkerNode:
    def __init__(self, worker: Worker, store: Store) -> None:
        self.worker = worker
        self.store = store

    async def shutdown(self) -> None:
        await self.worker.shutdown()
        self.store.close()


async def spawn_worker_node(
    keypair: KeyPair,
    worker_id: WorkerId,
    committee: Committee,
    parameters: Parameters,
    store_path: Optional[str] = None,
    benchmark: bool = False,
    fault_plan=None,
    store: Optional[Store] = None,
) -> WorkerNode:
    """``fault_plan`` wires the Byzantine worker wrappers (batch
    withholding / garbage serving / sync flooding — the fault suite's
    worker-plane adversary); None is the honest worker.  ``store`` hands
    the worker an existing Store object (sim crash/restart; see
    spawn_primary_node)."""
    store = Store(store_path) if store is None else store
    worker = await Worker.spawn(
        keypair.name,
        worker_id,
        committee,
        parameters,
        store,
        benchmark=benchmark,
        fault_plan=fault_plan,
    )
    return WorkerNode(worker, store)
