from .node import spawn_primary_node, spawn_worker_node

__all__ = ["spawn_primary_node", "spawn_worker_node"]
