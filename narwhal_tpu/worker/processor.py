"""Processor: hash, persist and report each batch.

Reference worker/src/processor.rs (57 LoC): SHA-512/32B digest of the
serialized batch (line 35 — the per-batch hot loop), write `digest → batch`
to the store, emit OurBatch/OthersBatch(digest, worker_id) toward the
primary.  Spawned twice: once for our sealed batches, once for batches
received from other workers.
"""

from __future__ import annotations

import asyncio
import logging
import os

from ..config import WorkerId
from ..crypto import digest32
from ..messages import encode_batch_digest

log = logging.getLogger("narwhal.worker")
_TRACE = bool(os.environ.get("NARWHAL_TRACE"))


class Processor:
    def __init__(
        self,
        worker_id: WorkerId,
        store,
        in_queue: asyncio.Queue,  # serialized batches
        out_queue: asyncio.Queue,  # → PrimaryConnector: encoded digest message
        own_digests: bool,
    ) -> None:
        self.worker_id = worker_id
        self.store = store
        self.in_queue = in_queue
        self.out_queue = out_queue
        self.own_digests = own_digests

    async def run(self) -> None:
        while True:
            item = await self.in_queue.get()
            if isinstance(item, tuple):
                # Own batches arrive with their digest already computed at
                # seal time (batch_maker.py) — no second 500 kB hash.
                digest, serialized = item
            else:
                serialized = item
                digest = digest32(serialized)
            self.store.write(bytes(digest), serialized)
            if _TRACE:
                log.info("TRACE processed %r own=%s", digest, self.own_digests)
            await self.out_queue.put(
                encode_batch_digest(digest, self.worker_id, self.own_digests)
            )
