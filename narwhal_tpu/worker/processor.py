"""Processor: hash, persist and report each batch.

Reference worker/src/processor.rs (57 LoC): SHA-512/32B digest of the
serialized batch (line 35 — the per-batch hot loop), write `digest → batch`
to the store, emit OurBatch/OthersBatch(digest, worker_id) toward the
primary.  Spawned twice: once for our sealed batches, once for batches
received from other workers.
"""

from __future__ import annotations

import asyncio
import logging

from .. import metrics
from ..config import WorkerId
from ..crypto import digest32
from ..messages import encode_batch_digest
from ..utils.env import env_flag

log = logging.getLogger("narwhal.worker")
_TRACE = env_flag("NARWHAL_TRACE")


class Processor:
    def __init__(
        self,
        worker_id: WorkerId,
        store,
        in_queue: asyncio.Queue,  # serialized batches
        out_queue: asyncio.Queue,  # → PrimaryConnector: encoded digest message
        own_digests: bool,
    ) -> None:
        self.worker_id = worker_id
        self.store = store
        self.in_queue = in_queue
        self.out_queue = out_queue
        self.own_digests = own_digests
        self._m_duplicates = metrics.counter("worker.duplicate_batches")

    async def run(self) -> None:
        while True:
            item = await self.in_queue.get()
            if isinstance(item, tuple):
                # Own batches arrive with their digest already computed at
                # seal time (batch_maker.py) — no second 500 kB hash.
                digest, serialized = item
            else:
                serialized = item
                digest = digest32(serialized)
            if not self.own_digests and self.store.read(bytes(digest)) is not None:
                # Re-delivered batch (helpful peers re-send during sync
                # storms, and escalated BatchRequests fan out to several
                # holders): the first delivery already persisted it and
                # reported the digest, so a second store append + digest
                # message would only grow the log and the primary's queue.
                # Own sealed batches are exempt — they arrive over no
                # network, and a (rare) byte-identical re-seal still owes
                # the proposer its digest.
                self._m_duplicates.inc()
                continue
            self.store.write(bytes(digest), serialized)
            if _TRACE:
                log.info("TRACE processed %r own=%s", digest, self.own_digests)
            await self.out_queue.put(
                encode_batch_digest(digest, self.worker_id, self.own_digests)
            )
