"""Worker wiring: three inbound planes, three pipelines.

Reference worker/src/worker.rs (318 LoC): `Worker::spawn` wires
- client transactions → BatchMaker → QuorumWaiter → Processor(own) →
  PrimaryConnector (the throughput hot path, SURVEY.md §3.2),
- other workers' frames → ACK → Processor(others) / Helper,
- primary commands → Synchronizer.
Channel capacity 1000 throughout (worker.rs:26) for backpressure.
"""

from __future__ import annotations

import asyncio
import logging
from typing import List, Optional

from .. import metrics, native
from ..config import Committee, Parameters, WorkerId
from ..crypto import PublicKey
from ..messages import (
    PRIMARY_WORKER_FRAME_TYPES,
    WORKER_BATCH,
    WORKER_FRAME_TYPES,
    decode_primary_worker_message,
    decode_worker_message,
    frame_classifier,
    set_wire_committee,
)
from ..network import Receiver, Writer
from ..network.clocksync import stamp_ack
from ..store import Store
from ..utils.env import env_flag, env_int, positive_int
from ..utils.tasks import spawn
from .batch_maker import BatchMaker
from .helper import Helper, max_request_digests
from .primary_connector import PrimaryConnector
from .processor import Processor
from .quorum_waiter import QuorumWaiter
from .synchronizer import Synchronizer

log = logging.getLogger("narwhal.worker")

CHANNEL_CAPACITY = 1_000

# In-flight sealed batches awaiting their ACK quorum.  Deliberately tiny
# (unlike the uniform 1000-capacity channels of the reference,
# worker.rs:26): when this fills, the BatchMaker pauses the client sockets,
# so TCP flow control adapts the offered load to the committee's real ACK
# bandwidth.  A deep queue here is pure bufferbloat — on congested hosts the
# ACK rate drops as the backlog grows (peers drown in queued batch frames),
# which turns a transient stall into an unrecoverable spiral.
QUORUM_WINDOW = 8


def max_batch_bytes(batch_size: int) -> int:
    """Largest serialized batch frame this worker accepts, derived from
    the committee's configured ``batch_size`` (an honest seal overshoots
    the threshold by at most one transaction plus frame overhead) with
    2x headroom plus 64 KiB of slack.  ``NARWHAL_MAX_BATCH_BYTES``
    overrides — raise it for deployments whose single transactions
    legitimately dwarf the batch threshold.  Anything larger is garbage
    or hostile: without this gate a peer can make us SHA-512 and persist
    megabytes of junk per frame (the fault suite's ``garbage_batches``
    behavior), bounded only by the 32 MiB wire cap."""
    return positive_int("NARWHAL_MAX_BATCH_BYTES", 2 * batch_size + 65_536)


def max_request_bytes() -> int:
    """Largest non-batch worker frame worth DECODING.  An over-cap
    BatchRequest is truncated-and-served by the Helper (the documented
    degradation), but decoding is itself O(frame) — a ~32 MiB hostile
    request would allocate ~1M Digest objects before the cap dropped
    99.99% of them.  Frames that could not possibly dedup down to the
    cap get a length compare instead of a decode: 8x the cap's wire size
    tolerates sloppy-but-honest senders (and the fault suite's own
    1024-digest flood, which must reach the truncation path under the
    default cap) while bounding the decode cost of a frame to ~8x what
    the Helper would ever serve."""
    # tag + count + digests + requestor key, at 8x the digest cap.
    return 1 + 4 + 32 * (8 * max_request_digests()) + 32


class WorkerReceiverHandler:
    """Other workers' traffic: ACK everything, route batches to the
    others-Processor and batch requests to the Helper
    (reference worker.rs:264-292)."""

    def __init__(
        self,
        others_queue: asyncio.Queue,
        helper_queue: asyncio.Queue,
        max_batch_bytes: Optional[int] = None,
    ) -> None:
        self.others_queue = others_queue
        self.helper_queue = helper_queue
        self.max_batch_bytes = max_batch_bytes
        self._max_request_bytes = max_request_bytes()
        self._m_batches_in = metrics.counter("worker.batches_received")
        self._m_batch_bytes_in = metrics.counter("worker.batch_bytes_received")
        self._m_malformed = metrics.counter("worker.malformed_frames")
        self._m_garbage = metrics.counter("worker.garbage_batches")
        self._m_request_rejected = metrics.counter(
            "worker.helper_rejected_requests"
        )

    async def dispatch(self, writer: Writer, message: bytes) -> None:
        # Batches are large and their raw frame is the hashing/storage unit:
        # structurally validate without decoding (native length-prefix walk,
        # no per-tx allocation), then ACK and store the raw bytes.  A
        # malformed batch is dropped un-ACKed, like the reference's
        # deserialization failure path (worker.rs:264-292).
        if message and message[0] == WORKER_BATCH:
            if (
                self.max_batch_bytes is not None
                and len(message) > self.max_batch_bytes
            ):
                # Size gate BEFORE the structural walk and the 32 B hash:
                # an oversized frame must cost us a length compare, not a
                # multi-megabyte SHA-512 + store append (worker.rs has no
                # equivalent; the garbage_batches fault scenario is the
                # regression harness).  Counted for the `garbage_batches`
                # health rule; dropped un-ACKed.
                self._m_garbage.inc()
                log.warning(
                    "Dropping oversized batch frame (%d B > cap %d B)",
                    len(message), self.max_batch_bytes,
                )
                return
            if native.validate_batch(message) < 0:
                self._m_malformed.inc()
                log.warning("Dropping malformed batch frame")
                return
            await writer.send(stamp_ack())
            self._m_batches_in.inc()
            self._m_batch_bytes_in.inc(len(message))
            await self.others_queue.put(message)
            return
        if len(message) > self._max_request_bytes:
            # Same cost discipline as the batch size gate: a request
            # frame too large to ever survive the Helper's dedup+cap is
            # dropped for a length compare, not an O(frame) decode
            # (counted into the helper_abuse rule's input).
            self._m_request_rejected.inc()
            log.warning(
                "Dropping oversized batch-request frame (%d B > cap %d B)",
                len(message), self._max_request_bytes,
            )
            return
        try:
            decoded = decode_worker_message(message)
        except ValueError as e:
            self._m_malformed.inc()
            log.warning("Dropping malformed worker message: %s", e)
            return
        await writer.send(stamp_ack())
        _, digests, requestor = decoded
        await self.helper_queue.put((digests, requestor))


class PrimaryReceiverHandler:
    """Commands from our primary (reference worker.rs:295-318)."""

    def __init__(self, sync_queue: asyncio.Queue) -> None:
        self.sync_queue = sync_queue

    async def dispatch(self, writer: Writer, message: bytes) -> None:
        try:
            cmd = decode_primary_worker_message(message)
        except ValueError as e:
            log.warning("Dropping malformed primary message: %s", e)
            return
        await self.sync_queue.put(cmd)


class Worker:
    def __init__(
        self,
        name: PublicKey,
        worker_id: WorkerId,
        committee: Committee,
        parameters: Parameters,
        store: Store,
        benchmark: bool = False,
    ) -> None:
        self.name = name
        self.worker_id = worker_id
        self.committee = committee
        self.parameters = parameters
        self.store = store
        self.benchmark = benchmark
        self.tasks: List[asyncio.Task] = []
        self.receivers: List[Receiver] = []
        self.senders: List = []  # network senders owned by our components

    @classmethod
    async def spawn(
        cls,
        name: PublicKey,
        worker_id: WorkerId,
        committee: Committee,
        parameters: Parameters,
        store: Store,
        benchmark: bool = False,
        fault_plan=None,
    ) -> "Worker":
        """``fault_plan`` (a ``narwhal_tpu.faults.byzantine.ByzantinePlan``
        with worker behaviors) swaps the BatchMaker/Helper pair for their
        Byzantine wrappers and spawns the sync flooder — the fault
        suite's worker-plane adversary; None (the default) is the honest
        worker."""
        self = cls(name, worker_id, committee, parameters, store, benchmark)
        loop = asyncio.get_running_loop()
        # Wire v2 key-index space (see Primary.spawn).
        set_wire_committee(committee)
        cap = env_int("NARWHAL_CHANNEL_CAPACITY", CHANNEL_CAPACITY)
        q = lambda ch: metrics.InstrumentedQueue(cap, channel=ch)  # noqa: E731

        # Byzantine wiring mirrors primary.py: same channels, same
        # pipelines — the adversary acts only at the network boundary.
        maker_cls, helper_cls = BatchMaker, Helper
        extra: tuple = ()
        flooder = None
        if fault_plan is not None and fault_plan.worker_behaviors():
            from ..faults.byzantine_worker import (
                ByzantineBatchMaker,
                ByzantineHelper,
                SyncFlooder,
            )

            maker_cls, helper_cls = ByzantineBatchMaker, ByzantineHelper
            extra = (fault_plan,)
            if "sync_flood" in fault_plan.behaviors:
                flooder = SyncFlooder(
                    fault_plan, name, worker_id, committee, store
                )

        # to_quorum keeps its QUORUM_WINDOW depth: its fullness IS the
        # admission backpressure (below queue_saturated's MIN_CAP floor
        # on purpose — running full there is mechanism, not anomaly).
        to_quorum = metrics.InstrumentedQueue(
            QUORUM_WINDOW, channel="worker.to_quorum"
        )
        own_batches = q("worker.own_batches")
        others_batches = q("worker.others_batches")
        to_primary = q("worker.to_primary")
        helper_queue = q("worker.helper")
        sync_queue = q("worker.sync")

        # Queue-depth gauges: callbacks polled only at snapshot/scrape
        # time, so the hot path pays nothing.  These are exactly the
        # depths the NARWHAL_TRACE heartbeat used to log — now first-class.
        # One literal call per name (no loop) so the metric-name-drift
        # lint rule can see every registered name statically.
        metrics.gauge_fn("worker.queue.to_quorum", to_quorum.qsize)
        metrics.gauge_fn("worker.queue.own_batches", own_batches.qsize)
        metrics.gauge_fn("worker.queue.others_batches", others_batches.qsize)
        metrics.gauge_fn("worker.queue.to_primary", to_primary.qsize)
        metrics.gauge_fn("worker.queue.helper", helper_queue.qsize)
        metrics.gauge_fn("worker.queue.sync", sync_queue.qsize)

        addrs = committee.worker(name, worker_id)
        primary_addr = committee.primary(name).worker_to_primary

        # Inbound planes.  The client transaction socket is bound by the
        # BatchMaker itself (native per-tx path; see batch_maker.py).
        self.receivers.append(
            await Receiver.spawn(
                addrs.worker_to_worker,
                WorkerReceiverHandler(
                    others_batches,
                    helper_queue,
                    max_batch_bytes=max_batch_bytes(parameters.batch_size),
                ),
                classify=frame_classifier(WORKER_FRAME_TYPES),
            )
        )
        self.receivers.append(
            await Receiver.spawn(
                addrs.primary_to_worker,
                PrimaryReceiverHandler(sync_queue),
                classify=frame_classifier(PRIMARY_WORKER_FRAME_TYPES),
            )
        )

        # Pipelines.
        batch_maker = maker_cls(
            *extra,
            name,
            worker_id,
            committee,
            parameters.batch_size,
            parameters.max_batch_delay,
            addrs.transactions,
            to_quorum,
            benchmark=benchmark,
        )
        quorum_waiter = QuorumWaiter(name, committee, to_quorum, own_batches)
        processor_own = Processor(worker_id, store, own_batches, to_primary, True)
        processor_others = Processor(
            worker_id, store, others_batches, to_primary, False
        )
        connector = PrimaryConnector(primary_addr, to_primary)
        synchronizer = Synchronizer(
            name,
            worker_id,
            committee,
            store,
            parameters.sync_retry_delay,
            parameters.sync_retry_nodes,
            sync_queue,
            gc_depth=parameters.gc_depth,
        )
        helper = helper_cls(*extra, worker_id, committee, store, helper_queue)
        self.senders = [
            batch_maker.sender,
            connector.sender,
            synchronizer.sender,
            helper.sender,
        ]

        runners = [
            batch_maker,
            quorum_waiter,
            processor_own,
            processor_others,
            connector,
            synchronizer,
            helper,
        ]
        if flooder is not None:
            runners.append(flooder)
            self.senders.append(flooder.sender)
        for runner in runners:
            self.tasks.append(
                spawn(runner.run(), name=type(runner).__name__.lower())
            )
        # The tx socket is bound inside BatchMaker.run; wait so clients can
        # connect as soon as spawn returns, and fail fast on a bind error.
        await batch_maker.started.wait()
        if batch_maker.boot_error is not None:
            await self.shutdown()
            raise batch_maker.boot_error

        if env_flag("NARWHAL_TRACE"):
            async def heartbeat():
                while True:
                    t0 = loop.time()
                    await asyncio.sleep(1.0)
                    lag = (loop.time() - t0) - 1.0
                    sender = batch_maker.sender
                    buf = sum(
                        len(c.buffer) + len(c.pending)
                        for c in sender._connections.values()
                    )
                    log.info(
                        "TRACE hb lag=%.0fms q_quorum=%d q_own=%d q_others=%d "
                        "q_prim=%d sender_backlog=%d batcher=%d",
                        lag * 1000, to_quorum.qsize(), own_batches.qsize(),
                        others_batches.qsize(), to_primary.qsize(), buf,
                        batch_maker.batcher.tx_bytes,
                    )

            self.tasks.append(spawn(heartbeat(), name="trace-heartbeat"))

        log.info(
            "Worker %d successfully booted on %s",
            worker_id,
            addrs.transactions.rsplit(":", 1)[0],
        )
        return self

    async def shutdown(self) -> None:
        for task in self.tasks:
            task.cancel()
        for sender in self.senders:
            sender.close()
        for receiver in self.receivers:
            await receiver.shutdown()
        await asyncio.gather(*self.tasks, return_exceptions=True)
