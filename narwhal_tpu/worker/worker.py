"""Worker wiring: three inbound planes, three pipelines.

Reference worker/src/worker.rs (318 LoC): `Worker::spawn` wires
- client transactions → BatchMaker → QuorumWaiter → Processor(own) →
  PrimaryConnector (the throughput hot path, SURVEY.md §3.2),
- other workers' frames → ACK → Processor(others) / Helper,
- primary commands → Synchronizer.
Channel capacity 1000 throughout (worker.rs:26) for backpressure.
"""

from __future__ import annotations

import asyncio
import logging
from typing import List

from .. import metrics, native
from ..config import Committee, Parameters, WorkerId
from ..crypto import PublicKey
from ..messages import (
    PRIMARY_WORKER_FRAME_TYPES,
    WORKER_BATCH,
    WORKER_FRAME_TYPES,
    decode_primary_worker_message,
    decode_worker_message,
    frame_classifier,
)
from ..network import Receiver, Writer
from ..store import Store
from .batch_maker import BatchMaker
from .helper import Helper
from .primary_connector import PrimaryConnector
from .processor import Processor
from .quorum_waiter import QuorumWaiter
from .synchronizer import Synchronizer

log = logging.getLogger("narwhal.worker")

CHANNEL_CAPACITY = 1_000

# In-flight sealed batches awaiting their ACK quorum.  Deliberately tiny
# (unlike the uniform 1000-capacity channels of the reference,
# worker.rs:26): when this fills, the BatchMaker pauses the client sockets,
# so TCP flow control adapts the offered load to the committee's real ACK
# bandwidth.  A deep queue here is pure bufferbloat — on congested hosts the
# ACK rate drops as the backlog grows (peers drown in queued batch frames),
# which turns a transient stall into an unrecoverable spiral.
QUORUM_WINDOW = 8


class WorkerReceiverHandler:
    """Other workers' traffic: ACK everything, route batches to the
    others-Processor and batch requests to the Helper
    (reference worker.rs:264-292)."""

    def __init__(
        self, others_queue: asyncio.Queue, helper_queue: asyncio.Queue
    ) -> None:
        self.others_queue = others_queue
        self.helper_queue = helper_queue
        self._m_batches_in = metrics.counter("worker.batches_received")
        self._m_batch_bytes_in = metrics.counter("worker.batch_bytes_received")
        self._m_malformed = metrics.counter("worker.malformed_frames")

    async def dispatch(self, writer: Writer, message: bytes) -> None:
        # Batches are large and their raw frame is the hashing/storage unit:
        # structurally validate without decoding (native length-prefix walk,
        # no per-tx allocation), then ACK and store the raw bytes.  A
        # malformed batch is dropped un-ACKed, like the reference's
        # deserialization failure path (worker.rs:264-292).
        if message and message[0] == WORKER_BATCH:
            if native.validate_batch(message) < 0:
                self._m_malformed.inc()
                log.warning("Dropping malformed batch frame")
                return
            await writer.send(b"Ack")
            self._m_batches_in.inc()
            self._m_batch_bytes_in.inc(len(message))
            await self.others_queue.put(message)
            return
        try:
            decoded = decode_worker_message(message)
        except ValueError as e:
            self._m_malformed.inc()
            log.warning("Dropping malformed worker message: %s", e)
            return
        await writer.send(b"Ack")
        _, digests, requestor = decoded
        await self.helper_queue.put((digests, requestor))


class PrimaryReceiverHandler:
    """Commands from our primary (reference worker.rs:295-318)."""

    def __init__(self, sync_queue: asyncio.Queue) -> None:
        self.sync_queue = sync_queue

    async def dispatch(self, writer: Writer, message: bytes) -> None:
        try:
            cmd = decode_primary_worker_message(message)
        except ValueError as e:
            log.warning("Dropping malformed primary message: %s", e)
            return
        await self.sync_queue.put(cmd)


class Worker:
    def __init__(
        self,
        name: PublicKey,
        worker_id: WorkerId,
        committee: Committee,
        parameters: Parameters,
        store: Store,
        benchmark: bool = False,
    ) -> None:
        self.name = name
        self.worker_id = worker_id
        self.committee = committee
        self.parameters = parameters
        self.store = store
        self.benchmark = benchmark
        self.tasks: List[asyncio.Task] = []
        self.receivers: List[Receiver] = []
        self.senders: List = []  # network senders owned by our components

    @classmethod
    async def spawn(
        cls,
        name: PublicKey,
        worker_id: WorkerId,
        committee: Committee,
        parameters: Parameters,
        store: Store,
        benchmark: bool = False,
    ) -> "Worker":
        self = cls(name, worker_id, committee, parameters, store, benchmark)
        loop = asyncio.get_running_loop()
        q = lambda: asyncio.Queue(maxsize=CHANNEL_CAPACITY)  # noqa: E731

        to_quorum = asyncio.Queue(maxsize=QUORUM_WINDOW)
        own_batches = q()
        others_batches = q()
        to_primary = q()
        helper_queue = q()
        sync_queue = q()

        # Queue-depth gauges: callbacks polled only at snapshot/scrape
        # time, so the hot path pays nothing.  These are exactly the
        # depths the NARWHAL_TRACE heartbeat used to log — now first-class.
        for gname, gq in (
            ("worker.queue.to_quorum", to_quorum),
            ("worker.queue.own_batches", own_batches),
            ("worker.queue.others_batches", others_batches),
            ("worker.queue.to_primary", to_primary),
            ("worker.queue.helper", helper_queue),
            ("worker.queue.sync", sync_queue),
        ):
            metrics.gauge_fn(gname, gq.qsize)

        addrs = committee.worker(name, worker_id)
        primary_addr = committee.primary(name).worker_to_primary

        # Inbound planes.  The client transaction socket is bound by the
        # BatchMaker itself (native per-tx path; see batch_maker.py).
        self.receivers.append(
            await Receiver.spawn(
                addrs.worker_to_worker,
                WorkerReceiverHandler(others_batches, helper_queue),
                classify=frame_classifier(WORKER_FRAME_TYPES),
            )
        )
        self.receivers.append(
            await Receiver.spawn(
                addrs.primary_to_worker,
                PrimaryReceiverHandler(sync_queue),
                classify=frame_classifier(PRIMARY_WORKER_FRAME_TYPES),
            )
        )

        # Pipelines.
        batch_maker = BatchMaker(
            name,
            worker_id,
            committee,
            parameters.batch_size,
            parameters.max_batch_delay,
            addrs.transactions,
            to_quorum,
            benchmark=benchmark,
        )
        quorum_waiter = QuorumWaiter(name, committee, to_quorum, own_batches)
        processor_own = Processor(worker_id, store, own_batches, to_primary, True)
        processor_others = Processor(
            worker_id, store, others_batches, to_primary, False
        )
        connector = PrimaryConnector(primary_addr, to_primary)
        synchronizer = Synchronizer(
            name,
            worker_id,
            committee,
            store,
            parameters.sync_retry_delay,
            parameters.sync_retry_nodes,
            sync_queue,
            gc_depth=parameters.gc_depth,
        )
        helper = Helper(worker_id, committee, store, helper_queue)
        self.senders = [
            batch_maker.sender,
            connector.sender,
            synchronizer.sender,
            helper.sender,
        ]

        for runner in (
            batch_maker,
            quorum_waiter,
            processor_own,
            processor_others,
            connector,
            synchronizer,
            helper,
        ):
            self.tasks.append(loop.create_task(runner.run()))
        # The tx socket is bound inside BatchMaker.run; wait so clients can
        # connect as soon as spawn returns, and fail fast on a bind error.
        await batch_maker.started.wait()
        if batch_maker.boot_error is not None:
            await self.shutdown()
            raise batch_maker.boot_error

        import os as _os

        if _os.environ.get("NARWHAL_TRACE"):
            async def heartbeat():
                while True:
                    t0 = loop.time()
                    await asyncio.sleep(1.0)
                    lag = (loop.time() - t0) - 1.0
                    sender = batch_maker.sender
                    buf = sum(
                        len(c.buffer) + len(c.pending)
                        for c in sender._connections.values()
                    )
                    log.info(
                        "TRACE hb lag=%.0fms q_quorum=%d q_own=%d q_others=%d "
                        "q_prim=%d sender_backlog=%d batcher=%d",
                        lag * 1000, to_quorum.qsize(), own_batches.qsize(),
                        others_batches.qsize(), to_primary.qsize(), buf,
                        batch_maker.batcher.tx_bytes,
                    )

            self.tasks.append(loop.create_task(heartbeat()))

        log.info(
            "Worker %d successfully booted on %s",
            worker_id,
            addrs.transactions.rsplit(":", 1)[0],
        )
        return self

    async def shutdown(self) -> None:
        for task in self.tasks:
            task.cancel()
        for sender in self.senders:
            sender.close()
        for receiver in self.receivers:
            await receiver.shutdown()
        await asyncio.gather(*self.tasks, return_exceptions=True)
