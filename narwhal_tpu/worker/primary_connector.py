"""PrimaryConnector: forward batch-digest messages to our primary (LAN hop).

Reference worker/src/primary_connector.rs (39 LoC).
"""

from __future__ import annotations

import asyncio

from ..network import SimpleSender


class PrimaryConnector:
    def __init__(self, primary_address: str, in_queue: asyncio.Queue) -> None:
        self.primary_address = primary_address
        self.in_queue = in_queue
        self.sender = SimpleSender()

    async def run(self) -> None:
        while True:
            message = await self.in_queue.get()
            self.sender.send(
                self.primary_address, message, msg_type="batch_digest"
            )
