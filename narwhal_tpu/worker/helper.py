"""Worker Helper: serve other workers' BatchRequests from our store.

Reference worker/src/helper.rs (71 LoC): read each requested digest and send
the raw serialized batch back to the requestor's same-id worker.  The reply
is a regular WorkerMessage::Batch frame, so the requestor's normal batch path
(Processor → store → OthersBatch digest) resolves the wait.
"""

from __future__ import annotations

import asyncio
import logging

from ..config import Committee, WorkerId
from ..crypto import PublicKey
from ..network import SimpleSender

log = logging.getLogger("narwhal.worker")


class Helper:
    def __init__(
        self,
        worker_id: WorkerId,
        committee: Committee,
        store,
        in_queue: asyncio.Queue,  # (digests, requestor)
    ) -> None:
        self.worker_id = worker_id
        self.committee = committee
        self.store = store
        self.in_queue = in_queue
        self.sender = SimpleSender()

    async def run(self) -> None:
        while True:
            digests, requestor = await self.in_queue.get()
            try:
                address = self.committee.worker(
                    requestor, self.worker_id
                ).worker_to_worker
            except Exception:
                log.warning("Received batch request from unknown authority")
                continue
            for digest in digests:
                serialized = self.store.read(bytes(digest))
                if serialized is not None:
                    self.sender.send(address, serialized, msg_type="batch")
