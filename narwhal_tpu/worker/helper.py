"""Worker Helper: serve other workers' BatchRequests from our store.

Reference worker/src/helper.rs (71 LoC): read each requested digest and send
the raw serialized batch back to the requestor's same-id worker.  The reply
is a regular WorkerMessage::Batch frame, so the requestor's normal batch path
(Processor → store → OthersBatch digest) resolves the wait.

Beyond the reference: requests are BOUNDED.  A BatchRequest is ~32 B per
digest while each reply is a full batch (~500 kB) — a ~15,000x
amplification lever that a hostile peer can pull with one frame (the
fault suite's ``sync_flood`` behavior).  Digests are deduplicated within
a request and capped at :func:`max_request_digests` per frame; anything
past the cap is dropped, counted into ``worker.helper_rejected_requests``
(the ``helper_abuse`` health rule's input) and logged at a bounded rate.
The honest requesting side (worker/synchronizer.py) chunks its own
requests under the same cap, so a clean committee never trips the
counter.
"""

from __future__ import annotations

import asyncio
import logging
import time

from .. import metrics
from ..config import Committee, WorkerId
from ..crypto import PublicKey
from ..network import SimpleSender
from ..utils.env import positive_int

log = logging.getLogger("narwhal.worker")

_MAX_DIGESTS_DEFAULT = 128
# Rate limit for the truncation warning: a flood is thousands of
# identical frames and the bench log parser reads every line.
_REJECT_WARN_INTERVAL = 5.0


def max_request_digests() -> int:
    """Per-BatchRequest digest ceiling (``NARWHAL_HELPER_MAX_DIGESTS``).
    One definition shared by the serving side (the Helper truncates
    over-limit requests, and the receiver pre-drops absurd frames before
    decode) and the requesting side (the Synchronizer chunks under it)
    so an honest committee never looks abusive."""
    return positive_int("NARWHAL_HELPER_MAX_DIGESTS", _MAX_DIGESTS_DEFAULT)


class Helper:
    def __init__(
        self,
        worker_id: WorkerId,
        committee: Committee,
        store,
        in_queue: asyncio.Queue,  # (digests, requestor)
    ) -> None:
        self.worker_id = worker_id
        self.committee = committee
        self.store = store
        self.in_queue = in_queue
        self.sender = SimpleSender()
        self.max_digests = max_request_digests()
        self._m_served = metrics.counter("worker.helper_served_batches")
        self._m_served_bytes = metrics.counter("worker.helper_served_bytes")
        self._m_rejected = metrics.counter("worker.helper_rejected_requests")
        self._last_reject_warn = 0.0

    async def run(self) -> None:
        while True:
            digests, requestor = await self.in_queue.get()
            try:
                address = self.committee.worker(
                    requestor, self.worker_id
                ).worker_to_worker
            except Exception:
                log.warning("Received batch request from unknown authority")
                continue
            await self._respond(address, self._bound(digests, requestor))

    def _bound(self, digests, requestor: PublicKey):
        """Dedup-then-cap one request's digest list; over-limit remainders
        are dropped and counted, never amplified.  Duplicate trimming is
        free — only a UNIQUE digest count past the cap is abuse (the
        rejected counter feeds a LATCHING health rule, so an under-cap
        request with a stray duplicate must not brand a peer hostile)."""
        unique = list(dict.fromkeys(digests))
        bounded = unique[: self.max_digests]
        dropped = len(unique) - len(bounded)
        if dropped:
            self._m_rejected.inc()
            now = time.monotonic()
            if now - self._last_reject_warn >= _REJECT_WARN_INTERVAL:
                self._last_reject_warn = now
                log.warning(
                    "Truncating batch request from %r: %d digest(s) "
                    "(%d duplicate), serving %d (cap %d)",
                    requestor, len(digests), len(digests) - len(unique),
                    len(bounded), self.max_digests,
                )
        return bounded

    async def _respond(self, address: str, digests) -> None:
        """Serve every bounded digest we hold.  The fault suite's
        ByzantineHelper overrides exactly this seam — the availability
        half of the worker plane — while request intake, bounding and
        accounting stay the honest path."""
        for digest in digests:
            serialized = self.store.read(bytes(digest))
            if serialized is not None:
                self._m_served.inc()
                self._m_served_bytes.inc(len(serialized))
                self.sender.send(address, serialized, msg_type="batch")
