"""Worker Synchronizer: fetch batches our primary is waiting for.

Reference worker/src/synchronizer.rs (226 LoC): execute the primary's
`Synchronize` commands — check the store, record pending requests, send a
`BatchRequest` to the target author's same-id worker; a 1 s resolution timer
re-broadcasts to `sync_retry_nodes` random peers once `sync_retry_delay`
elapses (191-222); `Cleanup(round)` garbage-collects pending state (160-176).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, Tuple

from ..config import Committee, WorkerId
from ..crypto import Digest, PublicKey
from ..messages import Round, encode_batch_request
from ..network import SimpleSender

log = logging.getLogger("narwhal.worker")

TIMER_RESOLUTION = 1.0  # seconds (reference synchronizer.rs:22)


class Synchronizer:
    def __init__(
        self,
        name: PublicKey,
        worker_id: WorkerId,
        committee: Committee,
        store,
        sync_retry_delay_ms: int,
        sync_retry_nodes: int,
        in_queue: asyncio.Queue,  # decoded PrimaryWorkerMessage tuples
        gc_depth: Round = 50,
    ) -> None:
        self.name = name
        self.worker_id = worker_id
        self.committee = committee
        self.store = store
        self.sync_retry_delay = sync_retry_delay_ms / 1000.0
        self.sync_retry_nodes = sync_retry_nodes
        self.in_queue = in_queue
        self.gc_depth = gc_depth
        self.sender = SimpleSender()
        self.round: Round = 0
        # digest → (round at request time, request timestamp)
        self.pending: Dict[Digest, Tuple[Round, float]] = {}
        self._waiters: Dict[Digest, asyncio.Task] = {}

    async def run(self) -> None:
        timer = asyncio.get_running_loop().create_task(self._timer())
        try:
            while True:
                cmd = await self.in_queue.get()
                if cmd[0] == "synchronize":
                    _, digests, target = cmd
                    await self._synchronize(digests, target)
                elif cmd[0] == "cleanup":
                    self._cleanup(cmd[1])
        finally:
            timer.cancel()
            for task in self._waiters.values():
                task.cancel()
            self._waiters.clear()

    async def _synchronize(self, digests, target: PublicKey) -> None:
        missing = []
        now = time.monotonic()
        for digest in digests:
            if digest in self.pending:
                continue
            if self.store.read(bytes(digest)) is not None:
                continue
            missing.append(digest)
            self.pending[digest] = (self.round, now)
            # Clear pending as soon as the batch lands in the store
            # (the Processor writes it when the Helper's reply arrives).
            self._waiters[digest] = asyncio.get_running_loop().create_task(
                self._await_arrival(digest)
            )
        if not missing:
            return
        message = encode_batch_request(missing, self.name)
        try:
            address = self.committee.worker(target, self.worker_id).worker_to_worker
        except Exception:
            log.warning("Sync request for unknown target authority")
            return
        self.sender.send(address, message, msg_type="batch_request")

    async def _await_arrival(self, digest: Digest) -> None:
        await self.store.notify_read(bytes(digest))
        self.pending.pop(digest, None)
        self._waiters.pop(digest, None)

    def _cleanup(self, round: Round) -> None:
        """Drop requests older than the GC window — they can no longer matter
        to header validation (reference synchronizer.rs:160-176 retains
        entries for gc_depth rounds, not merely the current round)."""
        self.round = round
        horizon = round - self.gc_depth
        for digest in [d for d, (r, _) in self.pending.items() if r < horizon]:
            del self.pending[digest]
            waiter = self._waiters.pop(digest, None)
            if waiter is not None:
                waiter.cancel()

    async def _timer(self) -> None:
        """Escalate overdue requests to `sync_retry_nodes` random peers
        (reference synchronizer.rs:191-222)."""
        while True:
            await asyncio.sleep(TIMER_RESOLUTION)
            now = time.monotonic()
            overdue = [
                d
                for d, (_, t) in self.pending.items()
                if now - t >= self.sync_retry_delay
            ]
            if not overdue:
                continue
            addresses = [
                addrs.worker_to_worker
                for _, addrs in self.committee.others_workers(self.name, self.worker_id)
            ]
            message = encode_batch_request(overdue, self.name)
            self.sender.lucky_broadcast(
                addresses, message, self.sync_retry_nodes,
                msg_type="batch_request",
            )
            for d in overdue:
                r, _ = self.pending[d]
                self.pending[d] = (r, now)
