"""Worker Synchronizer: fetch batches our primary is waiting for.

Reference worker/src/synchronizer.rs (226 LoC): execute the primary's
`Synchronize` commands — check the store, record pending requests, send a
`BatchRequest` to the target author's same-id worker; a 1 s resolution timer
re-broadcasts overdue requests to `sync_retry_nodes` random peers
(191-222); `Cleanup(round)` garbage-collects pending state (160-176).

Beyond the reference, the retry is a jittered, capped EXPONENTIAL backoff
per digest (one `next_backoff` schedule each, the reconnect schedule of
network/reliable_sender.py) instead of the reference's fixed cadence: a
fixed-period re-broadcast against a slow or withholding author is the
same duplicate-flood shape that outran signature verification in the
partition-heal fault scenario (ROADMAP item 4's second catch), only on
the payload plane — every period each helpful peer re-sends a ~500 kB
batch.  Requests are also chunked under the Helper's per-request digest
cap so an honest retry burst is never mistaken for the `sync_flood`
amplification attack.

Detection plane: ``worker.unserved_sync_age_seconds`` (age of the OLDEST
still-unserved request across the process's synchronizers) is the
``batch_withholding`` health rule's input — a worker whose certified
batches cannot be fetched is exactly the availability attack the paper's
certificate claim rules out.
"""

from __future__ import annotations

import asyncio
import logging
import random
import weakref
from typing import Dict

from .. import metrics
from ..config import Committee, WorkerId
from ..crypto import Digest, PublicKey
from ..messages import Round, encode_batch_request
from ..network import SimpleSender
from ..network.reliable_sender import next_backoff
from .helper import max_request_digests
from ..utils.clock import loop_now
from ..utils.tasks import spawn

log = logging.getLogger("narwhal.worker")

TIMER_RESOLUTION = 1.0  # seconds (reference synchronizer.rs:22)

# Live synchronizers, for the snapshot-time age gauge (one registry per
# process; the WeakSet mirrors store._STORES / reliable_sender._SENDERS).
_SYNCHRONIZERS: "weakref.WeakSet[Synchronizer]" = weakref.WeakSet()


def _oldest_unserved_age() -> float:
    oldest = None
    for sync in _SYNCHRONIZERS:
        for p in sync.pending.values():
            if oldest is None or p.first_ts < oldest:
                oldest = p.first_ts
    if oldest is None:
        return 0.0
    return max(0.0, loop_now() - oldest)


metrics.gauge_fn("worker.unserved_sync_age_seconds", _oldest_unserved_age)


class _PendingSync:
    """One digest's fetch obligation: when it was first requested (the
    age gauge's anchor), and its private backoff schedule."""

    __slots__ = ("round", "first_ts", "delay", "due")

    def __init__(self, round_: Round, now: float, delay: float) -> None:
        self.round = round_
        self.first_ts = now
        self.delay = delay        # next_backoff input (doubles toward cap)
        self.due = now + delay    # the first retry window is un-jittered


class Synchronizer:
    def __init__(
        self,
        name: PublicKey,
        worker_id: WorkerId,
        committee: Committee,
        store,
        sync_retry_delay_ms: int,
        sync_retry_nodes: int,
        in_queue: asyncio.Queue,  # decoded PrimaryWorkerMessage tuples
        gc_depth: Round = 50,
        rng: random.Random = random,  # type: ignore[assignment]
    ) -> None:
        self.name = name
        self.worker_id = worker_id
        self.committee = committee
        self.store = store
        self.sync_retry_delay = sync_retry_delay_ms / 1000.0
        self.sync_retry_nodes = sync_retry_nodes
        self.in_queue = in_queue
        self.gc_depth = gc_depth
        self.sender = SimpleSender()
        self.round: Round = 0
        self.pending: Dict[Digest, _PendingSync] = {}
        self._waiters: Dict[Digest, asyncio.Task] = {}
        self._rng = rng  # injectable: tests pin the jitter deterministically
        self._m_requested = metrics.counter("worker.sync_requested_digests")
        self._m_retries = metrics.counter("worker.sync_retried_digests")
        _SYNCHRONIZERS.add(self)

    async def run(self) -> None:
        timer = spawn(self._timer(), name="worker-sync-timer")
        try:
            while True:
                cmd = await self.in_queue.get()
                if cmd[0] == "synchronize":
                    _, digests, target = cmd
                    await self._synchronize(digests, target)
                elif cmd[0] == "cleanup":
                    self._cleanup(cmd[1])
        finally:
            timer.cancel()
            for task in self._waiters.values():
                task.cancel()
            self._waiters.clear()

    def _send_chunked(self, addresses, digests, escalate: bool) -> None:
        """Emit BatchRequests in chunks under the Helper's per-request
        cap — a storm of overdue digests must not turn our own retry into
        an over-limit request the peers count as abuse."""
        cap = max_request_digests()
        for i in range(0, len(digests), cap):
            message = encode_batch_request(digests[i : i + cap], self.name)
            if escalate:
                self.sender.lucky_broadcast(
                    addresses, message, self.sync_retry_nodes,
                    msg_type="batch_request",
                )
            else:
                for address in addresses:
                    self.sender.send(
                        address, message, msg_type="batch_request"
                    )

    async def _synchronize(self, digests, target: PublicKey) -> None:
        missing = []
        now = loop_now()
        for digest in digests:
            if digest in self.pending:
                continue
            if self.store.read(bytes(digest)) is not None:
                continue
            missing.append(digest)
            self.pending[digest] = _PendingSync(
                self.round, now, self.sync_retry_delay
            )
            # Clear pending as soon as the batch lands in the store
            # (the Processor writes it when the Helper's reply arrives).
            self._waiters[digest] = spawn(self._await_arrival(digest))
        if not missing:
            return
        self._m_requested.inc(len(missing))
        try:
            address = self.committee.worker(target, self.worker_id).worker_to_worker
        except Exception:
            log.warning("Sync request for unknown target authority")
            return
        self._send_chunked([address], missing, escalate=False)

    async def _await_arrival(self, digest: Digest) -> None:
        await self.store.notify_read(bytes(digest))
        self.pending.pop(digest, None)
        self._waiters.pop(digest, None)

    def _cleanup(self, round: Round) -> None:
        """Drop requests older than the GC window — they can no longer matter
        to header validation (reference synchronizer.rs:160-176 retains
        entries for gc_depth rounds, not merely the current round)."""
        self.round = round
        horizon = round - self.gc_depth
        for digest in [
            d for d, p in self.pending.items() if p.round < horizon
        ]:
            del self.pending[digest]
            waiter = self._waiters.pop(digest, None)
            if waiter is not None:
                waiter.cancel()

    async def _timer(self) -> None:
        while True:
            await asyncio.sleep(TIMER_RESOLUTION)
            self._retry_sweep()

    def _retry_sweep(self, now: float = None) -> int:  # type: ignore[assignment]
        """Escalate overdue requests to `sync_retry_nodes` random peers
        (reference synchronizer.rs:191-222), one jittered backoff window
        per digest; returns how many digests were re-requested (``now``
        is injectable so tests drive the windows deterministically)."""
        now = loop_now() if now is None else now
        overdue = []
        for digest, p in self.pending.items():
            if now < p.due:
                continue
            if self.store.read(bytes(digest)) is not None:
                # Landed, but the notify_read waiter task has not run
                # yet this tick: re-requesting would make helpful
                # peers re-send ~500 kB we already hold.  The waiter
                # will clear the entry on its next wakeup.
                continue
            overdue.append(digest)
            # Jittered exponential escalation: the sleep is this
            # window, the delay doubles toward the (env-tunable)
            # reconnect cap — same schedule, same rationale as the
            # sender's reconnect backoff.
            sleep_s, p.delay = next_backoff(p.delay, rng=self._rng)
            p.due = now + sleep_s
        if not overdue:
            return 0
        self._m_retries.inc(len(overdue))
        addresses = [
            addrs.worker_to_worker
            for _, addrs in self.committee.others_workers(self.name, self.worker_id)
        ]
        self._send_chunked(addresses, overdue, escalate=True)
        return len(overdue)
