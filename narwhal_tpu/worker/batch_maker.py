"""BatchMaker: the worker's transaction ingestion plane.

Reference worker/src/batch_maker.rs (157 LoC): gather raw transactions until
`batch_size` bytes or `max_batch_delay` ms (71-98), then seal — serialize,
reliable-broadcast the batch to the same-id workers of every other authority,
and hand the serialized batch plus its ACK futures to the QuorumWaiter
(102-156).  Under benchmark mode, log the sample-tx ids and the batch size so
the log parser can compute TPS and latency (103-141).

TPU-host design difference from the reference: the per-transaction loop
(frame split, byte counting, sample scan, batch serialization) runs in the
native data plane (native/dataplane.c) on raw socket buffers — this class
binds the client transaction socket itself (replacing the generic Receiver +
per-tx queue of the reference architecture) and observes only *sealed
batches*, tens per second.  Python cost is therefore per-batch, not per-tx —
essential on small host cores where the whole committee shares the CPU.

Backpressure: when the downstream queue fills, reading is paused on every
client transport (TCP flow control pushes back to the client), mirroring the
bounded-channel backpressure of the reference (worker.rs:26).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import List, Optional, Tuple

from .. import metrics, native
from ..config import Committee, WorkerId
from ..crypto import PublicKey, digest32
from ..network import ReliableSender
from ..network import transport as _transport
from ..network.framing import parse_address
from ..utils.tasks import spawn

log = logging.getLogger("narwhal.worker")

# How often the ingress-overflow warning may fire: the event itself is
# per-batch and a flooded committee would emit thousands of identical
# lines (and the bench parser reads every one).
_OVERFLOW_WARN_INTERVAL = 5.0


class _TxProtocol(asyncio.Protocol):
    """One inbound client connection: feeds raw chunks to the shared
    batcher through a per-connection framer (partial frames are
    per-stream state)."""

    __slots__ = ("maker", "framer", "transport")

    def __init__(self, maker: "BatchMaker") -> None:
        self.maker = maker
        self.framer = native.make_framer(maker.batcher)
        self.transport = None

    def connection_made(self, transport) -> None:
        self.transport = transport
        self.maker._protocols.add(self)
        if self.maker._paused:
            transport.pause_reading()

    def data_received(self, data: bytes) -> None:
        try:
            self.maker._on_tx_data(self.framer, data)
        except ValueError as e:
            self.maker._m_malformed.inc()
            log.warning("Dropping tx connection (malformed stream): %s", e)
            self.transport.close()

    def connection_lost(self, exc) -> None:
        self.maker._protocols.discard(self)


class BatchMaker:
    def __init__(
        self,
        name: PublicKey,
        worker_id: WorkerId,
        committee: Committee,
        batch_size: int,
        max_batch_delay_ms: int,
        address: str,  # client transaction socket to bind
        out_queue: asyncio.Queue,  # → QuorumWaiter: (serialized, [(stake, fut)])
        benchmark: bool = False,
    ) -> None:
        self.name = name
        self.worker_id = worker_id
        self.committee = committee
        self.batch_size = batch_size
        self.max_batch_delay = max_batch_delay_ms / 1000.0
        self.address = address
        self.out_queue = out_queue
        self.benchmark = benchmark
        self.sender = ReliableSender()
        self.batcher = native.make_batcher(batch_size)
        # Same-id workers at every other authority, resolved once.
        self._peers: List[Tuple[int, str]] = [
            (committee.stake(peer_name), addrs.worker_to_worker)
            for peer_name, addrs in committee.others_workers(name, worker_id)
        ]
        self._protocols: set = set()
        self._paused = False
        self._overflow: List = []
        self._drain_task: Optional[asyncio.Task] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._deadline: Optional[float] = None
        self._dirty = asyncio.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self.started = asyncio.Event()  # set once the tx socket is bound
        self.boot_error: Optional[BaseException] = None  # bind failure
        self._m_sealed = metrics.counter("worker.batches_sealed")
        self._m_tx_bytes = metrics.counter("worker.batch_bytes_sealed")
        self._m_txs = metrics.counter("worker.txs_sealed")
        self._m_overflow = metrics.counter("worker.ingress_overflow")
        self._m_malformed = metrics.counter("worker.malformed_tx_streams")
        self._trace = metrics.trace()
        self._last_overflow_warn = 0.0
        # Plain int alongside the counter: the warning text must report a
        # true event count even under NARWHAL_METRICS=0 (null counter).
        self._overflow_events = 0

    @property
    def port(self) -> int:
        """Actual bound port (useful when spawned with port 0)."""
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def run(self) -> None:
        self._loop = asyncio.get_running_loop()
        host, port = parse_address(self.address)
        try:
            # Transport seam (see network/transport.py): an installed
            # in-memory transport owns the client-transaction ingress
            # too — the simulation harness's clients feed _TxProtocol
            # through seeded in-process connections, no kernel socket.
            sim = _transport.active()
            if sim is not None:
                self._server = sim.create_tx_server(
                    self.address, lambda: _TxProtocol(self)
                )
            else:
                self._server = await self._loop.create_server(
                    lambda: _TxProtocol(self), host, port
                )
        except BaseException as e:
            # Surface bind failures to Worker.spawn (which waits on
            # `started`) instead of dying silently in this task.
            self.boot_error = e
            self.started.set()
            raise
        self.started.set()
        try:
            # The seal deadline is fixed when the first tx of a batch
            # arrives — NOT restarted per tx — so a steady trickle still
            # seals every max_batch_delay (reference batch_maker.rs:71-98
            # uses an interval timer for the same reason).
            while True:
                # lint: allow-interleave(_dirty/_deadline are rewritten by the client-socket data_received callbacks (size-seal path) while this loop sleeps — safely: every suspension is followed by a `continue` that re-reads both before acting, and the deadline-expired _seal below runs synchronously from a post-suspension read, so a size-seal can only ever cause one spurious re-check, never a stale seal)
                await self._dirty.wait()
                # lint: allow-interleave(same re-read discipline as the wait above: the sleep is followed by a `continue`, never by acting on the pre-sleep deadline)
                deadline = self._deadline
                if deadline is None:  # sealed by size meanwhile
                    self._dirty.clear()
                    continue
                remaining = deadline - self._loop.time()
                if remaining > 0:
                    await asyncio.sleep(remaining)
                    continue  # re-check: a size-seal may have intervened
                self._seal()
        finally:
            if self._drain_task is not None:
                self._drain_task.cancel()
            self._server.close()
            for p in list(self._protocols):
                if p.transport is not None:
                    p.transport.close()

    # -- hot path (called from data_received; must not await) ---------------

    def _on_tx_data(self, framer, data: bytes) -> None:
        batcher = self.batcher
        more = framer.feed(batcher, data)
        while more:
            self._seal()
            more = framer.feed(batcher, b"")  # drain retained remainder
        if batcher.tx_count > 0 and self._deadline is None:
            # First tx of a new batch (fresh stream or post-seal remainder):
            # fix the seal deadline now, not per tx.
            self._deadline = self._loop.time() + self.max_batch_delay
            self._dirty.set()

    def _seal(self) -> None:
        self._deadline = None
        self._dirty.clear()
        sealed = self.batcher.seal()
        if sealed is None:
            return

        # The digest is computed exactly once per own batch, here, and flows
        # with the message through QuorumWaiter → Processor (the reference
        # re-hashes in the processor, processor.rs:35 — at ~500 kB per batch
        # the duplicate hash is worth eliminating on shared-core hosts).
        digest = digest32(sealed.message)
        self._m_sealed.inc()
        self._m_tx_bytes.inc(sealed.tx_bytes)
        self._m_txs.inc(sealed.tx_count)
        self._trace.mark(
            bytes(digest).hex(), "seal", bytes=sealed.tx_bytes,
            txs=sealed.tx_count,
        )
        if self.benchmark:
            # Sample transactions carry byte0 == 0 and a u64 counter; the
            # log parser joins these lines with the client's send log to
            # measure end-to-end latency (reference batch_maker.rs:103-141).
            for sample_id in sealed.samples:
                log.info("Batch %r contains sample tx %d", digest, sample_id)
            log.info("Batch %r contains %d B", digest, sealed.tx_bytes)

        handlers = self._broadcast_batch(digest, sealed.message)
        item = (digest, sealed.message, handlers)
        try:
            self.out_queue.put_nowait(item)
        except asyncio.QueueFull:
            # Downstream is lagging: park the batch, stop reading clients
            # (TCP flow control), drain asynchronously.  Counted + a
            # rate-limited warning: a flooded committee must be VISIBLE
            # (round 5 published 3 s latencies because this path was
            # silent, VERDICT.md §1), but one line per parked batch would
            # melt the log under exactly the load that triggers it.
            self._m_overflow.inc()
            self._overflow_events += 1
            now = time.monotonic()
            if now - self._last_overflow_warn >= _OVERFLOW_WARN_INTERVAL:
                self._last_overflow_warn = now
                log.warning(
                    "Client ingress overflowing: quorum pipeline full "
                    "(%d events so far); pausing client sockets",
                    self._overflow_events,
                )
            self._overflow.append(item)
            if not self._paused:
                self._paused = True
                for p in self._protocols:
                    if p.transport is not None:
                        p.transport.pause_reading()
                self._drain_task = spawn(
                    self._drain_overflow(), name="batch-maker-drain"
                )

    def _broadcast_batch(self, digest, message: bytes):
        """Reliable-broadcast the sealed batch to our counterpart workers
        at every other authority; returns the ``[(stake, ack_future)]``
        list the QuorumWaiter counts.  This is the quorum-ACK half of the
        worker's availability split (the Helper serves the fetch half) —
        the fault suite's ByzantineBatchMaker overrides exactly this seam
        to under-share while still certifying."""
        return [
            (stake, self.sender.send(addr, message, msg_type="batch"))
            for stake, addr in self._peers
        ]

    async def _drain_overflow(self) -> None:
        while self._overflow:
            item = self._overflow.pop(0)
            await self.out_queue.put(item)
        self._paused = False
        for p in self._protocols:
            if p.transport is not None:
                p.transport.resume_reading()
