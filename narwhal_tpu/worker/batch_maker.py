"""BatchMaker: accumulate client transactions into sealed batches.

Reference worker/src/batch_maker.rs (157 LoC): gather raw transactions until
`batch_size` bytes or `max_batch_delay` ms (71-98), then seal — serialize,
reliable-broadcast the batch to the same-id workers of every other authority,
and hand the serialized batch plus its ACK futures to the QuorumWaiter
(102-156).  Under benchmark mode, log the sample-tx ids and the batch size so
the log parser can compute TPS and latency (103-141).
"""

from __future__ import annotations

import asyncio
import logging
from typing import List, Tuple

from ..config import Committee, WorkerId
from ..crypto import PublicKey, sha512_digest
from ..messages import Transaction, encode_batch
from ..network import ReliableSender

log = logging.getLogger("narwhal.worker")


class BatchMaker:
    def __init__(
        self,
        name: PublicKey,
        worker_id: WorkerId,
        committee: Committee,
        batch_size: int,
        max_batch_delay_ms: int,
        tx_queue: asyncio.Queue,
        out_queue: asyncio.Queue,  # → QuorumWaiter: (serialized, [(stake, fut)])
        benchmark: bool = False,
    ) -> None:
        self.name = name
        self.worker_id = worker_id
        self.committee = committee
        self.batch_size = batch_size
        self.max_batch_delay = max_batch_delay_ms / 1000.0
        self.tx_queue = tx_queue
        self.out_queue = out_queue
        self.benchmark = benchmark
        self.sender = ReliableSender()
        self._batch: List[Transaction] = []
        self._bytes = 0

    async def run(self) -> None:
        # The seal deadline is fixed when the first tx of a batch arrives —
        # NOT restarted per tx — so a steady trickle still seals every
        # max_batch_delay (reference batch_maker.rs:71-98 uses an interval
        # timer for the same reason).
        loop = asyncio.get_running_loop()
        deadline = None
        while True:
            if deadline is None:
                tx = await self.tx_queue.get()
                deadline = loop.time() + self.max_batch_delay
            else:
                remaining = deadline - loop.time()
                if remaining <= 0:
                    await self._seal()
                    deadline = None
                    continue
                try:
                    tx = await asyncio.wait_for(self.tx_queue.get(), remaining)
                except asyncio.TimeoutError:
                    await self._seal()
                    deadline = None
                    continue
            self._batch.append(tx)
            self._bytes += len(tx)
            if self._bytes >= self.batch_size:
                await self._seal()
                deadline = None

    async def _seal(self) -> None:
        batch, self._batch = self._batch, []
        size, self._bytes = self._bytes, 0
        serialized = encode_batch(batch)

        if self.benchmark:
            digest = sha512_digest(serialized)
            # Sample transactions carry byte0 == 0 and a u64 counter; the log
            # parser joins these lines with the client's send log to measure
            # end-to-end latency (reference batch_maker.rs:103-141).
            for tx in batch:
                if tx and tx[0] == 0 and len(tx) >= 9:
                    sample_id = int.from_bytes(tx[1:9], "little")
                    log.info("Batch %r contains sample tx %d", digest, sample_id)
            log.info("Batch %r contains %d B", digest, size)

        # Reliable-broadcast to our counterpart workers at every other
        # authority; the ACK futures feed the quorum count.
        peers: List[Tuple[PublicKey, str]] = [
            (name, addrs.worker_to_worker)
            for name, addrs in self.committee.others_workers(self.name, self.worker_id)
        ]
        handlers = []
        for peer_name, addr in peers:
            fut = self.sender.send(addr, serialized)
            handlers.append((self.committee.stake(peer_name), fut))
        await self.out_queue.put((serialized, handlers))
