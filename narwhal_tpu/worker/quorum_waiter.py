"""QuorumWaiter: hold each sealed batch until 2f+1 stake has ACKed it.

Reference worker/src/quorum_waiter.rs (87 LoC): wait on the ACK futures until
the acknowledging stake (including our own) reaches the quorum threshold,
then release the batch downstream; remaining in-flight deliveries are
abandoned (their retransmission pressure ends with the cancel).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from .. import metrics
from ..config import Committee
from ..crypto import PublicKey
from ..utils.clock import loop_now
from ..utils.env import env_flag

log = logging.getLogger("narwhal.worker")
_TRACE = env_flag("NARWHAL_TRACE")


class QuorumWaiter:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        in_queue: asyncio.Queue,  # ← BatchMaker: (serialized, [(stake, fut)])
        out_queue: asyncio.Queue,  # → Processor: serialized batch
    ) -> None:
        self.name = name
        self.committee = committee
        self.in_queue = in_queue
        self.out_queue = out_queue
        self._m_latency = metrics.histogram("worker.quorum_latency_seconds")
        self._m_reached = metrics.counter("worker.quorum_reached")
        self._m_dropped = metrics.counter("worker.quorum_dropped")
        self._mtrace = metrics.trace()
        # Live wedge detection (health rule `quorum_wedge`): how long the
        # CURRENT batch has been waiting for quorum (0 when idle) and how
        # much stake has ACKed it so far.  A waiter stuck at 2f stake —
        # one ACK short, forever — previously surfaced only via
        # pending-ACK growth on the sender; these gauges name it.
        self._wait_started: Optional[float] = None
        self._m_acked_stake = metrics.gauge("worker.quorum_acked_stake")
        self._m_threshold = metrics.gauge("worker.quorum_threshold")
        metrics.gauge_fn(
            "worker.quorum_wait_age_seconds",
            lambda: (
                0.0
                if self._wait_started is None
                else max(0.0, loop_now() - self._wait_started)
            ),
        )

    async def run(self) -> None:
        threshold = self.committee.quorum_threshold()
        self._m_threshold.set(threshold)
        loop = asyncio.get_running_loop()
        while True:
            digest, serialized, handlers = await self.in_queue.get()
            # ACK-latency clock starts here, when the wait begins: the
            # broadcast itself was enqueued at seal time, so this measures
            # wire + peer validation + ACK return (minus queue time in
            # to_quorum, which the queue-depth gauge exposes separately).
            t0 = loop.time()
            self._wait_started = loop_now()
            total = self.committee.stake(self.name)  # our own stake counts
            self._m_acked_stake.set(total)
            pending = {fut: stake for stake, fut in handlers}
            while total < threshold and pending:
                done, _ = await asyncio.wait(
                    set(pending), return_when=asyncio.FIRST_COMPLETED
                )
                for fut in done:
                    stake = pending.pop(fut)
                    if not fut.cancelled() and fut.exception() is None:
                        total += stake
                self._m_acked_stake.set(total)
            # Quorum reached (or unreachable): abandon in-flight deliveries.
            for fut in pending:
                fut.cancel()
            self._wait_started = None
            self._m_acked_stake.set(0)
            if total >= threshold:
                self._m_latency.observe(loop.time() - t0)
                self._m_reached.inc()
                self._mtrace.mark(bytes(digest).hex(), "quorum")
                if _TRACE:
                    log.info("TRACE quorum reached (%d B)", len(serialized))
                await self.out_queue.put((digest, serialized))
            else:
                self._m_dropped.inc()
                log.warning("Batch dropped: quorum unreachable (got %d)", total)
