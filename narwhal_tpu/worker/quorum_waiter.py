"""QuorumWaiter: hold each sealed batch until 2f+1 stake has ACKed it.

Reference worker/src/quorum_waiter.rs (87 LoC): wait on the ACK futures until
the acknowledging stake (including our own) reaches the quorum threshold,
then release the batch downstream; remaining in-flight deliveries are
abandoned (their retransmission pressure ends with the cancel).
"""

from __future__ import annotations

import asyncio
import logging
import os

from ..config import Committee
from ..crypto import PublicKey

log = logging.getLogger("narwhal.worker")
_TRACE = bool(os.environ.get("NARWHAL_TRACE"))


class QuorumWaiter:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        in_queue: asyncio.Queue,  # ← BatchMaker: (serialized, [(stake, fut)])
        out_queue: asyncio.Queue,  # → Processor: serialized batch
    ) -> None:
        self.name = name
        self.committee = committee
        self.in_queue = in_queue
        self.out_queue = out_queue

    async def run(self) -> None:
        threshold = self.committee.quorum_threshold()
        while True:
            digest, serialized, handlers = await self.in_queue.get()
            total = self.committee.stake(self.name)  # our own stake counts
            pending = {fut: stake for stake, fut in handlers}
            while total < threshold and pending:
                done, _ = await asyncio.wait(
                    set(pending), return_when=asyncio.FIRST_COMPLETED
                )
                for fut in done:
                    stake = pending.pop(fut)
                    if not fut.cancelled() and fut.exception() is None:
                        total += stake
            # Quorum reached (or unreachable): abandon in-flight deliveries.
            for fut in pending:
                fut.cancel()
            if total >= threshold:
                if _TRACE:
                    log.info("TRACE quorum reached (%d B)", len(serialized))
                await self.out_queue.put((digest, serialized))
            else:
                log.warning("Batch dropped: quorum unreachable (got %d)", total)
