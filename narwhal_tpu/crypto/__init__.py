"""Identity layer: digests, ed25519 keys/signatures, signature service.

Mirrors the capability surface of the reference `crypto` crate
(reference crypto/src/lib.rs): a 32-byte `Digest` newtype, a `Hash` seam
(here: objects expose `.digest()`), ed25519 keypairs, single `verify` and
batched `verify_batch`, and a `SignatureService` that serializes signing.

This module is also the backend seam for TPU execution: `set_backend("tpu")`
routes `verify_batch` through the JAX batched-verification kernel in
`narwhal_tpu.ops.ed25519` (reference's per-certificate
`Signature::verify_batch`, crypto/src/lib.rs:206-219, is the #1 crypto hot
loop per SURVEY.md §3.3).
"""

from .digest import Digest, digest32
from .keys import KeyPair, PublicKey, SecretKey, Signature
from .aggregate import (
    AggregateSignature,
    SchemeMismatch,
    aggregate_votes,
)
from .service import SignatureService
from .backend import (
    set_backend,
    get_backend,
    verify,
    verify_aggregate,
    verify_batch,
    verify_batch_mask,
)

__all__ = [
    "AggregateSignature",
    "Digest",
    "digest32",
    "KeyPair",
    "PublicKey",
    "SchemeMismatch",
    "SecretKey",
    "Signature",
    "SignatureService",
    "aggregate_votes",
    "set_backend",
    "get_backend",
    "verify",
    "verify_aggregate",
    "verify_batch",
    "verify_batch_mask",
]
