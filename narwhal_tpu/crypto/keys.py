"""ed25519 keys and signatures (CPU reference implementation).

Mirrors reference crypto/src/lib.rs:64-220: `PublicKey`/`SecretKey` newtypes
with base64 serialization, deterministic keygen from a seeded RNG for test
fixtures, and 64-byte signatures.  The CPU implementation rides the
`cryptography` package (OpenSSL ed25519); the TPU batched verifier lives in
`narwhal_tpu.ops.ed25519` behind `crypto.backend`.
"""

from __future__ import annotations

import base64
from typing import Optional

from cryptography.hazmat.primitives.asymmetric.ed25519 import (
    Ed25519PrivateKey,
    Ed25519PublicKey,
)
from cryptography.exceptions import InvalidSignature

from .digest import Digest


class PublicKey(bytes):
    """32-byte ed25519 public key."""

    __slots__ = ()

    def __new__(cls, b: bytes) -> "PublicKey":
        if len(b) != 32:
            raise ValueError(f"PublicKey must be 32 bytes, got {len(b)}")
        return super().__new__(cls, b)

    @classmethod
    def default(cls) -> "PublicKey":
        return cls(bytes(32))

    def encode_base64(self) -> str:
        return base64.b64encode(self).decode()

    @classmethod
    def decode_base64(cls, s: str) -> "PublicKey":
        return cls(base64.b64decode(s))

    def __repr__(self) -> str:
        return self.encode_base64()[:16]


class SecretKey(bytes):
    """32-byte ed25519 secret seed."""

    __slots__ = ()

    def __new__(cls, b: bytes) -> "SecretKey":
        if len(b) != 32:
            raise ValueError(f"SecretKey must be 32 bytes, got {len(b)}")
        return super().__new__(cls, b)

    def encode_base64(self) -> str:
        return base64.b64encode(self).decode()

    @classmethod
    def decode_base64(cls, s: str) -> "SecretKey":
        return cls(base64.b64decode(s))


class Signature(bytes):
    """64-byte ed25519 signature (R || S)."""

    __slots__ = ()

    def __new__(cls, b: bytes) -> "Signature":
        if len(b) != 64:
            raise ValueError(f"Signature must be 64 bytes, got {len(b)}")
        return super().__new__(cls, b)

    @classmethod
    def default(cls) -> "Signature":
        # An all-zero signature (never valid); used for unsigned placeholders
        # the way the reference uses Signature::default() in tests.
        return cls(bytes(64))

    def encode_base64(self) -> str:
        return base64.b64encode(self).decode()


class KeyPair:
    """An ed25519 identity: public name + secret seed.

    Reference config/src/lib.rs:249-271 (KeyPair with JSON import/export).
    """

    __slots__ = ("name", "secret", "_sk")

    def __init__(self, name: PublicKey, secret: SecretKey) -> None:
        self.name = name
        self.secret = secret
        self._sk = Ed25519PrivateKey.from_private_bytes(secret)

    @classmethod
    def generate(cls, rng_seed: Optional[bytes] = None) -> "KeyPair":
        """Generate a keypair; pass a 32-byte seed for deterministic fixtures
        (the reference tests seed StdRng with [0;32],
        reference primary/src/tests/common.rs:29-32)."""
        if rng_seed is None:
            sk = Ed25519PrivateKey.generate()
            seed = sk.private_bytes_raw()
        else:
            if len(rng_seed) != 32:
                raise ValueError("seed must be 32 bytes")
            seed = rng_seed
            sk = Ed25519PrivateKey.from_private_bytes(seed)
        pk = sk.public_key().public_bytes_raw()
        return cls(PublicKey(pk), SecretKey(seed))

    def sign(self, digest: Digest) -> Signature:
        return Signature(self._sk.sign(bytes(digest)))

    # --- JSON file import/export (reference config/src/lib.rs:28-56) ---

    def to_json(self) -> dict:
        return {"name": self.name.encode_base64(), "secret": self.secret.encode_base64()}

    @classmethod
    def from_json(cls, obj: dict) -> "KeyPair":
        return cls(
            PublicKey.decode_base64(obj["name"]),
            SecretKey.decode_base64(obj["secret"]),
        )


def cpu_verify(message: bytes, key: PublicKey, signature: Signature) -> bool:
    """Single strict-ish verification via OpenSSL."""
    try:
        Ed25519PublicKey.from_public_bytes(bytes(key)).verify(
            bytes(signature), bytes(message)
        )
        return True
    except (InvalidSignature, ValueError):
        return False
