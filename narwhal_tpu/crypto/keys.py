"""ed25519 keys and signatures (CPU reference implementation).

Mirrors reference crypto/src/lib.rs:64-220: `PublicKey`/`SecretKey` newtypes
with base64 serialization, deterministic keygen from a seeded RNG for test
fixtures, and 64-byte signatures.  The CPU implementation rides the
`cryptography` package (OpenSSL ed25519) when installed and falls back to
the dependency-free pure-Python RFC 8032 signer (`_ed25519_py`) otherwise —
same keys, signatures, and verify semantics, just slower per call.  The
TPU batched verifier lives in `narwhal_tpu.ops.ed25519` behind
`crypto.backend`.
"""

from __future__ import annotations

import base64
import os
from typing import Optional

try:
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    from cryptography.exceptions import InvalidSignature

    _HAVE_OPENSSL = True
except ImportError:  # minimal container: pure-Python fallback
    _HAVE_OPENSSL = False
    import warnings

    # Loud, once, at import: the fallback is correct but ~1000× slower and
    # NOT constant-time (Python big-int scalar muls branch on secret
    # nibbles).  A production image must ship the `cryptography` wheel —
    # this downgrade should be a deliberate choice, never a silent
    # accident of an incomplete build.
    warnings.warn(
        "narwhal_tpu.crypto: the `cryptography` package is not installed; "
        "falling back to the pure-Python ed25519 signer (slow, "
        "non-constant-time — fine for tests/benches, NOT for production "
        "keys)",
        RuntimeWarning,
        stacklevel=2,
    )

import hashlib as _hashlib
import time as _time

from .. import metrics as _metrics
from . import _ed25519_py
from .digest import Digest

# -- simulation MAC mode ------------------------------------------------------
#
# The deterministic simulation harness (narwhal_tpu/sim) replaces ed25519
# sign/verify with a keyed hash: sig = SHA-512(public_key ‖ message)[:64].
# Protocol-visible semantics are preserved — a signature only verifies
# against the key it was minted under, so the wrong_key Byzantine
# behavior still reads as invalid, twins stay validly signed, and every
# frame keeps its real wire size — but one op costs ~2 µs instead of the
# ~1-4 ms of the pure-Python fallback, which is what lets an N=20/50
# committee execute 60 virtual seconds in single-digit wall seconds.
# NOT a signature scheme (anyone holding the public key can forge);
# never enabled outside the sim harness, which brackets every run with
# set_sim_mac(True/False).

_SIM_MAC = False


def set_sim_mac(enabled: bool) -> None:
    global _SIM_MAC
    _SIM_MAC = bool(enabled)


def sim_mac_enabled() -> bool:
    return _SIM_MAC


def _sim_mac(public: bytes, message: bytes) -> bytes:
    return _hashlib.sha512(bytes(public) + bytes(message)).digest()[:64]

# Crypto-cost ledger, signing side: op counts and wall time per call
# site ("header" / "vote" via SignatureService, "other" for direct
# callers).  Memoized like the verify-side instruments in backend.py.
_sign_instruments_cache: dict = {}


def _sign_instruments(site: str):
    inst = _sign_instruments_cache.get(site)
    if inst is None:
        inst = _sign_instruments_cache[site] = (
            _metrics.counter(f"crypto.sign.ops.{site}"),
            _metrics.histogram(f"crypto.sign.seconds.{site}"),
        )
    return inst


class PublicKey(bytes):
    """32-byte ed25519 public key."""

    __slots__ = ()

    def __new__(cls, b: bytes) -> "PublicKey":
        if len(b) != 32:
            raise ValueError(f"PublicKey must be 32 bytes, got {len(b)}")
        return super().__new__(cls, b)

    @classmethod
    def default(cls) -> "PublicKey":
        return cls(bytes(32))

    def encode_base64(self) -> str:
        return base64.b64encode(self).decode()

    @classmethod
    def decode_base64(cls, s: str) -> "PublicKey":
        return cls(base64.b64decode(s))

    def __repr__(self) -> str:
        return self.encode_base64()[:16]


class SecretKey(bytes):
    """32-byte ed25519 secret seed."""

    __slots__ = ()

    def __new__(cls, b: bytes) -> "SecretKey":
        if len(b) != 32:
            raise ValueError(f"SecretKey must be 32 bytes, got {len(b)}")
        return super().__new__(cls, b)

    def encode_base64(self) -> str:
        return base64.b64encode(self).decode()

    @classmethod
    def decode_base64(cls, s: str) -> "SecretKey":
        return cls(base64.b64decode(s))


class Signature(bytes):
    """64-byte ed25519 signature (R || S)."""

    __slots__ = ()

    def __new__(cls, b: bytes) -> "Signature":
        if len(b) != 64:
            raise ValueError(f"Signature must be 64 bytes, got {len(b)}")
        return super().__new__(cls, b)

    @classmethod
    def default(cls) -> "Signature":
        # An all-zero signature (never valid); used for unsigned placeholders
        # the way the reference uses Signature::default() in tests.
        return cls(bytes(64))

    def encode_base64(self) -> str:
        return base64.b64encode(self).decode()


class KeyPair:
    """An ed25519 identity: public name + secret seed.

    Reference config/src/lib.rs:249-271 (KeyPair with JSON import/export).
    """

    __slots__ = ("name", "secret", "_sk", "_py_expanded")

    def __init__(self, name: PublicKey, secret: SecretKey) -> None:
        self.name = name
        self.secret = secret
        if _HAVE_OPENSSL:
            self._sk = Ed25519PrivateKey.from_private_bytes(secret)
        else:
            self._sk = None
            # Cache the expanded scalar/prefix: repeated fallback signing
            # then costs one base multiplication per call, not two.
            a, prefix = _ed25519_py._secret_expand(bytes(secret))
            self._py_expanded = (a, prefix, bytes(name))

    @classmethod
    def generate(cls, rng_seed: Optional[bytes] = None) -> "KeyPair":
        """Generate a keypair; pass a 32-byte seed for deterministic fixtures
        (the reference tests seed StdRng with [0;32],
        reference primary/src/tests/common.rs:29-32)."""
        if rng_seed is None:
            seed = os.urandom(32)
        elif len(rng_seed) != 32:
            raise ValueError("seed must be 32 bytes")
        else:
            seed = rng_seed
        if _HAVE_OPENSSL:
            sk = Ed25519PrivateKey.from_private_bytes(seed)
            pk = sk.public_key().public_bytes_raw()
        else:
            pk = _ed25519_py.secret_to_public(seed)
        return cls(PublicKey(pk), SecretKey(seed))

    def sign(self, digest: Digest, site: str = "other") -> Signature:
        ops, secs = _sign_instruments(site)
        t0 = _time.perf_counter()
        try:
            if _SIM_MAC:
                return Signature(_sim_mac(self.name, bytes(digest)))
            if self._sk is not None:
                return Signature(self._sk.sign(bytes(digest)))
            a, prefix, pub = self._py_expanded
            return Signature(
                _ed25519_py.sign_expanded(a, prefix, pub, bytes(digest))
            )
        finally:
            ops.inc()
            secs.observe(_time.perf_counter() - t0)

    # --- JSON file import/export (reference config/src/lib.rs:28-56) ---

    def to_json(self) -> dict:
        return {"name": self.name.encode_base64(), "secret": self.secret.encode_base64()}

    @classmethod
    def from_json(cls, obj: dict) -> "KeyPair":
        return cls(
            PublicKey.decode_base64(obj["name"]),
            SecretKey.decode_base64(obj["secret"]),
        )


def cpu_verify(message: bytes, key: PublicKey, signature: Signature) -> bool:
    """Single strict-ish verification via OpenSSL (pure-Python RFC 8032
    fallback when the `cryptography` package is absent)."""
    if _SIM_MAC:
        return _sim_mac(key, message) == bytes(signature)
    if not _HAVE_OPENSSL:
        return _ed25519_py.verify(bytes(key), bytes(message), bytes(signature))
    try:
        Ed25519PublicKey.from_public_bytes(bytes(key)).verify(
            bytes(signature), bytes(message)
        )
        return True
    except (InvalidSignature, ValueError):
        return False
