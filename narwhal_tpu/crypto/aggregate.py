"""ed25519 half-aggregation of certificate vote quorums (ROADMAP item 2).

A certificate at N=20 is 63% raw signature bytes (974 of 1546 B/frame,
artifacts/wire_n20_r19.json) and costs 2f+1 ed25519 verifications to
sanitize.  This module implements the ``halfagg`` certificate-signature
scheme (``NARWHAL_CERT_SIG_SCHEME`` / ``node run --cert-sig-scheme``):
the 2f+1 vote signatures over ONE digest are folded at
certificate-assembly time into a single aggregate blob that verifies
with ONE multi-exponentiation equation.

The construction is non-interactive half-aggregation of Schnorr/EdDSA
signatures (Chalkias–Garillot–Kondi–Nikolaenko, CT-RSA 2021): given
votes ``(Rᵢ, sᵢ)`` over message ``m`` under keys ``Aᵢ``, keep every
nonce commitment ``Rᵢ`` and replace the n scalar halves with one
random-linear combination

    s̄ = Σ zᵢ·sᵢ  (mod L),   zᵢ = H(domain ‖ m ‖ A₁‖R₁‖…‖Aₙ‖Rₙ ‖ i)

verified by the single equation

    s̄·B  ==  Σ zᵢ·Rᵢ + Σ (zᵢ·hᵢ mod L)·Aᵢ,   hᵢ = H(Rᵢ‖Aᵢ... (RFC 8032)

computed as one shared-window multiexp (``_ed25519_py.multi_scalar_mul``).

**Size honesty.**  The blob is ``32·(n+1)`` bytes — the n commitments
``Rᵢ`` CANNOT be dropped (each challenge ``hᵢ`` hashes its own ``Rᵢ``),
and CGKN prove this is essentially optimal for non-interactive EdDSA
aggregation.  So ``halfagg`` halves certificate signature bytes
(974 → 558 at N=20, fraction 0.63 → ~0.49); a CONSTANT-size aggregate
requires either pairings (BLS — a dependency this container does not
ship and a different key type) or 2-round interactive signing
(MuSig2/FROST — impossible here: votes are produced independently by
peers that don't yet know the final signer subset).  The ISSUE 20
aspiration of ``cert_sig_bytes_fraction ≤ 0.25`` prices that
pairing-based endgame; the measured half-agg numbers are recorded
as-is in the gate artifacts.

Sim-MAC mode (``keys.set_sim_mac``): the deterministic sim replaces
ed25519 with a keyed hash, and the aggregate analog keeps the exact
wire size — per-voter ``macᵢ[:32]`` plus one 32-byte closing binder —
so sim wire captures price ``halfagg`` frames byte-exactly while a
forged vote MAC still reads as invalid.
"""

from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence, Tuple

from .. import metrics
from ..utils.env import env_str
from . import _ed25519_py
from .keys import PublicKey, Signature, sim_mac_enabled, _sim_mac

__all__ = [
    "SCHEMES",
    "AggregateSignature",
    "SchemeMismatch",
    "aggregate_votes",
    "cert_sig_wire_bytes",
    "resolve_scheme",
    "scheme",
    "set_scheme",
    "verify_halfagg",
]

# The selectable certificate-signature schemes and their wire bytes
# (scheme byte 0/1 in the Certificate encoding, see primary/messages.py).
# Like the commit rules, a mixed committee is not supported: frames and
# checkpoints carry the scheme and refuse loudly on mismatch.
SCHEMES = ("individual", "halfagg")

_DOMAIN = b"NARWHAL-ED25519-HALFAGG-v1"
_SIM_DOMAIN = b"NARWHAL-SIMAGG-v1"

_L = _ed25519_py.L


class SchemeMismatch(ValueError):
    """Material produced under one cert-sig scheme was offered to a node
    running the other.  Deliberately loud (the CheckpointRuleMismatch
    pattern): silently parsing the other scheme's bytes would either
    misread signature material or re-verify history the store cannot
    replay — the operator flipped the flag on a live committee/store and
    must be told."""


def resolve_scheme(explicit: Optional[str] = None) -> str:
    """Effective scheme: the explicit (CLI) value wins, else the
    NARWHAL_CERT_SIG_SCHEME env knob, else ``individual``.  Garbage
    raises — a bench arm must never silently measure the wrong scheme
    (the resolve_commit_rule precedent)."""
    name = explicit if explicit is not None else env_str("NARWHAL_CERT_SIG_SCHEME")
    name = (name or "individual").strip().lower()
    if name not in SCHEMES:
        raise ValueError(
            f"unknown cert-sig scheme {name!r}; expected one of {SCHEMES}"
        )
    return name


_SCHEME_OVERRIDE: Optional[str] = None
_SCHEME_CACHE: Optional[str] = None


def scheme() -> str:
    """The process-wide certificate-signature scheme
    (``NARWHAL_CERT_SIG_SCHEME``, default ``individual``).  Read once
    per process — the scheme must not change under live certificates —
    unless a test/harness overrides it via :func:`set_scheme`."""
    global _SCHEME_CACHE
    if _SCHEME_OVERRIDE is not None:
        return _SCHEME_OVERRIDE
    if _SCHEME_CACHE is None:
        _SCHEME_CACHE = resolve_scheme()
    return _SCHEME_CACHE


def set_scheme(value: Optional[str]) -> None:
    """Test/A-B override: a scheme name forces the arm, None re-reads
    the environment on next use."""
    global _SCHEME_OVERRIDE, _SCHEME_CACHE
    if value is not None and value not in SCHEMES:
        raise ValueError(
            f"unknown cert-sig scheme {value!r}; expected one of {SCHEMES}"
        )
    _SCHEME_OVERRIDE = value
    _SCHEME_CACHE = None


def scheme_override() -> Optional[str]:
    """The current override (None = following the environment) — for
    harnesses that scope a temporary arm switch without clobbering an
    outer one (the wirev2.enabled_override pattern)."""
    return _SCHEME_OVERRIDE


# Which scheme this process runs, for the bench summary's scheme-aware
# signature-byte arithmetic and the A/B artifact's arm labelling
# (the wire.format_version gauge pattern).
metrics.gauge_fn(
    "crypto.cert_sig_scheme", lambda: float(SCHEMES.index(scheme()))
)


class AggregateSignature(bytes):
    """``32·(n+1)`` bytes: the n vote nonce commitments ``Rᵢ`` in signer
    order, then the 32-byte aggregated scalar ``s̄`` (sim-MAC mode: n
    truncated MACs then the closing binder — same widths)."""

    __slots__ = ()

    def __new__(cls, b: bytes) -> "AggregateSignature":
        if len(b) < 64 or len(b) % 32:
            raise ValueError(
                "AggregateSignature must be 32*(n+1) bytes for n >= 1 "
                f"signers, got {len(b)}"
            )
        return super().__new__(cls, b)

    @property
    def n_signers(self) -> int:
        return len(self) // 32 - 1


def _coefficients(message: bytes, publics: Sequence[bytes], rs: Sequence[bytes]) -> List[int]:
    """The random-oracle weights zᵢ.  Every zᵢ binds the FULL transcript
    (message, all keys, all commitments) plus its own index, so no
    signer can bias its own weight after seeing the others' — the
    rogue-key/wrong-subset resistance of the scheme lives here."""
    pre = hashlib.sha512()
    pre.update(_DOMAIN)
    pre.update(len(publics).to_bytes(2, "little"))
    pre.update(message)
    for a, r in zip(publics, rs):
        pre.update(a)
        pre.update(r)
    seed = pre.digest()
    return [
        int.from_bytes(
            hashlib.sha512(seed + i.to_bytes(2, "little")).digest(), "little"
        )
        % _L
        for i in range(len(publics))
    ]


def _sim_closing(message: bytes, publics: Sequence[bytes], macs: Sequence[bytes]) -> bytes:
    h = hashlib.sha512()
    h.update(_SIM_DOMAIN)
    h.update(message)
    for a, m in zip(publics, macs):
        h.update(a)
        h.update(m)
    return h.digest()[:32]


def aggregate_votes(
    digest: bytes, votes: Sequence[Tuple[PublicKey, Signature]]
) -> Tuple[List[PublicKey], AggregateSignature]:
    """Fold a quorum of votes over one certificate digest into
    ``(signers, aggregate)``.  Signers are sorted by key (the canonical
    committee order) so the aggregate — and the coefficients bound into
    it — are independent of vote arrival order; duplicates raise."""
    if not votes:
        raise ValueError("aggregate_votes: empty vote set")
    ordered = sorted(votes, key=lambda nv: bytes(nv[0]))
    signers = [name for name, _ in ordered]
    if len(set(signers)) != len(signers):
        raise ValueError("aggregate_votes: duplicate signer")
    publics = [bytes(name) for name in signers]
    message = bytes(digest)
    if sim_mac_enabled():
        macs = [bytes(sig) for _, sig in ordered]
        blob = b"".join(m[:32] for m in macs) + _sim_closing(
            message, publics, macs
        )
        return signers, AggregateSignature(blob)
    rs = [bytes(sig)[:32] for _, sig in ordered]
    zs = _coefficients(message, publics, rs)
    s_bar = 0
    for (_, sig), z in zip(ordered, zs):
        s = int.from_bytes(bytes(sig)[32:], "little")
        if s >= _L:
            raise ValueError("aggregate_votes: non-canonical scalar in vote")
        s_bar = (s_bar + z * s) % _L
    blob = b"".join(rs) + s_bar.to_bytes(32, "little")
    return signers, AggregateSignature(blob)


def verify_halfagg(
    message: bytes, publics: Sequence[bytes], blob: bytes
) -> bool:
    """ONE boolean for the whole quorum.  Strict on structure: exact
    blob width for the signer count, canonical s̄ < L, decompressible
    keys and commitments, no duplicate signers — a truncated, padded or
    bit-flipped aggregate is invalid, never a crash."""
    n = len(publics)
    if n == 0 or len(blob) != 32 * (n + 1):
        return False
    publics = [bytes(p) for p in publics]
    if any(len(p) != 32 for p in publics) or len(set(publics)) != n:
        return False
    message = bytes(message)
    if sim_mac_enabled():
        macs = [_sim_mac(p, message) for p in publics]
        for i, mac in enumerate(macs):
            if blob[32 * i : 32 * i + 32] != mac[:32]:
                return False
        return blob[32 * n :] == _sim_closing(message, publics, macs)
    e = _ed25519_py
    s_bar = int.from_bytes(blob[32 * n :], "little")
    if s_bar >= _L:
        return False
    rs = [blob[32 * i : 32 * i + 32] for i in range(n)]
    pairs = []
    for p_enc, r_enc in zip(publics, rs):
        a = e._point_decompress(p_enc)
        r = e._point_decompress(r_enc)
        if a is None or r is None:
            return False
        pairs.append((a, r))
    zs = _coefficients(message, publics, rs)
    terms = []
    for (a, r), z, p_enc, r_enc in zip(pairs, zs, publics, rs):
        h = e._sha512_mod_l(r_enc + p_enc + message)
        terms.append((z, r))
        terms.append((z * h % _L, a))
    return e._point_equal(
        e._point_mul_base(s_bar), e.multi_scalar_mul(terms)
    )


def cert_sig_wire_bytes(
    scheme_name: str, quorum: int, wire_version: int = 2
) -> int:
    """Signature material per certificate frame under a scheme — the
    formula the bench's wire summary derives `cert_sig_bytes_per_cert`
    from (replacing the hardcoded 96·q+64): header signature (64) plus,
    per scheme,

    - ``individual``: q × (key ref + 64-byte vote signature)
    - ``halfagg``:    q key refs + the 32·(q+1) aggregate blob

    Key refs are 1 byte under wire v2 (committee index) and 32 raw bytes
    under the legacy format."""
    if scheme_name not in SCHEMES:
        raise ValueError(
            f"unknown cert-sig scheme {scheme_name!r}; expected one of {SCHEMES}"
        )
    ref = 1 if wire_version == 2 else 32
    if scheme_name == "halfagg":
        return quorum * ref + 32 * (quorum + 1) + 64
    return quorum * (ref + 64) + 64
