"""32-byte digest newtype and the canonical protocol hash.

The reference hashes every protocol message with SHA-512 truncated to 32
bytes (reference worker/src/processor.rs:35, primary/src/messages.rs:70-84).
This framework keeps the 32-byte digest shape but uses **SHA-256**: the
per-batch digest is the worker data plane's hot hash (~100 MB/s of batch
bytes at the reference's local config), SHA-256 has hardware support
(SHA-NI / dedicated units) giving ~2.3× the SHA-512 throughput on the host
cores this runs on, and our canonical serde already makes digests
non-wire-compatible with the Rust reference, so SHA-512 bit-parity buys
nothing.  Security properties (256-bit collision-resistant hash) are
equivalent for the protocol's use.
"""

from __future__ import annotations

import base64
import hashlib

DIGEST_LEN = 32


class Digest(bytes):
    """32-byte content digest. Subclasses bytes: hashable, ordered, compact."""

    __slots__ = ()

    def __new__(cls, b: bytes) -> "Digest":
        if len(b) != DIGEST_LEN:
            raise ValueError(f"Digest must be {DIGEST_LEN} bytes, got {len(b)}")
        return super().__new__(cls, b)

    @classmethod
    def zero(cls) -> "Digest":
        return cls(bytes(DIGEST_LEN))

    def __repr__(self) -> str:  # short base64 like the reference's Debug impl
        return base64.b64encode(self).decode()[:16]


def digest32(data: bytes) -> Digest:
    """The protocol-wide 32-byte hash (see module docstring for why this is
    SHA-256 under the reference-parity name)."""
    return Digest(hashlib.sha256(data).digest())
