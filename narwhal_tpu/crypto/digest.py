"""32-byte digest newtype and the canonical protocol hash.

Every protocol message hashes with SHA-512 truncated to 32 bytes, exactly as
the reference does for batches, headers, votes and certificates (reference
worker/src/processor.rs:35, primary/src/messages.rs:70-84).
"""

from __future__ import annotations

import base64
import hashlib

DIGEST_LEN = 32


class Digest(bytes):
    """32-byte content digest. Subclasses bytes: hashable, ordered, compact."""

    __slots__ = ()

    def __new__(cls, b: bytes) -> "Digest":
        if len(b) != DIGEST_LEN:
            raise ValueError(f"Digest must be {DIGEST_LEN} bytes, got {len(b)}")
        return super().__new__(cls, b)

    @classmethod
    def zero(cls) -> "Digest":
        return cls(bytes(DIGEST_LEN))

    def __repr__(self) -> str:  # short base64 like the reference's Debug impl
        return base64.b64encode(self).decode()[:16]


def sha512_digest(data: bytes) -> Digest:
    """SHA-512 truncated to 32 bytes — the protocol-wide hash function."""
    return Digest(hashlib.sha512(data).digest()[:DIGEST_LEN])
