"""Pluggable verification backend: CPU reference vs. TPU batched kernel.

The reference's `Signature::verify_batch` (crypto/src/lib.rs:206-219) is the
per-round crypto hot spot — 2f+1 ed25519 verifications per certificate × N
certificates per round (SURVEY.md §3.3).  Here that call is a seam: the CPU
backend loops over OpenSSL verifies; the TPU backend ships the whole batch to
a vmapped JAX verifier (narwhal_tpu/ops/ed25519.py) in one dispatch.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple

from .. import metrics
from ..utils.env import env_flag, env_str
from .aggregate import AggregateSignature, verify_halfagg
from .digest import Digest
from .keys import PublicKey, Signature, cpu_verify

log = logging.getLogger("narwhal.crypto")

# -- crypto-cost ledger -------------------------------------------------------
#
# Every module-level verify entry point below is labelled with its CALL
# SITE so the bench's `crypto` section can attribute where verification
# ops (and their wall time) come from:
#
#   header / vote / certificate  inline sanitization (Header.verify,
#                                Vote.verify, Certificate.verify — the
#                                serial path)
#   batch_burst                  Core's accumulate→averify→replay seam
#                                (the batched path the ROADMAP item-1 A/B
#                                must show absorbing the serial ops)
#   certificate_agg              ONE half-aggregated quorum check under
#                                --cert-sig-scheme halfagg (whether it
#                                arrives serially via Certificate.verify
#                                or inside a burst batch) — ops count 1
#                                per certificate, which is the ledger
#                                witness for the "2f+1 → 1 verify"
#                                claim of ROADMAP item 2
#
# Per site: `crypto.verify.ops.<site>` (signature checks performed),
# `crypto.verify.seconds.<site>` (wall time per CALL — for the async
# batched path this includes event-loop yields/device round-trip, which
# is exactly the latency the caller pays), and
# `crypto.verify.batch_size.<site>` (ops per call — the serial→batched
# conversion shows up as mass moving to higher buckets).  The async
# batched path additionally records
# `crypto.verify.device_seconds.<site>`: the backend's own compute time
# (host prep + device dispatch + result sync), EXCLUDING event-loop
# yield/executor-queue time — without the split, the single wall
# histogram conflates "crypto is slow" with "the loop was busy", which
# under-credits pipelining in the A/B.
# Instrumentation lives HERE, on the module seam, so both the CPU and
# TPU backends are covered and backend-internal chunking is not
# double-counted.

_verify_instruments_cache: Dict[str, Tuple] = {}


def _verify_instruments(site: str):
    inst = _verify_instruments_cache.get(site)
    if inst is None:
        inst = _verify_instruments_cache[site] = (
            metrics.counter(f"crypto.verify.ops.{site}"),
            metrics.histogram(f"crypto.verify.seconds.{site}"),
            metrics.histogram(
                f"crypto.verify.batch_size.{site}", metrics.COUNT_BUCKETS
            ),
            metrics.histogram(f"crypto.verify.device_seconds.{site}"),
        )
    return inst


class CpuBackend:
    name = "cpu"

    def verify(self, message: bytes, key: PublicKey, sig: Signature) -> bool:
        return cpu_verify(message, key, sig)

    def verify_batch_mask(
        self,
        messages: Sequence[bytes],
        keys: Sequence[PublicKey],
        sigs: Sequence[Signature],
    ) -> List[bool]:
        return [cpu_verify(m, k, s) for m, k, s in zip(messages, keys, sigs)]

    # Inline chunk size: ~64 OpenSSL verifies ≈ 10 ms — the max the event
    # loop may stall between yields.  A thread handoff per burst was
    # measured strictly worse on core-starved hosts (GIL/scheduler
    # ping-pong, cf. store.py), so big bursts stay on-loop but cooperative.
    AVERIFY_CHUNK = 64

    async def averify_batch_mask(
        self,
        messages: Sequence[bytes],
        keys: Sequence[PublicKey],
        sigs: Sequence[Signature],
    ) -> List[bool]:
        mask, _ = await self.averify_batch_mask_timed(messages, keys, sigs)
        return mask

    async def averify_batch_mask_timed(
        self,
        messages: Sequence[bytes],
        keys: Sequence[PublicKey],
        sigs: Sequence[Signature],
    ) -> Tuple[List[bool], float]:
        """(mask, compute_seconds): compute time sums the synchronous
        verify chunks only — the inter-chunk event-loop yields are wall
        time the CALLER'S latency pays, not crypto cost."""
        n = len(messages)
        t0 = time.perf_counter()
        if n <= self.AVERIFY_CHUNK:
            return (
                self.verify_batch_mask(messages, keys, sigs),
                time.perf_counter() - t0,
            )
        import asyncio

        out: List[bool] = []
        compute = 0.0
        for i in range(0, n, self.AVERIFY_CHUNK):
            j = i + self.AVERIFY_CHUNK
            t0 = time.perf_counter()
            out.extend(self.verify_batch_mask(messages[i:j], keys[i:j], sigs[i:j]))
            compute += time.perf_counter() - t0
            # Yield between chunks so network/timers keep running during a
            # committee-sized burst (tens of ms of crypto at N=20+).
            await asyncio.sleep(0)
        return out, compute


_backend = CpuBackend()

# The batched JAX verifier runs on whatever platform JAX has — a real
# TPU or the jax-cpu mesh (the A/B fallback arm) — so "jax" is the
# honest spelling; "tpu" is kept as the historical alias.
_BATCHED_NAMES = ("tpu", "jax")


def set_backend(name: str, strict: Optional[bool] = None) -> None:
    """Select the verification backend: "cpu", or "jax"/"tpu" (the
    batched device verifier).

    A jax/tpu request whose import fails is a BOOT error, not a
    first-burst error: with ``strict`` (default: the
    NARWHAL_CRYPTO_BACKEND_STRICT flag, on) the import failure raises
    here, at selection time; with strict off it logs the import error
    and falls back to the cpu backend — an explicit, logged downgrade.
    """
    global _backend
    if name == "cpu":
        _backend = CpuBackend()
    elif name in _BATCHED_NAMES:
        try:
            from ..ops.ed25519 import TpuBackend  # deferred: JAX import is heavy
        except ImportError as e:
            if strict is None:
                strict = env_flag("NARWHAL_CRYPTO_BACKEND_STRICT")
            if strict:
                raise RuntimeError(
                    f"crypto backend {name!r} requested but the batched "
                    f"verifier failed to import: {e} — install jax/numpy "
                    "or set NARWHAL_CRYPTO_BACKEND_STRICT=0 to fall back "
                    "to the cpu backend"
                ) from e
            log.error(
                "crypto backend %r unavailable (%s); falling back to cpu "
                "(NARWHAL_CRYPTO_BACKEND_STRICT=0)", name, e,
            )
            _backend = CpuBackend()
            return
        _backend = TpuBackend()
    else:
        raise ValueError(f"unknown crypto backend {name!r}")


def set_backend_from_env(cli_choice: Optional[str] = None) -> str:
    """Boot-time backend selection: the CLI flag wins, then the
    NARWHAL_CRYPTO_BACKEND env knob, then "cpu".  Returns the name that
    was requested (the live backend's name may differ only under the
    non-strict fallback)."""
    name = cli_choice or env_str("NARWHAL_CRYPTO_BACKEND") or "cpu"
    set_backend(name)
    return name


def get_backend():
    return _backend


def verify_aggregate(
    message: bytes,
    signers: Sequence[PublicKey],
    agg: AggregateSignature,
    site: str = "certificate_agg",
) -> bool:
    """One half-aggregated quorum check: the whole 2f+1 vote set of a
    certificate is ONE op in the crypto ledger (`crypto.verify.ops.
    certificate_agg`).  The multiexp equation runs on the CPU fallback
    for now — a batched device multiexp kernel is the natural follow-up
    once the scheme flips default — so both backends route here."""
    ops, secs, sizes, _dev = _verify_instruments(site)
    t0 = time.perf_counter()
    try:
        return verify_halfagg(bytes(message), signers, bytes(agg))
    finally:
        ops.inc()
        sizes.observe(1)
        secs.observe(time.perf_counter() - t0)


def _split_aggregate_claims(messages, keys, sigs):
    """Partition a mixed claim batch into plain (message, key, sig)
    triples and aggregate (message, signer-tuple, AggregateSignature)
    claims — the shape Certificate.signature_claims emits under
    ``halfagg``.  Returns (plain_positions, plain triples, agg_positions,
    agg claims); plain order is preserved so the backend sees the same
    batch it would without aggregates present."""
    plain_pos: List[int] = []
    pm: List[bytes] = []
    pk: List[PublicKey] = []
    ps: List[Signature] = []
    agg_pos: List[int] = []
    aggs: List[Tuple[bytes, Sequence[PublicKey], AggregateSignature]] = []
    for i, (m, k, s) in enumerate(zip(messages, keys, sigs)):
        if isinstance(s, AggregateSignature):
            agg_pos.append(i)
            aggs.append((m, k, s))
        else:
            plain_pos.append(i)
            pm.append(m)
            pk.append(k)
            ps.append(s)
    return plain_pos, pm, pk, ps, agg_pos, aggs


def verify(
    message: bytes, key: PublicKey, sig: Signature, site: str = "other"
) -> bool:
    ops, secs, sizes, _dev = _verify_instruments(site)
    t0 = time.perf_counter()
    try:
        return _backend.verify(message, key, sig)
    finally:
        ops.inc()
        sizes.observe(1)
        secs.observe(time.perf_counter() - t0)


def verify_batch_mask(
    messages: Sequence[bytes],
    keys: Sequence[PublicKey],
    sigs: Sequence[Signature],
    site: str = "other",
) -> List[bool]:
    """Per-item validity mask for a batch of (message, key, signature).
    Aggregate claims (an AggregateSignature in the sig slot) are split
    out and checked one equation each under the ``certificate_agg``
    site; the plain remainder rides the selected backend unchanged."""
    if not (len(messages) == len(keys) == len(sigs)):
        raise ValueError("verify_batch: length mismatch")
    if not messages:
        return []
    if any(isinstance(s, AggregateSignature) for s in sigs):
        plain_pos, pm, pk, ps, agg_pos, aggs = _split_aggregate_claims(
            messages, keys, sigs
        )
        mask: List[bool] = [False] * len(messages)
        for pos, ok in zip(
            plain_pos, verify_batch_mask(pm, pk, ps, site=site) if pm else []
        ):
            mask[pos] = ok
        for pos, (m, k, s) in zip(agg_pos, aggs):
            mask[pos] = verify_aggregate(m, k, s)
        return mask
    ops, secs, sizes, _dev = _verify_instruments(site)
    t0 = time.perf_counter()
    try:
        return list(_backend.verify_batch_mask(messages, keys, sigs))
    finally:
        ops.inc(len(messages))
        sizes.observe(len(messages))
        secs.observe(time.perf_counter() - t0)


async def averify_batch_mask(
    messages: Sequence[bytes],
    keys: Sequence[PublicKey],
    sigs: Sequence[Signature],
    site: str = "other",
) -> List[bool]:
    """Async verify_batch_mask: the TPU backend runs the device round trip
    in an executor thread so the node's event loop (networking, proposer
    timers, waiters) keeps running during the dispatch+sync — without this,
    every Core burst would stall the whole primary for the device latency."""
    if not (len(messages) == len(keys) == len(sigs)):
        raise ValueError("verify_batch: length mismatch")
    if not messages:
        return []
    if any(isinstance(s, AggregateSignature) for s in sigs):
        # Mixed burst under halfagg: plain claims (header signatures,
        # votes) keep the async backend path; each aggregate claim is
        # one CPU multiexp with an event-loop yield between equations
        # (the AVERIFY_CHUNK discipline — ~10-30 ms per equation on the
        # pure-Python fallback must not starve timers at N=20 catch-up).
        import asyncio

        plain_pos, pm, pk, ps, agg_pos, aggs = _split_aggregate_claims(
            messages, keys, sigs
        )
        mask: List[bool] = [False] * len(messages)
        if pm:
            plain_mask = await averify_batch_mask(pm, pk, ps, site=site)
            for pos, ok in zip(plain_pos, plain_mask):
                mask[pos] = ok
        for pos, (m, k, s) in zip(agg_pos, aggs):
            mask[pos] = verify_aggregate(m, k, s)
            await asyncio.sleep(0)
        return mask
    ops, secs, sizes, dev = _verify_instruments(site)
    t0 = time.perf_counter()
    try:
        mask, compute_s = await _backend.averify_batch_mask_timed(
            messages, keys, sigs
        )
        # Backend-side compute only (host prep + dispatch + result sync)
        # vs the wall observation below, which additionally carries the
        # event-loop yields / executor-queue wait across the await.
        dev.observe(compute_s)
        return list(mask)
    finally:
        # Wall time across the await: includes event-loop yields and the
        # device round trip — the latency the calling burst actually pays.
        ops.inc(len(messages))
        sizes.observe(len(messages))
        secs.observe(time.perf_counter() - t0)


def verify_batch(
    digest: Digest,
    keys: Sequence[PublicKey],
    sigs: Sequence[Signature],
    site: str = "other",
) -> bool:
    """All-or-nothing batch verification of many signatures over ONE digest —
    the certificate-quorum check (reference primary/src/messages.rs:189-215).
    An empty batch is invalid: a zero-signature certificate must never pass."""
    if not keys:
        return False
    msgs = [bytes(digest)] * len(keys)
    return all(verify_batch_mask(msgs, keys, sigs, site=site))
