"""Pluggable verification backend: CPU reference vs. TPU batched kernel.

The reference's `Signature::verify_batch` (crypto/src/lib.rs:206-219) is the
per-round crypto hot spot — 2f+1 ed25519 verifications per certificate × N
certificates per round (SURVEY.md §3.3).  Here that call is a seam: the CPU
backend loops over OpenSSL verifies; the TPU backend ships the whole batch to
a vmapped JAX verifier (narwhal_tpu/ops/ed25519.py) in one dispatch.
"""

from __future__ import annotations

from typing import List, Sequence

from .digest import Digest
from .keys import PublicKey, Signature, cpu_verify


class CpuBackend:
    name = "cpu"

    def verify(self, message: bytes, key: PublicKey, sig: Signature) -> bool:
        return cpu_verify(message, key, sig)

    def verify_batch_mask(
        self,
        messages: Sequence[bytes],
        keys: Sequence[PublicKey],
        sigs: Sequence[Signature],
    ) -> List[bool]:
        return [cpu_verify(m, k, s) for m, k, s in zip(messages, keys, sigs)]

    # Inline chunk size: ~64 OpenSSL verifies ≈ 10 ms — the max the event
    # loop may stall between yields.  A thread handoff per burst was
    # measured strictly worse on core-starved hosts (GIL/scheduler
    # ping-pong, cf. store.py), so big bursts stay on-loop but cooperative.
    AVERIFY_CHUNK = 64

    async def averify_batch_mask(
        self,
        messages: Sequence[bytes],
        keys: Sequence[PublicKey],
        sigs: Sequence[Signature],
    ) -> List[bool]:
        n = len(messages)
        if n <= self.AVERIFY_CHUNK:
            return self.verify_batch_mask(messages, keys, sigs)
        import asyncio

        out: List[bool] = []
        for i in range(0, n, self.AVERIFY_CHUNK):
            j = i + self.AVERIFY_CHUNK
            out.extend(self.verify_batch_mask(messages[i:j], keys[i:j], sigs[i:j]))
            # Yield between chunks so network/timers keep running during a
            # committee-sized burst (tens of ms of crypto at N=20+).
            await asyncio.sleep(0)
        return out


_backend = CpuBackend()


def set_backend(name: str) -> None:
    """Select the verification backend: "cpu" or "tpu"."""
    global _backend
    if name == "cpu":
        _backend = CpuBackend()
    elif name == "tpu":
        try:
            from ..ops.ed25519 import TpuBackend  # deferred: JAX import is heavy
        except ImportError as e:
            raise NotImplementedError(
                "TPU crypto backend requires narwhal_tpu.ops.ed25519 "
                f"(import failed: {e})"
            ) from e
        _backend = TpuBackend()
    else:
        raise ValueError(f"unknown crypto backend {name!r}")


def get_backend():
    return _backend


def verify(message: bytes, key: PublicKey, sig: Signature) -> bool:
    return _backend.verify(message, key, sig)


def verify_batch_mask(
    messages: Sequence[bytes],
    keys: Sequence[PublicKey],
    sigs: Sequence[Signature],
) -> List[bool]:
    """Per-item validity mask for a batch of (message, key, signature)."""
    if not (len(messages) == len(keys) == len(sigs)):
        raise ValueError("verify_batch: length mismatch")
    if not messages:
        return []
    return list(_backend.verify_batch_mask(messages, keys, sigs))


async def averify_batch_mask(
    messages: Sequence[bytes],
    keys: Sequence[PublicKey],
    sigs: Sequence[Signature],
) -> List[bool]:
    """Async verify_batch_mask: the TPU backend runs the device round trip
    in an executor thread so the node's event loop (networking, proposer
    timers, waiters) keeps running during the dispatch+sync — without this,
    every Core burst would stall the whole primary for the device latency."""
    if not (len(messages) == len(keys) == len(sigs)):
        raise ValueError("verify_batch: length mismatch")
    if not messages:
        return []
    return list(await _backend.averify_batch_mask(messages, keys, sigs))


def verify_batch(
    digest: Digest,
    keys: Sequence[PublicKey],
    sigs: Sequence[Signature],
) -> bool:
    """All-or-nothing batch verification of many signatures over ONE digest —
    the certificate-quorum check (reference primary/src/messages.rs:189-215).
    An empty batch is invalid: a zero-signature certificate must never pass."""
    if not keys:
        return False
    msgs = [bytes(digest)] * len(keys)
    return all(verify_batch_mask(msgs, keys, sigs))
