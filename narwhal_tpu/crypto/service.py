"""SignatureService: an actor that owns the secret key and serializes signing.

Reference crypto/src/lib.rs:224-250 — callers send a digest over a channel
and receive the signature via oneshot.  In asyncio terms, a queue-fed task
resolving futures; callers `await service.request_signature(digest)`.
Serializing through one task keeps the secret key in one place and gives the
TPU build a natural batching point for outbound signing.
"""

from __future__ import annotations

import asyncio

from .. import metrics
from ..utils.tasks import spawn
from typing import Optional, Tuple

from .digest import Digest
from .keys import KeyPair, Signature


class SignatureService:
    def __init__(self, keypair: KeyPair) -> None:
        self._keypair = keypair
        self._queue: Optional[asyncio.Queue] = None
        self._task: Optional[asyncio.Task] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None

    def _ensure_started(self) -> None:
        loop = asyncio.get_running_loop()
        # Re-arm if never started, the task died, or we moved to a new loop
        # (e.g. successive asyncio.run calls in tests).
        if self._task is None or self._task.done() or self._loop is not loop:
            # Unbounded (capacity 0: never saturates, reported without a
            # utilization) — its residence histogram is the sign-request
            # queue wait, the number that shows when the single signer
            # actor becomes the backlog.
            self._queue = metrics.InstrumentedQueue(
                channel="crypto.sign_service"
            )
            self._loop = loop
            self._task = spawn(self._run(self._queue), name="signature-service")

    async def _run(self, queue: asyncio.Queue) -> None:
        while True:
            digest, fut, site = await queue.get()
            if fut.cancelled():
                continue
            try:
                # lint: allow-blocking(signing IS this actor's entire job and the protocol signs at most one header+one vote per round — ~0.6 ms on the pure-Python fallback, µs with `cryptography`; an executor hop would cost more in GIL ping-pong than the sign itself on shared-core hosts)
                fut.set_result(self._keypair.sign(digest, site=site))
            except Exception as e:  # propagate instead of wedging the actor
                fut.set_exception(e)

    async def request_signature(
        self, digest: Digest, site: str = "other"
    ) -> Signature:
        """``site`` labels the op in the crypto-cost ledger (the caller
        knows what the digest is — "header" for Header.new, "vote" for
        Vote.new)."""
        self._ensure_started()
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        item: Tuple[Digest, asyncio.Future, str] = (digest, fut, site)
        await self._queue.put(item)
        return await fut

    def sign_now(self, digest: Digest, site: str = "other") -> Signature:
        """Synchronous signing for non-async contexts (tests, tools)."""
        return self._keypair.sign(digest, site=site)

    def close(self) -> None:
        if self._task is not None and not self._task.done():
            self._task.cancel()
        self._task = None
        self._queue = None
        self._loop = None
