"""Pure-Python ed25519 (RFC 8032) — the dependency-free fallback signer.

`crypto/keys.py` rides OpenSSL via the `cryptography` package when it is
installed; hosts without it (minimal containers) fall back here so the
protocol stack, tests, and benches still run.  This is the textbook
RFC 8032 construction over Python ints: correct and compact, not fast
(~1 ms per scalar multiplication).  Production verification throughput
comes from the batched TPU kernel (`narwhal_tpu.ops.ed25519`) either way;
this module only has to keep single-signature sign/verify available.

Semantics match `cpu_verify`'s OpenSSL behavior for well-formed inputs:
cofactorless verification, s < L enforced (RFC 8032 §5.1.7), invalid
point encodings rejected.
"""

from __future__ import annotations

import hashlib

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = -121665 * pow(121666, P - 2, P) % P
_SQRT_M1 = pow(2, (P - 1) // 4, P)


def _sha512(s: bytes) -> bytes:
    return hashlib.sha512(s).digest()


def _sha512_mod_l(s: bytes) -> int:
    return int.from_bytes(_sha512(s), "little") % L


# Points are extended homogeneous coordinates (X, Y, Z, T), x = X/Z,
# y = Y/Z, x*y = T/Z.
def _point_add(p, q):
    px, py, pz, pt = p
    qx, qy, qz, qt = q
    a = (py - px) * (qy - qx) % P
    b = (py + px) * (qy + qx) % P
    c = 2 * pt * qt * D % P
    d = 2 * pz * qz % P
    e = b - a
    f = d - c
    g = d + c
    h = b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


_NEUTRAL = (0, 1, 1, 0)


def _point_mul(s: int, p):
    """Arbitrary-point scalar multiply, 4-bit fixed window: 14 precompute
    adds + 4 doublings/digit + ~1 add/digit ≈ 330 point ops vs ~384 for
    double-and-add."""
    if s <= 0:
        return _NEUTRAL
    row = [None] * 16
    row[1] = p
    for j in range(2, 16):
        row[j] = _point_add(row[j - 1], p)
    digits = []
    while s > 0:
        digits.append(s & 15)
        s >>= 4
    q = _NEUTRAL
    for d in reversed(digits):
        q = _point_add(q, q)
        q = _point_add(q, q)
        q = _point_add(q, q)
        q = _point_add(q, q)
        if d:
            q = _point_add(q, row[d])
    return q


# Fixed-base comb for the generator: _G_TABLE[i][j] = (j·16^i)·G, so a
# base multiplication is ≤ 64 additions and no doublings.  Built lazily —
# importers that never sign/verify don't pay the ~1000 point adds.
_G_TABLE = None


def _base_table():
    global _G_TABLE
    if _G_TABLE is None:
        tbl = []
        base = _G
        for _ in range(64):
            row = [None] * 16
            p = base
            for j in range(1, 16):
                row[j] = p
                p = _point_add(p, base)
            tbl.append(row)
            base = p  # 16·previous base
        _G_TABLE = tbl
    return _G_TABLE


def _point_mul_base(s: int):
    """s·G via the comb table."""
    tbl = _base_table()
    q = _NEUTRAL
    i = 0
    while s > 0:
        d = s & 15
        if d:
            q = _point_add(q, tbl[i][d])
        s >>= 4
        i += 1
    return q


def multi_scalar_mul(pairs):
    """Σ sᵢ·Pᵢ via Straus interleaved 4-bit windows: one shared doubling
    chain (≤ 256 doublings total) plus 14 precompute adds and ≤ 64
    digit adds PER POINT — ~74·n + 256 point ops for n terms, vs the
    ~400·n of independent `_point_mul` calls.  This is what makes the
    half-aggregated certificate check (one equation over 2·q+1 points)
    cheaper than q serial verifies even on the pure-Python fallback.

    ``pairs`` is a sequence of ``(scalar, point)`` with points in
    extended coordinates; scalars are taken mod nothing (callers reduce
    mod L), non-positive scalars contribute the neutral element."""
    live = [(s, p) for s, p in pairs if s > 0]
    if not live:
        return _NEUTRAL
    tables = []
    max_bits = 0
    for s, p in live:
        row = [None] * 16
        row[1] = p
        for j in range(2, 16):
            row[j] = _point_add(row[j - 1], p)
        tables.append(row)
        if s.bit_length() > max_bits:
            max_bits = s.bit_length()
    q = _NEUTRAL
    for i in range((max_bits + 3) // 4 - 1, -1, -1):
        q = _point_add(q, q)
        q = _point_add(q, q)
        q = _point_add(q, q)
        q = _point_add(q, q)
        shift = 4 * i
        for (s, _), row in zip(live, tables):
            d = (s >> shift) & 15
            if d:
                q = _point_add(q, row[d])
    return q


def _point_equal(p, q) -> bool:
    # x1/z1 == x2/z2  and  y1/z1 == y2/z2, avoiding inversions.
    return (
        (p[0] * q[2] - q[0] * p[2]) % P == 0
        and (p[1] * q[2] - q[1] * p[2]) % P == 0
    )


def _recover_x(y: int, sign: int):
    if y >= P:
        return None
    x2 = (y * y - 1) * pow(D * y * y + 1, P - 2, P) % P
    if x2 == 0:
        return None if sign else 0
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * _SQRT_M1 % P
    if (x * x - x2) % P != 0:
        return None
    if (x & 1) != sign:
        x = P - x
    return x


_G_Y = 4 * pow(5, P - 2, P) % P
_G_X = _recover_x(_G_Y, 0)
_G = (_G_X, _G_Y, 1, _G_X * _G_Y % P)


def _point_compress(p) -> bytes:
    zinv = pow(p[2], P - 2, P)
    x = p[0] * zinv % P
    y = p[1] * zinv % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def _point_decompress(s: bytes):
    if len(s) != 32:
        return None
    y = int.from_bytes(s, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


def _secret_expand(secret: bytes):
    h = _sha512(secret)
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


def secret_to_public(secret: bytes) -> bytes:
    a, _ = _secret_expand(secret)
    return _point_compress(_point_mul_base(a))


def sign(secret: bytes, msg: bytes) -> bytes:
    a, prefix = _secret_expand(secret)
    pub = _point_compress(_point_mul_base(a))
    return sign_expanded(a, prefix, pub, msg)


def sign_expanded(a: int, prefix: bytes, pub: bytes, msg: bytes) -> bytes:
    """Sign with a pre-expanded secret (`KeyPair` caches the expansion so
    repeated signing pays one base multiplication, not two)."""
    r = _sha512_mod_l(prefix + msg)
    r_enc = _point_compress(_point_mul_base(r))
    h = _sha512_mod_l(r_enc + pub + msg)
    s = (r + h * a) % L
    return r_enc + s.to_bytes(32, "little")


def verify(public: bytes, msg: bytes, signature: bytes) -> bool:
    if len(public) != 32 or len(signature) != 64:
        return False
    a = _point_decompress(public)
    if a is None:
        return False
    r_enc = signature[:32]
    r = _point_decompress(r_enc)
    if r is None:
        return False
    s = int.from_bytes(signature[32:], "little")
    if s >= L:
        return False
    h = _sha512_mod_l(r_enc + bytes(public) + msg)
    return _point_equal(_point_mul_base(s), _point_add(r, _point_mul(h, a)))
