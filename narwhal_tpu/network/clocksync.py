"""Per-peer clock-offset estimation from ReliableSender ACK round-trips.

Every reliable send already buys a round-trip: the peer validates the
frame and writes an ACK back (worker/primary receiver handlers).  By
stamping the ACK with the responder's wall clock and keeping the
sender's own send/receive wall stamps, each ACK yields one NTP-style
sample of the peer's clock offset:

    offset = t_peer - (t_send + t_recv) / 2      (peer_clock - my_clock)
    rtt    = t_recv - t_send

with worst-case error rtt/2 (the peer's stamp can sit anywhere inside
the round-trip).  Samples ride piggyback on protocol traffic — no probe
messages, no extra frames — and the per-peer estimator below filters
them by RTT (a queued or retransmitted exchange produces a fat RTT and
a correspondingly untrustworthy midpoint) and smooths the survivors.

The estimates are exported as gauges:

- ``clock.offset_ms.<addr>``             — smoothed (peer - self), ms;
- ``clock.offset_uncertainty_ms.<addr>`` — smoothed rtt/2 bound, ms;

and reconciled committee-wide at join time (benchmark/metrics_check
``snapshot_offsets_ms``): pairwise offsets only fix clock DIFFERENCES,
so the reconciliation anchors the committee mean to zero and assigns
each node the offset that makes its peer vector consistent — every
snapshot carries enough to place its own clock without any address→node
identity mapping.

Wire compatibility: a stamped ACK is ``b"Ack"`` + 8 little-endian
float64 bytes.  ``parse_ack`` accepts the legacy bare ``b"Ack"`` (and
any other payload) as "no stamp", so mixed-version committees degrade
to RTT-only instrumentation instead of failing.  ACK bytes are not part
of the wire ledger, so stamping does not perturb the goodput A/B.
"""

from __future__ import annotations

import struct
from typing import Dict, Optional, Tuple

from .. import metrics
from ..utils.clock import wall_now

_ACK_MAGIC = b"Ack"
_STAMP = struct.Struct("<d")
_STAMPED_LEN = len(_ACK_MAGIC) + _STAMP.size

# Clock-filter knobs (module constants, not env: the estimator must be
# bit-reproducible under sim, and nothing about them is deployment-
# shaped).  A sample is trusted when its RTT is within _RTT_GATE of the
# best RTT seen — fatter round-trips put the midpoint anywhere.
_RTT_GATE = 2.0
_EWMA_ALPHA = 0.2


def stamp_ack() -> bytes:
    """The ACK payload a receiver handler writes: magic + responder's
    wall clock at validation time."""
    return _ACK_MAGIC + _STAMP.pack(wall_now())


def parse_ack(payload: bytes) -> Optional[float]:
    """The responder's wall stamp, or None for a legacy/foreign ACK."""
    if len(payload) == _STAMPED_LEN and payload.startswith(_ACK_MAGIC):
        return _STAMP.unpack_from(payload, len(_ACK_MAGIC))[0]
    return None


class OffsetEstimator:
    """Smoothed (peer_clock - my_clock) from RTT-filtered ACK samples."""

    __slots__ = ("offset_s", "uncertainty_s", "min_rtt_s", "samples")

    def __init__(self) -> None:
        self.offset_s: Optional[float] = None
        self.uncertainty_s: Optional[float] = None
        self.min_rtt_s: Optional[float] = None
        self.samples = 0

    def add(self, offset_s: float, rtt_s: float) -> bool:
        """Fold in one sample; True if it passed the RTT gate."""
        rtt_s = max(0.0, rtt_s)
        if self.min_rtt_s is None or rtt_s < self.min_rtt_s:
            self.min_rtt_s = rtt_s
        elif rtt_s > self.min_rtt_s * _RTT_GATE + 1e-4:
            # Fat round-trip: queueing/retransmission noise dominates the
            # midpoint.  (The +1e-4 floor keeps the gate permissive when
            # min RTT is ~0, e.g. loopback and the sim's 1 ms grid.)
            return False
        bound = rtt_s / 2.0
        if self.offset_s is None:
            self.offset_s = offset_s
            self.uncertainty_s = bound
        else:
            a = _EWMA_ALPHA
            self.offset_s += a * (offset_s - self.offset_s)
            self.uncertainty_s += a * (bound - self.uncertainty_s)
        self.samples += 1
        return True


# Per-peer estimators, keyed by (source label, peer address) like the
# per-peer RTT instruments (network/reliable_sender._peer_instruments).
# In production the source label is "" — one process IS one node, every
# sender talking to the same peer feeds the same estimate, and the
# gauges above are exported.  The simulation runs the whole committee in
# ONE process against ONE registry, so its channels pass their node
# label as ``src``: estimates stay per-(src, dst) — never mixed across
# differently-skewed virtual nodes — and are read back through
# :func:`offsets_by_source` instead of gauges.
_ESTIMATORS: Dict[Tuple[str, str], Tuple[OffsetEstimator, object, object]] = {}


def _peer_clock(src: str, address: str):
    entry = _ESTIMATORS.get((src, address))
    if entry is None:
        entry = (
            OffsetEstimator(),
            metrics.gauge(f"clock.offset_ms.{address}") if not src else None,
            metrics.gauge(f"clock.offset_uncertainty_ms.{address}")
            if not src
            else None,
        )
        _ESTIMATORS[(src, address)] = entry
    return entry


def record_ack_sample(
    address: str,
    t_send: float,
    t_recv: float,
    t_peer: float,
    src: str = "",
) -> None:
    """Fold one stamped ACK exchange into ``address``'s offset estimate
    and refresh its gauges.  All stamps are ``wall_now()`` readings:
    ``t_send``/``t_recv`` on our clock, ``t_peer`` on the responder's."""
    est, g_off, g_unc = _peer_clock(src, address)
    offset = t_peer - (t_send + t_recv) / 2.0
    if est.add(offset, t_recv - t_send) and g_off is not None:
        g_off.set(round(est.offset_s * 1000.0, 3))
        g_unc.set(round(est.uncertainty_s * 1000.0, 3))


def peer_offset_ms(address: str, src: str = "") -> Optional[float]:
    """Current smoothed offset for ``address`` in ms, if estimated."""
    entry = _ESTIMATORS.get((src, address))
    if entry is None or entry[0].offset_s is None:
        return None
    return entry[0].offset_s * 1000.0


def offsets_by_source() -> Dict[str, Dict[str, Dict[str, float]]]:
    """Every current estimate, grouped by source label — the sim
    harness's read path (its shared registry cannot carry per-node
    gauges): ``{src: {addr: {offset_ms, uncertainty_ms, samples}}}``."""
    out: Dict[str, Dict[str, Dict[str, float]]] = {}
    for (src, addr), (est, _, _) in _ESTIMATORS.items():
        if est.offset_s is None:
            continue
        out.setdefault(src, {})[addr] = {
            "offset_ms": round(est.offset_s * 1000.0, 3),
            "uncertainty_ms": round((est.uncertainty_s or 0.0) * 1000.0, 3),
            "samples": est.samples,
        }
    return out


def reconcile_zero_mean(
    peer_offsets_ms: Dict[str, Dict[str, float]]
) -> Dict[str, float]:
    """Committee-wide reconciliation: pairwise estimates only fix clock
    DIFFERENCES, so anchor the committee mean to zero and give node ``n``
    (with ``k`` measured peers) the correction

        c_n = -(k / (k+1)) * mean_p(offset_ms[n][p])

    With a full peer vector (k = N-1) this is exactly ``skew_n -
    mean(skew)``: each peer offset estimates ``skew_p - skew_n``, so the
    mean is ``(S - skew_n)/(N-1) - skew_n`` and the scaling recovers the
    deviation from the committee mean.  Corrected stamp = raw - c_n/1000.
    Each node's correction needs only its OWN peer vector — every
    snapshot is self-sufficient, no address→node identity map required.
    """
    out: Dict[str, float] = {}
    for node, peers in peer_offsets_ms.items():
        vals = [v for v in peers.values() if v is not None]
        if not vals:
            out[node] = 0.0
            continue
        k = len(vals)
        out[node] = -(k / (k + 1.0)) * (sum(vals) / k)
    return out


def reset_estimators() -> None:
    """Drop all per-peer state (sim cross-run isolation: the registry's
    ``clock.*`` gauges are deleted between runs, and a retained smoothed
    estimate would leak the previous run's committee into this one)."""
    _ESTIMATORS.clear()
