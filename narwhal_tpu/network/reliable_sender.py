"""At-least-once sender with per-message delivery futures.

Reference network/src/reliable_sender.rs (248 LoC): every `send` returns a
`CancelHandler` — a future that resolves when the peer ACKs the message.
Un-ACKed messages are retransmitted across reconnects with exponential
backoff (200 ms ×2, capped 60 s; reliable_sender.rs:119,141-181), and the
caller abandons delivery by cancelling the future (dropping the handler,
reliable_sender.rs:193-197).  Quorum counting (QuorumWaiter, vote gathering)
is built directly on these futures.
"""

from __future__ import annotations

import asyncio
import collections
import functools
import logging
import random
import weakref
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from .. import metrics
from ..faults import netem as _netem
from ..utils.clock import wall_now
from ..utils.env import env_raw
from ..utils.tasks import spawn
from . import transport as _transport
from . import wirev2
from .clocksync import parse_ack, record_ack_sample
from .framing import (
    MAX_FRAME,
    STREAM_LIMIT,
    frame,
    parse_address,
    read_frame,
    sample_peers,
    tune_writer,
    write_frame,
)

log = logging.getLogger("narwhal.network")

_BACKOFF_START = 0.2
_BACKOFF_CAP_DEFAULT = 60.0

# How long connect failures against a NEVER-connected peer stay off the
# health gauge (boot stagger ≠ dead validator; see _Connection).  Well
# past any observed committee boot spread — wait_for_boot's own deadline
# is 60 s — and short enough that a validator already dead at our start
# is still named within the first minute.
_NEVER_CONNECTED_GRACE_S = 45.0


@functools.lru_cache(maxsize=8)
def _parse_backoff_cap(raw: str) -> float:
    # Memoized per raw value: backoff_cap() runs once per reconnect
    # attempt per peer, and a misconfigured value must not warn at retry
    # frequency forever.
    try:
        return max(_BACKOFF_START, float(raw))
    except ValueError:
        log.warning(
            "NARWHAL_NET_BACKOFF_MAX_S=%r is not a number; using %s s",
            raw, _BACKOFF_CAP_DEFAULT,
        )
        return _BACKOFF_CAP_DEFAULT


def backoff_cap() -> float:
    """Reconnect-backoff ceiling in seconds, env-tunable via
    ``NARWHAL_NET_BACKOFF_MAX_S``.  A 60 s ceiling is right for a dead
    peer but wrong for a short partition: every sender that backed off to
    the cap takes up to a minute to notice the heal.  Fault scenarios
    (and latency-sensitive deployments) lower it."""
    raw = env_raw("NARWHAL_NET_BACKOFF_MAX_S")
    if raw is None:
        return _BACKOFF_CAP_DEFAULT
    return _parse_backoff_cap(raw)


def next_backoff(
    delay: float,
    cap: Optional[float] = None,
    rng: random.Random = random,  # type: ignore[assignment]
) -> Tuple[float, float]:
    """One step of the reconnect schedule: ``(sleep_s, next_delay)``.

    The sleep is the current delay with 50-100% jitter applied — without
    it, every peer partitioned at the same instant retries in lockstep
    and thundering-herds the healed peer's accept queue forever (their
    backoff clocks stay phase-locked).  The next delay doubles toward the
    cap; the cap bounds the delay BEFORE jitter, so the worst-case sleep
    is exactly ``cap``."""
    cap = backoff_cap() if cap is None else cap
    delay = min(delay, cap)
    sleep = delay * (0.5 + 0.5 * rng.random())
    return sleep, min(delay * 2, cap)

class _Msg:
    """One queued message, across its whole delivery lifecycle (buffer →
    pending → possibly re-buffered after a lost connection).

    ``accounted`` tracks whether a COMPLETED write of this frame has
    already been charged to the wire ledger's per-type first-transmission
    counters: the first completed write is the protocol's cost, every
    later completed write is a retransmission (separate counters, so
    per-type protocol bytes are never inflated by link flaps — and a
    frame whose first write attempt died mid-stream still gets exactly
    one first-transmission count when it finally lands).  ``t0`` is the
    write timestamp while pending, for the per-peer ACK-RTT histogram;
    ``t0_wall`` is the same instant on the wall clock, paired with the
    peer's stamped ACK for the clock-offset estimator (clocksync).
    """

    __slots__ = ("data", "fut", "msg_type", "accounted", "t0", "t0_wall")

    def __init__(self, data: bytes, fut: asyncio.Future, msg_type: str):
        self.data = data
        self.fut = fut
        self.msg_type = msg_type
        self.accounted = False
        self.t0 = 0.0
        self.t0_wall = 0.0

# Counters are shared by every ReliableSender in the process (one registry
# per process); the per-peer detail below disaggregates when needed.
_m_frames = metrics.counter("net.reliable.frames_sent")
_m_bytes = metrics.counter("net.reliable.bytes_sent")
_m_retrans = metrics.counter("net.reliable.retransmissions")
_m_connect_fail = metrics.counter("net.reliable.connect_failures")
_m_acks = metrics.counter("net.reliable.acks_received")

# Wire-v2 coalescing instruments: one `flush` = one writer.write +
# drain() covering every frame the per-connection buffer held at wakeup
# (the Store.flush_deferred pattern applied to the socket).  The
# histogram is the acceptance series — mean frames_per_flush > 1 IS the
# syscall batching, measured, not inferred.
_m_flushes = metrics.counter("wire.out.flushes")
_h_frames_per_flush = metrics.histogram("wire.out.frames_per_flush")

# One flush is bounded so a deep backlog cannot turn into an unbounded
# buffered write (latency + memory): past this many payload bytes the
# loop writes, drains, and immediately continues on the remainder.
_FLUSH_MAX_BYTES = 1 << 20

# Worst-case growth of a v2 container over its raw frame (tag + op
# stream for every span a walker could legitimately yield); messages
# within this distance of MAX_FRAME are refused on the v2 path rather
# than risking a frame the receiver's cap would reject.
_V2_HEADROOM = 64 * 1024

# Live senders, for snapshot-time gauges: total un-ACKed backlog and how
# many peer connections are currently in reconnect backoff.  WeakSet so a
# closed sender's state stops being reported once collected.
_SENDERS: "weakref.WeakSet[ReliableSender]" = weakref.WeakSet()


def _connections():
    for sender in _SENDERS:
        yield from sender._connections.values()


metrics.gauge_fn(
    "net.reliable.pending_acks",
    lambda: sum(len(c.pending) + len(c.buffer) for c in _connections()),
)
metrics.gauge_fn(
    "net.reliable.peers_backing_off",
    lambda: sum(1 for c in _connections() if c.backing_off),
)
metrics.detail_fn(
    "net.reliable.pending_by_peer",
    lambda: {
        c.address: len(c.pending) + len(c.buffer)
        for c in _connections()
        if c.pending or c.buffer
    },
)


def _peer_instruments(address: str):
    """Per-peer instruments, memoized by name in the process registry so
    every sender talking to the same peer shares them.  These are what
    lets a health rule (or a human) name WHICH validator is slow:

    - ``net.reliable.peer.rtt_seconds.<addr>`` — ACK round-trip
      histogram (write → ACK, so it includes the peer's validation);
    - ``net.reliable.peer.retransmissions.<addr>`` — counter;
    - ``net.reliable.peer.consecutive_failures.<addr>`` — gauge, reset
      to 0 on a successful connect and reported only once the peer has
      accepted at least one connection or the boot-grace window has
      passed (boot-stagger must not read as a dead validator; the
      peer_unreachable rule's input);
    - ``net.reliable.peer.backing_off.<addr>`` — 0/1 gauge.
    """
    return (
        metrics.histogram(f"net.reliable.peer.rtt_seconds.{address}"),
        metrics.counter(f"net.reliable.peer.retransmissions.{address}"),
        metrics.gauge(f"net.reliable.peer.consecutive_failures.{address}"),
        metrics.gauge(f"net.reliable.peer.backing_off.{address}"),
    )


class _Connection:
    """Owns the channel to one peer: buffered retransmission until ACK.

    Invariants that delivery semantics rest on:
    - an item sits in exactly one of `buffer` (not yet written this
      connection) or `pending` (written, awaiting ACK) until its future is
      resolved or cancelled;
    - the peer ACKs frames in order, so each ACK consumes exactly one
      `pending` entry (cancelled entries included — their frame was written).
    """

    def __init__(self, address: str) -> None:
        self.address = address
        self.buffer: Deque[_Msg] = collections.deque()
        self.pending: Deque[_Msg] = collections.deque()
        self.wakeup = asyncio.Event()
        self.backing_off = False  # reconnect backoff state (metrics gauge)
        self.failures = 0  # consecutive connect failures (health rule input)
        # Whether this peer has EVER accepted a connection: failures are
        # reported to the health plane only after it has (or after the
        # boot-grace window below) — a committee boots staggered, and a
        # peer that simply hasn't bound its socket yet is
        # indistinguishable from our own early start.  Without the gate,
        # a slow boot under a low reconnect-backoff cap crosses the
        # peer_unreachable threshold and the latched FIRING event poisons
        # the run's anomaly record (caught by a fuzzed scenario's CLEAN
        # control arm firing peer_unreachable at boot).  A peer that dies
        # later was necessarily connected once, so real deaths still fire
        # within one evaluation interval.
        self.ever_connected = False
        self.created = asyncio.get_running_loop().time()
        (
            self._m_rtt,
            self._m_peer_retrans,
            self._g_failures,
            self._g_backoff,
        ) = _peer_instruments(address)
        self.task = spawn(self._keep_alive(), name="reliable-sender-conn")

    def push(self, data: bytes, fut: asyncio.Future, msg_type: str) -> None:
        self.buffer.append(_Msg(data, fut, msg_type))
        self.wakeup.set()

    def abort_all(self) -> None:
        """Fail every outstanding delivery (sender shutdown)."""
        for item in list(self.pending) + list(self.buffer):
            if not item.fut.done():
                item.fut.cancel()
        self.pending.clear()
        self.buffer.clear()

    def _requeue_pending(self) -> None:
        """Move un-ACKed items back to the front of the buffer, oldest first,
        dropping messages whose caller gave up (cancelled future)."""
        while self.pending:
            item = self.pending.pop()
            if not item.fut.cancelled():
                self.buffer.appendleft(item)
                # Written once, un-ACKed, will be written again: that is a
                # retransmission, the signal a flapping/slow peer leaves.
                _m_retrans.inc()
                self._m_peer_retrans.inc()

    async def _keep_alive(self) -> None:
        host, port = parse_address(self.address)
        delay = _BACKOFF_START
        try:
            while True:
                try:
                    # Fault-injection partition shim: a partitioned peer
                    # fails exactly like a dead host, through the same
                    # backoff/health accounting below.
                    if _netem.blocked(self.address):
                        raise OSError("netem: partitioned from peer")
                    reader, writer = await asyncio.open_connection(
                        host, port, limit=STREAM_LIMIT
                    )
                    tune_writer(writer)
                    reader, writer = _netem.wrap(self.address, reader, writer)
                except OSError as e:
                    log.debug("ReliableSender: cannot reach %s: %s", self.address, e)
                    _m_connect_fail.inc()
                    self.backing_off = True
                    self.failures += 1
                    # Boot-grace only, never a permanent blind spot: a
                    # peer that is ALREADY dead when this process starts
                    # (e.g. we restarted while it stayed down) was never
                    # connected, yet must still be reported once the
                    # stagger window has safely passed.
                    if self.ever_connected or (
                        asyncio.get_running_loop().time() - self.created
                        > _NEVER_CONNECTED_GRACE_S
                    ):
                        self._g_failures.set(self.failures)
                    self._g_backoff.set(1)
                    sleep_s, delay = next_backoff(delay)
                    await asyncio.sleep(sleep_s)
                    continue
                delay = _BACKOFF_START
                self.backing_off = False
                self.ever_connected = True
                self.failures = 0
                self._g_failures.set(0)
                self._g_backoff.set(0)
                try:
                    await self._exchange(reader, writer)
                except (ConnectionError, OSError, asyncio.IncompleteReadError) as e:
                    log.debug("ReliableSender: lost %s: %s", self.address, e)
                finally:
                    writer.close()
                    self._requeue_pending()
        finally:
            self._requeue_pending()

    async def _exchange(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        """Pipeline writes from the buffer; match ACK frames FIFO."""

        loop = asyncio.get_running_loop()
        v2 = wirev2.enabled()

        async def write_loop() -> None:
            # Wire v2: announce the format, then speak compressed frames
            # against a dictionary that lives and dies with THIS
            # connection (reconnect = fresh dictionaries on both sides,
            # so retransmitted frames re-encode and stale references
            # cannot survive a flap).  The HELLO is not a protocol
            # message: never in `pending`, never ACKed.
            enc_dict = None
            if v2:
                enc_dict = wirev2.DigestDict()
                writer.write(frame(wirev2.HELLO))
                await writer.drain()
                _m_bytes.inc(len(wirev2.HELLO))
                metrics.wire_account(
                    "out", "wire_hello", self.address, len(wirev2.HELLO)
                )
            while True:
                while self.buffer:
                    if not v2:
                        # Legacy arm: byte- and syscall-identical to the
                        # pre-v2 sender (one write_frame + drain per
                        # message) — the paired A/B's baseline.
                        item = self.buffer.popleft()
                        if item.fut.cancelled():
                            continue
                        # Into `pending` BEFORE the await: if the write
                        # (or this task) dies mid-frame, reconnect
                        # retransmits it rather than losing the message
                        # and wedging its future.
                        item.t0 = loop.time()
                        item.t0_wall = wall_now()
                        self.pending.append(item)
                        # lint: allow-interleave(_requeue_pending only runs after _exchange's finally has cancelled AND awaited this task — "let cancellation unwind so neither loop touches the deques after we return" — so the buffer/pending writes it performs can never interleave with this suspended frame write; read_loop only popleft()s entries this loop appended before the suspension, which is exactly the ACK-FIFO contract)
                        await write_frame(writer, item.data)
                        # Counted after the write returns (same
                        # convention as SimpleSender): a frame lost to a
                        # mid-write disconnect is not "sent" — its
                        # rewrite after reconnect is.
                        _m_frames.inc()
                        _m_bytes.inc(len(item.data))
                        metrics.wire_account(
                            "out", item.msg_type, self.address,
                            len(item.data), retransmit=item.accounted,
                        )
                        item.accounted = True
                        continue
                    # v2: drain the WHOLE buffer into one multi-frame
                    # write + a single drain().  Everything is staged in
                    # `pending` before the await, and NOTHING is
                    # accounted until the drain returns: a flush that
                    # dies mid-stream charges zero first-transmission
                    # bytes, and the eventual rewrite of each frame is
                    # its (single) first transmission — the _Msg
                    # accounting rules hold exactly, per frame, inside a
                    # coalesced flush.
                    blob = bytearray()
                    wrote = []
                    while self.buffer and len(blob) < _FLUSH_MAX_BYTES:
                        item = self.buffer.popleft()
                        if item.fut.cancelled():
                            continue
                        if len(item.data) > MAX_FRAME - _V2_HEADROOM:
                            # An incompressible payload hugging the cap
                            # could grow past MAX_FRAME under the
                            # container overhead; the receiver would
                            # reject it, killing the connection and
                            # retransmitting the same poison frame
                            # forever.  Rejected BEFORE compress() so
                            # the dictionary is never mutated by a
                            # frame the receiver won't see.
                            if not item.fut.done():
                                item.fut.set_exception(
                                    ValueError(
                                        f"message of {len(item.data)} "
                                        "bytes cannot ride a v2 frame "
                                        "within MAX_FRAME"
                                    )
                                )
                            continue
                        payload = wirev2.compress(
                            item.data, item.msg_type, enc_dict
                        )
                        item.t0 = loop.time()
                        item.t0_wall = wall_now()
                        self.pending.append(item)
                        blob += frame(payload)
                        wrote.append((item, len(payload)))
                    if not wrote:
                        continue
                    writer.write(bytes(blob))
                    await writer.drain()
                    _m_flushes.inc()
                    _h_frames_per_flush.observe(len(wrote))
                    for item, nbytes in wrote:
                        _m_frames.inc()
                        _m_bytes.inc(nbytes)
                        metrics.wire_account(
                            "out", item.msg_type, self.address, nbytes,
                            retransmit=item.accounted,
                            raw_nbytes=len(item.data),
                        )
                        item.accounted = True
                self.wakeup.clear()
                await self.wakeup.wait()
                if v2:
                    # Micro-batch: one zero-delay yield before draining.
                    # Everything already scheduled in this event-loop
                    # pass (a burst being processed, a broadcast loop,
                    # peers' frames just read) gets to push into the
                    # buffer first, so the burst leaves as ONE flush.
                    # Costs one ready-queue rotation — no timer, no
                    # measurable latency — and is the difference between
                    # frames_per_flush ~1 and the batched regime under
                    # load.
                    await asyncio.sleep(0)

        async def read_loop() -> None:
            while True:
                ack = await read_frame(reader)
                _m_acks.inc()
                # Exactly one pending entry per ACK frame — the peer ACKs
                # everything we wrote, including since-cancelled messages.
                if self.pending:
                    item = self.pending.popleft()
                    self._m_rtt.observe(loop.time() - item.t0)
                    # Stamped ACK → one NTP-style offset sample for this
                    # peer (legacy bare b"Ack" parses to None: mixed
                    # committees degrade to RTT-only, never fail).
                    t_peer = parse_ack(ack)
                    if t_peer is not None and item.t0_wall:
                        record_ack_sample(
                            self.address, item.t0_wall, wall_now(), t_peer
                        )
                    if not item.fut.done():
                        item.fut.set_result(ack)

        w = asyncio.get_running_loop().create_task(write_loop())
        r = asyncio.get_running_loop().create_task(read_loop())
        try:
            done, _ = await asyncio.wait({w, r}, return_when=asyncio.FIRST_COMPLETED)
            for t in done:
                exc = t.exception()
                if exc is not None:
                    raise exc
        finally:
            for t in (w, r):
                t.cancel()
            # Let cancellation unwind so neither loop touches the deques
            # after we return.
            await asyncio.gather(w, r, return_exceptions=True)


class ReliableSender:
    def __new__(cls):
        # Transport seam: see SimpleSender.__new__ — an installed
        # in-memory transport provides the drop-in counterpart (same
        # future-per-send delivery contract, resolved with the peer's
        # ACK) so every call site keeps writing `ReliableSender()`.
        sim = _transport.active()
        if sim is not None and cls is ReliableSender:
            return sim.reliable_sender()
        return super().__new__(cls)

    def __init__(self) -> None:
        self._connections: Dict[str, _Connection] = {}
        _SENDERS.add(self)

    def _connection(self, address: str) -> _Connection:
        conn = self._connections.get(address)
        if conn is None or conn.task.done():
            conn = _Connection(address)
            self._connections[address] = conn
        return conn

    def send(
        self, address: str, data: bytes, msg_type: str = "other"
    ) -> asyncio.Future:
        """Queue `data` for delivery; the returned future resolves with the
        peer's ACK payload.  Cancel it to abandon delivery.  ``msg_type``
        labels the frame in the wire-goodput ledger (the caller just
        encoded the message, so it knows; see metrics.WireLedger)."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        if len(data) > MAX_FRAME:
            fut.set_exception(
                ValueError(f"message of {len(data)} bytes exceeds MAX_FRAME")
            )
            return fut
        self._connection(address).push(data, fut, msg_type)
        return fut

    def broadcast(
        self, addresses: Sequence[str], data: bytes, msg_type: str = "other"
    ) -> List[asyncio.Future]:
        return [self.send(addr, data, msg_type) for addr in addresses]

    def lucky_broadcast(
        self,
        addresses: Sequence[str],
        data: bytes,
        nodes: int,
        msg_type: str = "other",
    ) -> List[asyncio.Future]:
        """Send to `nodes` random peers (reference reliable_sender.rs:91-100)."""
        return self.broadcast(sample_peers(addresses, nodes), data, msg_type)

    def close(self) -> None:
        for conn in self._connections.values():
            conn.task.cancel()
            conn.abort_all()
        self._connections.clear()
