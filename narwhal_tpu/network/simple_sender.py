"""Best-effort sender: one connection task per peer, drops on failure.

Reference network/src/simple_sender.rs (143 LoC): used for sync replies,
cleanup commands and helper responses — anything where the application-level
retry logic (timers + lucky_broadcast) already provides liveness.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, Sequence

from .. import metrics
from ..faults import netem as _netem
from ..utils.tasks import spawn
from . import transport as _transport
from . import wirev2
from .framing import (
    STREAM_LIMIT,
    frame,
    parse_address,
    read_frame,
    sample_peers,
    tune_writer,
    write_frame,
)

log = logging.getLogger("narwhal.network")

_QUEUE_CAP = 1_000

_m_frames = metrics.counter("net.simple.frames_sent")
_m_bytes = metrics.counter("net.simple.bytes_sent")
_m_dropped = metrics.counter("net.simple.dropped")

# Shared with ReliableSender: one flush = one writer.write + drain()
# covering every message the per-peer queue held at wakeup.  The
# batch_digest plane (a worker's highest-frequency connection) rides
# this sender, so its syscall batching lands in the same acceptance
# series.
_m_flushes = metrics.counter("wire.out.flushes")
_h_frames_per_flush = metrics.histogram("wire.out.frames_per_flush")

# Flush bounds, frames AND bytes: a deep backlog (the queue holds up to
# 1000 messages, each up to ~500 KB batch frames on the worker Helper
# path) must not turn into one multi-hundred-MB buffered write — same
# rationale as ReliableSender's _FLUSH_MAX_BYTES.
_FLUSH_MAX_FRAMES = 256
_FLUSH_MAX_BYTES = 1 << 20


class _Peer:
    def __init__(self, address: str) -> None:
        self.address = address
        # One shared channel name for every peer: the depth gauge is
        # last-writer-wins across instances but high-water is monotone
        # and the counters aggregate — the same committee-aggregated
        # convention as the sim registry.
        self.queue: asyncio.Queue = metrics.InstrumentedQueue(
            _QUEUE_CAP, channel="net.simple_sender"
        )
        self.task = spawn(self._run(), name="simple-sender-peer")

    async def _run(self) -> None:
        host, port = parse_address(self.address)
        while True:
            data, msg_type = await self.queue.get()
            try:
                # Fault-injection partition shim: best-effort semantics —
                # a partitioned peer's message is a visible drop.
                if _netem.blocked(self.address):
                    raise OSError("netem: partitioned from peer")
                reader, writer = await asyncio.open_connection(
                    host, port, limit=STREAM_LIMIT
                )
                tune_writer(writer)
                reader, writer = _netem.wrap(self.address, reader, writer)
            except OSError as e:
                log.debug("SimpleSender: cannot reach %s: %s", self.address, e)
                _m_dropped.inc()
                continue  # drop this message; try fresh on the next one
            # Drain-and-discard replies (e.g. ACKs) so the peer's writes
            # don't stall; best-effort senders ignore response content.
            drain = spawn(self._drain(reader))
            batch = []
            try:
                while True:
                    if not wirev2.enabled():
                        # Legacy arm: one write_frame + drain per message,
                        # byte- and syscall-identical to the pre-v2 path.
                        await write_frame(writer, data)
                        # Counted only after the write succeeds; the
                        # failure path below counts the in-flight message
                        # as dropped (this sender's whole contract is
                        # visible loss).
                        _m_frames.inc()
                        _m_bytes.inc(len(data))
                        metrics.wire_account(
                            "out", msg_type, self.address, len(data)
                        )
                        data, msg_type = await self.queue.get()
                        continue
                    # v2: one zero-delay yield (anything scheduled this
                    # loop pass gets to enqueue), then drain the whole
                    # queue into ONE write + drain().
                    await asyncio.sleep(0)
                    batch = [(data, msg_type)]
                    nbytes = len(data)
                    while (
                        len(batch) < _FLUSH_MAX_FRAMES
                        and nbytes < _FLUSH_MAX_BYTES
                    ):
                        try:
                            item = self.queue.get_nowait()
                        except asyncio.QueueEmpty:
                            break
                        batch.append(item)
                        nbytes += len(item[0])
                    writer.write(b"".join(frame(d) for d, _ in batch))
                    await writer.drain()
                    _m_flushes.inc()
                    _h_frames_per_flush.observe(len(batch))
                    for d, t in batch:
                        _m_frames.inc()
                        _m_bytes.inc(len(d))
                        metrics.wire_account("out", t, self.address, len(d))
                    batch = []
                    data, msg_type = await self.queue.get()
            except (ConnectionError, OSError) as e:
                # Every message of a failed coalesced flush is a visible
                # drop, exactly like the single in-flight message was.
                _m_dropped.inc(max(1, len(batch)))
                log.debug("SimpleSender: lost %s: %s", self.address, e)
            finally:
                drain.cancel()
                writer.close()

    @staticmethod
    async def _drain(reader: asyncio.StreamReader) -> None:
        try:
            while True:
                await read_frame(reader)
        except Exception:
            pass


class SimpleSender:
    def __new__(cls):
        # Transport seam: under an installed in-memory transport
        # (deterministic simulation) construction yields the sim
        # counterpart — call sites keep writing `SimpleSender()` and the
        # swap happens here, exactly like Receiver.spawn.  Subclasses
        # (none today) would build the TCP sender as written.
        sim = _transport.active()
        if sim is not None and cls is SimpleSender:
            return sim.simple_sender()
        return super().__new__(cls)

    def __init__(self) -> None:
        self._peers: Dict[str, _Peer] = {}

    def send(
        self, address: str, data: bytes, msg_type: str = "other"
    ) -> None:
        """``msg_type`` labels the frame in the wire-goodput ledger (the
        caller just encoded the message, so it knows)."""
        peer = self._peers.get(address)
        if peer is None or peer.task.done():
            peer = _Peer(address)
            self._peers[address] = peer
        try:
            peer.queue.put_nowait((data, msg_type))
        except asyncio.QueueFull:
            _m_dropped.inc()
            log.warning("SimpleSender: queue full for %s; dropping", address)

    def broadcast(
        self, addresses: Sequence[str], data: bytes, msg_type: str = "other"
    ) -> None:
        for addr in addresses:
            self.send(addr, data, msg_type)

    def lucky_broadcast(
        self,
        addresses: Sequence[str],
        data: bytes,
        nodes: int,
        msg_type: str = "other",
    ) -> None:
        """Send to `nodes` random peers (reference simple_sender.rs:76-85)."""
        self.broadcast(sample_peers(addresses, nodes), data, msg_type)

    def close(self) -> None:
        for peer in self._peers.values():
            peer.task.cancel()
        self._peers.clear()
