"""Host-side transport: TCP with length-delimited frames.

Mirrors the reference `network` crate (≈480 LoC): a `Receiver` dispatching
frames to a `MessageHandler` that can reply on the same socket, a
fire-and-forget `SimpleSender`, and an at-least-once `ReliableSender` whose
per-message futures double as delivery (quorum-counting) signals.  This is
deliberately host-side TCP: BFT peers are mutually untrusting machines, so
inter-authority traffic can never ride ICI collectives (SURVEY.md §2.4) —
the TPU surface is within an authority, not between them.
"""

from .framing import read_frame, write_frame, FrameError, MAX_FRAME
from .receiver import Receiver, Writer
from .simple_sender import SimpleSender
from .reliable_sender import ReliableSender

__all__ = [
    "read_frame",
    "write_frame",
    "FrameError",
    "MAX_FRAME",
    "Receiver",
    "Writer",
    "SimpleSender",
    "ReliableSender",
]
