"""The transport seam: who provides Receiver/SimpleSender/ReliableSender.

By default nobody — the three concrete TCP classes in this package build
themselves and this module is a single ``is None`` check on their
construction paths (zero cost for normal runs, same pattern as
``faults/netem.py``).  The deterministic simulation harness
(``narwhal_tpu/sim/transport.py``) installs an in-memory transport here
before booting a committee, and every ``Receiver.spawn(...)``,
``SimpleSender()``, ``ReliableSender()`` and BatchMaker client-socket
bind in the process routes through seeded in-process queues instead of
the kernel — the FoundationDB-style INetwork swap, at the seam the
reference architecture already isolates (SURVEY.md §2.4: inter-authority
traffic is a replaceable byte transport, never a device collective).

An installed transport must provide:

- ``spawn_receiver(address, handler, classify) -> receiver`` — an object
  with ``shutdown()`` (coroutine) and ``port``;
- ``simple_sender()`` / ``reliable_sender()`` — drop-in counterparts of
  the TCP senders (same ``send``/``broadcast``/``lucky_broadcast``/
  ``close`` surface; reliable futures resolve with the peer's ACK);
- ``create_tx_server(address, protocol_factory) -> server`` — the
  client-transaction ingress bind (an object with ``close()``), fed by
  the harness's in-memory clients.
"""

from __future__ import annotations

from typing import Optional

_ACTIVE: Optional[object] = None


def install(transport: Optional[object]) -> None:
    """Install (or with ``None`` clear) the process's active transport.
    The simulation harness brackets every run with install/uninstall so
    ordinary code never sees a stale transport."""
    global _ACTIVE
    _ACTIVE = transport


def active() -> Optional[object]:
    """The installed transport, or None (the TCP default)."""
    return _ACTIVE


def reset() -> None:
    install(None)
