"""TCP server: accept loop, one task per inbound connection, frames
dispatched to a `MessageHandler` which may write replies/ACKs back on the
same socket (reference network/src/receiver.rs:18-47).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Protocol

from .. import metrics
from ..utils.env import env_flag
from ..utils.tasks import spawn
from . import transport as _transport
from . import wirev2
from .framing import (
    STREAM_LIMIT,
    FrameError,
    frame,
    parse_address,
    read_frame,
    tune_writer,
    write_frame,
)

log = logging.getLogger("narwhal.network")

_m_frames_in = metrics.counter("net.recv.frames")
_m_bytes_in = metrics.counter("net.recv.bytes")
_m_bad_frames = metrics.counter("net.recv.bad_frames")

# ACK-coalescing instruments (wire v2): replies written during one burst
# of buffered inbound frames leave in ONE transport.write instead of one
# syscall per ACK — votes and ACKs stop riding one syscall each.
_m_ack_flushes = metrics.counter("wire.out.ack_flushes")
_h_acks_per_flush = metrics.histogram("wire.out.acks_per_flush")

# Backpressure floor for the coalesced reply path: replies are tiny, so
# drain() (which can suspend the dispatch loop) is only awaited once
# this much is buffered un-drained — a peer that stops reading ACKs
# still bounds our buffer, without paying a drain per reply.
_ACK_DRAIN_BYTES = 256 * 1024


class Writer:
    """Reply channel handed to the handler: writes frames back to the peer.

    Under wire v2 (``coalesce=True``) replies are buffered and flushed
    with one ``transport.write`` per event-loop turn: a burst of inbound
    frames dispatched back-to-back (the reader's buffer already held
    them) accumulates its ACKs and the scheduled flush fires when the
    loop next idles — the receiver-side mirror of the sender's
    frame-coalescing.  The legacy arm keeps the one-write-plus-drain-
    per-reply path byte- and syscall-identical."""

    __slots__ = ("_writer", "_buf", "_replies", "_scheduled", "_coalesce",
                 "_undrained")

    def __init__(
        self, writer: asyncio.StreamWriter, coalesce: bool = False
    ) -> None:
        self._writer = writer
        self._coalesce = coalesce
        self._buf = bytearray()
        self._replies = 0
        self._scheduled = False
        self._undrained = 0

    async def send(self, data: bytes) -> None:
        if not self._coalesce:
            await write_frame(self._writer, data)
            return
        self._buf += frame(data)
        self._replies += 1
        if not self._scheduled:
            self._scheduled = True
            asyncio.get_running_loop().call_soon(self.flush)
        if self._undrained >= _ACK_DRAIN_BYTES:
            self.flush()
            self._undrained = 0
            await self._writer.drain()

    def flush(self) -> None:
        self._scheduled = False
        if not self._buf:
            return
        if self._writer.is_closing():
            self._buf.clear()
            self._replies = 0
            return
        _m_ack_flushes.inc()
        _h_acks_per_flush.observe(self._replies)
        self._undrained += len(self._buf)
        self._writer.write(bytes(self._buf))
        self._buf.clear()
        self._replies = 0


class MessageHandler(Protocol):
    async def dispatch(self, writer: Writer, message: bytes) -> None: ...


class Receiver:
    """Binds `address` and dispatches every inbound frame to `handler`.

    ``classify`` (optional, ``bytes -> type-name``) is the plane's frame
    classifier (messages.frame_classifier over the plane's tag space):
    when present, every inbound frame is ALSO accounted per message type
    in the wire-goodput ledger — the receiver side of the sender/receiver
    reconciliation the bench's ``wire`` section reports.  Without it,
    frames are accounted under the "unframed" type so inbound totals
    still cover every byte."""

    def __init__(
        self, address: str, handler: MessageHandler, classify=None
    ) -> None:
        self.address = address
        self.handler = handler
        self.classify = classify
        self._server: asyncio.AbstractServer | None = None
        self._connections: set = set()
        self._closing = False

    @classmethod
    async def spawn(
        cls, address: str, handler: MessageHandler, classify=None
    ) -> "Receiver":
        # Transport seam: an installed in-memory transport (deterministic
        # simulation) owns every listener in the process — same handler
        # contract, frames arrive from seeded in-process queues instead
        # of sockets.
        sim = _transport.active()
        if sim is not None:
            return sim.spawn_receiver(address, handler, classify)
        self = cls(address, handler, classify)
        host, port = parse_address(address)
        # NARWHAL_BIND_ANY=1: listen on 0.0.0.0 with the committee port
        # instead of the advertised IP.  Multi-host deployments need this
        # whenever the reachable address is not on a local interface
        # (NAT'd/cloud public IPs); the reference node rewrites its listen
        # IP to 0.0.0.0 unconditionally (primary.rs:97-104).
        if env_flag("NARWHAL_BIND_ANY"):
            host = "0.0.0.0"
        self._server = await asyncio.start_server(
            self._on_connection, host, port, limit=STREAM_LIMIT
        )
        log.debug("Listening on %s", address)
        return self

    def _on_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        # Synchronous accept callback: register the handler task BEFORE any
        # await, so shutdown() can never miss a just-accepted connection
        # (Python ≥3.12 Server.wait_closed() blocks on every live handler).
        if self._closing:
            writer.close()
            return
        task = spawn(self._handle(reader, writer))
        self._connections.add(task)
        task.add_done_callback(self._connections.discard)

    @property
    def port(self) -> int:
        """Actual bound port (useful when spawned with port 0)."""
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername")
        # Per-peer attribution is the source IP only: the source port is
        # ephemeral, so peers are indistinguishable on localhost — the
        # outbound side (which knows the dialed address) carries the
        # precise per-peer split.
        peer_ip = peer[0] if isinstance(peer, tuple) else str(peer)
        tune_writer(writer)
        v2_capable = wirev2.enabled()
        w = Writer(writer, coalesce=v2_capable)
        # Per-connection wire-v2 state: a connection speaks v2 only after
        # its first frame is the sender's HELLO (ReliableSender does;
        # SimpleSender and legacy peers never do, and their raw frames
        # keep working on the same listener).  The decode dictionary is
        # connection state — a reconnect is a new connection, so stale
        # back-references cannot survive a flap by construction.
        v2_conn = False
        dec_dict = None
        first = True
        try:
            while True:
                message = await read_frame(reader)
                if first:
                    first = False
                    if v2_capable and message == wirev2.HELLO:
                        v2_conn = True
                        dec_dict = wirev2.DigestDict()
                        _m_frames_in.inc()
                        _m_bytes_in.inc(len(message))
                        metrics.wire_account(
                            "in", "wire_hello", peer_ip, len(message)
                        )
                        continue
                wire_len = len(message)
                if v2_conn:
                    try:
                        message = wirev2.decompress(message, dec_dict)
                    except FrameError:
                        # Typed into the ledger (the `frame_error` row of
                        # wire.in.*), then the connection dies: a corrupt
                        # or out-of-range reference means the dictionaries
                        # may have diverged, and only a reconnect (which
                        # resets both) is safe.
                        metrics.wire_account(
                            "in", "frame_error", peer_ip, wire_len
                        )
                        raise
                _m_frames_in.inc()
                _m_bytes_in.inc(wire_len)
                metrics.wire_account(
                    "in",
                    self.classify(message) if self.classify else "unframed",
                    peer_ip,
                    wire_len,
                    raw_nbytes=len(message),
                )
                await self.handler.dispatch(w, message)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass  # peer closed
        except FrameError as e:
            _m_bad_frames.inc()
            log.warning("Bad frame from %s: %s", peer, e)
        except Exception:
            log.exception("Handler error for peer %s", peer)
        finally:
            w.flush()  # any coalesced replies still buffered
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                pass

    async def shutdown(self) -> None:
        if self._server is not None:
            self._closing = True
            self._server.close()
            for task in list(self._connections):
                task.cancel()
            await asyncio.gather(*self._connections, return_exceptions=True)
            self._connections.clear()
            await self._server.wait_closed()
            self._server = None
