"""TCP server: accept loop, one task per inbound connection, frames
dispatched to a `MessageHandler` which may write replies/ACKs back on the
same socket (reference network/src/receiver.rs:18-47).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Protocol

from .. import metrics
from ..utils.env import env_flag
from ..utils.tasks import spawn
from . import transport as _transport
from .framing import (
    STREAM_LIMIT,
    FrameError,
    parse_address,
    read_frame,
    tune_writer,
    write_frame,
)

log = logging.getLogger("narwhal.network")

_m_frames_in = metrics.counter("net.recv.frames")
_m_bytes_in = metrics.counter("net.recv.bytes")
_m_bad_frames = metrics.counter("net.recv.bad_frames")


class Writer:
    """Reply channel handed to the handler: writes frames back to the peer."""

    __slots__ = ("_writer",)

    def __init__(self, writer: asyncio.StreamWriter) -> None:
        self._writer = writer

    async def send(self, data: bytes) -> None:
        await write_frame(self._writer, data)


class MessageHandler(Protocol):
    async def dispatch(self, writer: Writer, message: bytes) -> None: ...


class Receiver:
    """Binds `address` and dispatches every inbound frame to `handler`.

    ``classify`` (optional, ``bytes -> type-name``) is the plane's frame
    classifier (messages.frame_classifier over the plane's tag space):
    when present, every inbound frame is ALSO accounted per message type
    in the wire-goodput ledger — the receiver side of the sender/receiver
    reconciliation the bench's ``wire`` section reports.  Without it,
    frames are accounted under the "unframed" type so inbound totals
    still cover every byte."""

    def __init__(
        self, address: str, handler: MessageHandler, classify=None
    ) -> None:
        self.address = address
        self.handler = handler
        self.classify = classify
        self._server: asyncio.AbstractServer | None = None
        self._connections: set = set()
        self._closing = False

    @classmethod
    async def spawn(
        cls, address: str, handler: MessageHandler, classify=None
    ) -> "Receiver":
        # Transport seam: an installed in-memory transport (deterministic
        # simulation) owns every listener in the process — same handler
        # contract, frames arrive from seeded in-process queues instead
        # of sockets.
        sim = _transport.active()
        if sim is not None:
            return sim.spawn_receiver(address, handler, classify)
        self = cls(address, handler, classify)
        host, port = parse_address(address)
        # NARWHAL_BIND_ANY=1: listen on 0.0.0.0 with the committee port
        # instead of the advertised IP.  Multi-host deployments need this
        # whenever the reachable address is not on a local interface
        # (NAT'd/cloud public IPs); the reference node rewrites its listen
        # IP to 0.0.0.0 unconditionally (primary.rs:97-104).
        if env_flag("NARWHAL_BIND_ANY"):
            host = "0.0.0.0"
        self._server = await asyncio.start_server(
            self._on_connection, host, port, limit=STREAM_LIMIT
        )
        log.debug("Listening on %s", address)
        return self

    def _on_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        # Synchronous accept callback: register the handler task BEFORE any
        # await, so shutdown() can never miss a just-accepted connection
        # (Python ≥3.12 Server.wait_closed() blocks on every live handler).
        if self._closing:
            writer.close()
            return
        task = spawn(self._handle(reader, writer))
        self._connections.add(task)
        task.add_done_callback(self._connections.discard)

    @property
    def port(self) -> int:
        """Actual bound port (useful when spawned with port 0)."""
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername")
        # Per-peer attribution is the source IP only: the source port is
        # ephemeral, so peers are indistinguishable on localhost — the
        # outbound side (which knows the dialed address) carries the
        # precise per-peer split.
        peer_ip = peer[0] if isinstance(peer, tuple) else str(peer)
        tune_writer(writer)
        w = Writer(writer)
        try:
            while True:
                message = await read_frame(reader)
                _m_frames_in.inc()
                _m_bytes_in.inc(len(message))
                metrics.wire_account(
                    "in",
                    self.classify(message) if self.classify else "unframed",
                    peer_ip,
                    len(message),
                )
                await self.handler.dispatch(w, message)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass  # peer closed
        except FrameError as e:
            _m_bad_frames.inc()
            log.warning("Bad frame from %s: %s", peer, e)
        except Exception:
            log.exception("Handler error for peer %s", peer)
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                pass

    async def shutdown(self) -> None:
        if self._server is not None:
            self._closing = True
            self._server.close()
            for task in list(self._connections):
                task.cancel()
            await asyncio.gather(*self._connections, return_exceptions=True)
            self._connections.clear()
            await self._server.wait_closed()
            self._server = None
