"""TCP server: accept loop, one task per inbound connection, frames
dispatched to a `MessageHandler` which may write replies/ACKs back on the
same socket (reference network/src/receiver.rs:18-47).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Protocol

from .framing import FrameError, parse_address, read_frame, write_frame

log = logging.getLogger(__name__)


class Writer:
    """Reply channel handed to the handler: writes frames back to the peer."""

    __slots__ = ("_writer",)

    def __init__(self, writer: asyncio.StreamWriter) -> None:
        self._writer = writer

    async def send(self, data: bytes) -> None:
        await write_frame(self._writer, data)


class MessageHandler(Protocol):
    async def dispatch(self, writer: Writer, message: bytes) -> None: ...


class Receiver:
    """Binds `address` and dispatches every inbound frame to `handler`."""

    def __init__(self, address: str, handler: MessageHandler) -> None:
        self.address = address
        self.handler = handler
        self._server: asyncio.AbstractServer | None = None

    @classmethod
    async def spawn(cls, address: str, handler: MessageHandler) -> "Receiver":
        self = cls(address, handler)
        host, port = parse_address(address)
        self._server = await asyncio.start_server(self._on_connection, host, port)
        log.debug("Listening on %s", address)
        return self

    @property
    def port(self) -> int:
        """Actual bound port (useful when spawned with port 0)."""
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def _on_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername")
        w = Writer(writer)
        try:
            while True:
                message = await read_frame(reader)
                await self.handler.dispatch(w, message)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass  # peer closed
        except FrameError as e:
            log.warning("Bad frame from %s: %s", peer, e)
        except Exception:
            log.exception("Handler error for peer %s", peer)
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                pass

    async def shutdown(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
