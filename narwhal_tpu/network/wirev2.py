"""Wire-format v2: the per-connection compression layer of the goodput
overhaul (ROADMAP item 5).

The r12 wire ledger measured goodput_ratio 0.24 — three of every four
wire bytes were protocol overhead — and the r16 profiler found the
one-syscall-per-frame socket path costing as much committee CPU as the
ed25519 fallback itself.  Wire v2 attacks both, behind ONE flag
(``NARWHAL_WIRE_V2``, default on; ``=0`` is the byte-identical legacy
arm the paired A/B runs against):

- **frame coalescing** lives in ReliableSender/Receiver (one
  ``writer.write`` + one ``drain()`` per wakeup; ACK replies batch the
  same way) — this module only carries the shared flag;
- **digest-reference compression** (this module): a per-connection
  sender/receiver dictionary replaces repeated 32-byte digest/key spans
  with small back-references.  The sender decides which spans are
  dictionary material (schema-registered walkers, see
  :func:`register_spans`) and tells the receiver explicitly via ADD ops,
  so the decoder needs NO schema: decode is a pure, lossless transform
  whatever the walkers said.  Dictionaries are connection state — reset
  on reconnect on both sides, so a retransmitted frame re-encodes
  against a fresh dictionary and stale references cannot survive a
  connection flap;
- **transparent residual deflate**: after digest patching, large
  residuals (batch frames — 98.8% of all r12 wire bytes) are
  deflate-compressed when that actually shrinks them, with a raw
  escape so incompressible payloads cost one tag byte, never an
  expansion.

Compressed-frame anatomy (the payload of one length-delimited frame on
a negotiated v2 connection)::

    0xF2 | uvarint n_ops | (uvarint gap, uvarint ref)* | residual
    0xF3 | uvarint n_ops | (uvarint gap, uvarint ref)* | deflate(residual)

``gap`` is the count of residual bytes copied before the op; ``ref=0``
is ADD (the next 32 residual bytes are a span — insert into the
dictionary on both sides), ``ref>=1`` references the dictionary entry
of age ``ref-1`` (0 = most recently added).  Anything malformed — bad
tag, out-of-range reference, truncated ops, oversized inflate — is a
typed :class:`~narwhal_tpu.network.framing.FrameError`: the receiver
counts it into ``wire.in.*`` and kills the connection (a corrupt
reference means the dictionaries may have diverged; reconnect resets
both sides).

Version negotiation is the first frame of a connection: a v2 sender
writes :data:`HELLO` before anything else; a v2 receiver that sees it
switches that connection to v2 decode (and never dispatches it).  The
flag is committee-wide — mixed-version committees are not supported
(README "Wire format v2").  SimpleSender connections never send HELLO
and stay on legacy framing; the in-memory sim transport moves frames
without a byte layer, so only the compact message encodings (the other
half of wire v2, in the ``messages`` modules) apply there.
"""

from __future__ import annotations

import zlib
from typing import Callable, Dict, List, Optional

from .. import metrics
from ..utils.env import env_flag
from ..utils.serde import write_uvarint as _uvarint
from .framing import MAX_FRAME, FrameError

# First frame of every v2 ReliableSender connection.  0xF1 collides with
# no plane's tag space (all real tags are < 0x10), so a legacy receiver
# classifies it "unknown" and drops it — visible, not corrupting.
HELLO = b"\xf1NW2\x01"

TAG_PLAIN = 0xF2
TAG_DEFLATE = 0xF3

# Dictionary capacity per connection direction.  Bounded so a long-lived
# connection cannot grow without limit; 512 spans cover several rounds
# of parents/payload digests and the whole committee's keys at N=50.
DICT_CAP = 512

# Residuals below this skip the deflate attempt: control frames are
# already compact post-patching and zlib's header would eat the gain.
_DEFLATE_MIN = 1024
_DEFLATE_LEVEL = 1

_m_dict_hits = metrics.counter("net.wirev2.dict_hits")
_m_deflated = metrics.counter("net.wirev2.deflated_frames")

# Which wire format this process speaks, for the bench summary's
# format-aware arithmetic (cert signature fraction) and the A/B
# artifact's arm labelling.
metrics.gauge_fn(
    "wire.format_version", lambda: 2.0 if enabled() else 1.0
)

_ENABLED_OVERRIDE: Optional[bool] = None
_ENABLED_CACHE: Optional[bool] = None


def enabled() -> bool:
    """The process-wide wire-v2 flag (``NARWHAL_WIRE_V2``, default on).
    Read once per process — the format must not change under live
    connections — unless a test overrides it via :func:`set_enabled`."""
    global _ENABLED_CACHE
    if _ENABLED_OVERRIDE is not None:
        return _ENABLED_OVERRIDE
    if _ENABLED_CACHE is None:
        _ENABLED_CACHE = env_flag("NARWHAL_WIRE_V2")
    return _ENABLED_CACHE


def set_enabled(value: Optional[bool]) -> None:
    """Test/A-B override: True/False forces the arm, None re-reads the
    environment on next use."""
    global _ENABLED_OVERRIDE, _ENABLED_CACHE
    _ENABLED_OVERRIDE = value
    _ENABLED_CACHE = None


def enabled_override() -> Optional[bool]:
    """The current override (None = following the environment) — for
    callers that need to scope a temporary arm switch without
    clobbering an outer override (the audit replay's arm sniffing)."""
    return _ENABLED_OVERRIDE


class DigestDict:
    """One connection direction's bounded span dictionary.

    Insertion-ordered ring of ``cap`` 32-byte spans, oldest evicted.
    References are AGES (0 = most recently added): both sides apply
    identical ADDs in identical frame order over an ordered byte stream,
    so ages agree at every decode instant without any agreement
    protocol.  Encoder and decoder share this one class so the two
    sides' eviction arithmetic can never drift.
    """

    __slots__ = ("cap", "slots", "serial_of", "count")

    def __init__(self, cap: int = DICT_CAP) -> None:
        self.cap = cap
        self.slots: List[bytes] = []  # ring, slot = serial % cap
        self.serial_of: Dict[bytes, int] = {}  # span -> insertion serial
        self.count = 0  # total inserts ever

    def add(self, span: bytes) -> None:
        slot = self.count % self.cap
        if self.count >= self.cap:
            evicted = self.slots[slot]
            if self.serial_of.get(evicted) == self.count - self.cap:
                del self.serial_of[evicted]
            self.slots[slot] = span
        else:
            self.slots.append(span)
        self.serial_of[span] = self.count
        self.count += 1

    def ref_for(self, span: bytes) -> Optional[int]:
        """Age of ``span`` if it is still resident, else None."""
        serial = self.serial_of.get(span)
        if serial is None:
            return None
        age = self.count - 1 - serial
        return age if age < self.cap else None

    def get(self, age: int) -> bytes:
        """The span of ``age``; FrameError on an out-of-range reference
        (the typed corrupt-frame signal the receiver counts)."""
        if age < 0 or age >= min(self.count, self.cap):
            raise FrameError(
                f"digest reference age {age} outside dictionary "
                f"({min(self.count, self.cap)} entries)"
            )
        return self.slots[(self.count - 1 - age) % self.cap]


# --- span registry -----------------------------------------------------------
#
# msg_type (the wire-ledger label the sender already passes) -> walker
# returning the byte offsets of the frame's 32-byte dictionary-material
# spans (digests, public keys).  Registered by the messages modules next
# to their encoders.  Walkers are best-effort: compression correctness
# NEVER depends on them (ADD/REF ops are explicit in the wire format) —
# a wrong or failing walker only costs compression ratio, so any parse
# error degrades to "no spans".

_SPAN_FNS: Dict[str, Callable[[bytes], List[int]]] = {}


def register_spans(msg_type: str, fn: Callable[[bytes], List[int]]) -> None:
    _SPAN_FNS[msg_type] = fn


def spans_for(msg_type: str, data: bytes) -> List[int]:
    fn = _SPAN_FNS.get(msg_type)
    if fn is None:
        return []
    try:
        spans = fn(data)
    except Exception:
        return []
    # Sanitize: sorted, in-bounds, non-overlapping — compress() trusts
    # this shape.
    out: List[int] = []
    last_end = 0
    for off in sorted(spans):
        if off < last_end or off + 32 > len(data):
            continue
        out.append(off)
        last_end = off + 32
    return out


def _read_uvarint(data: bytes, pos: int) -> tuple:
    result = 0
    shift = 0
    n = len(data)
    while True:
        if pos >= n:
            raise FrameError("truncated varint in compressed frame")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise FrameError("oversized varint in compressed frame")


# Deflate memo for span-free frames (their compressed form is
# connection-independent): a batch broadcast deflates once, not once per
# peer.  Bounded FIFO — broadcast fan-out reuses entries within
# microseconds, so a small cap suffices.
_DEFLATE_MEMO: Dict[bytes, bytes] = {}
_DEFLATE_MEMO_CAP = 64


def _deflate(residual: bytes) -> Optional[bytes]:
    """Deflated residual when that actually helps, else None."""
    if len(residual) < _DEFLATE_MIN:
        return None
    packed = zlib.compress(residual, _DEFLATE_LEVEL)
    # Require a real win (>= 1/8 saved): borderline frames keep the raw
    # path so the receiver never inflates for nothing.
    if len(packed) + (len(residual) >> 3) >= len(residual):
        return None
    return packed


def compress(data: bytes, msg_type: str, enc: DigestDict) -> bytes:
    """One frame -> its v2 compressed payload, updating ``enc`` exactly
    as the receiver's dictionary will be updated on decode."""
    spans = spans_for(msg_type, data)
    if not spans:
        memo = _DEFLATE_MEMO.get(data)
        if memo is not None:
            return memo
    ops = bytearray()
    residual = bytearray()
    pos = 0
    n_ops = 0
    for off in spans:
        span = data[off:off + 32]
        ref = enc.ref_for(span)
        _uvarint(ops, off - pos)
        residual += data[pos:off]
        if ref is not None:
            _uvarint(ops, ref + 1)
            _m_dict_hits.inc()
        else:
            ops.append(0)
            residual += span
            enc.add(span)
        pos = off + 32
        n_ops += 1
    residual += data[pos:]
    packed = _deflate(bytes(residual))
    head = bytearray()
    if packed is not None:
        head.append(TAG_DEFLATE)
        _uvarint(head, n_ops)
        out = bytes(head) + bytes(ops) + packed
        _m_deflated.inc()
    else:
        head.append(TAG_PLAIN)
        _uvarint(head, n_ops)
        out = bytes(head) + bytes(ops) + bytes(residual)
    if not spans:
        if len(_DEFLATE_MEMO) >= _DEFLATE_MEMO_CAP:
            _DEFLATE_MEMO.clear()
        _DEFLATE_MEMO[data] = out
    return out


def decompress(payload: bytes, dec: DigestDict) -> bytes:
    """One v2 compressed payload -> the original frame bytes, updating
    ``dec``.  Raises FrameError on anything malformed."""
    if not payload:
        raise FrameError("empty v2 frame")
    tag = payload[0]
    if tag not in (TAG_PLAIN, TAG_DEFLATE):
        raise FrameError(f"bad v2 frame tag 0x{tag:02x}")
    n_ops, pos = _read_uvarint(payload, 1)
    if n_ops > MAX_FRAME // 32:
        raise FrameError(f"v2 frame claims {n_ops} ops")
    ops = []
    for _ in range(n_ops):
        gap, pos = _read_uvarint(payload, pos)
        ref, pos = _read_uvarint(payload, pos)
        ops.append((gap, ref))
    residual = payload[pos:]
    if tag == TAG_DEFLATE:
        d = zlib.decompressobj()
        try:
            residual = d.decompress(residual, MAX_FRAME + 1)
        except zlib.error as e:
            raise FrameError(f"corrupt deflate residual: {e}") from None
        if len(residual) > MAX_FRAME or d.unconsumed_tail:
            raise FrameError("inflated residual exceeds frame cap")
    out = bytearray()
    rpos = 0
    for gap, ref in ops:
        if rpos + gap > len(residual):
            raise FrameError("gap overruns residual")
        out += residual[rpos:rpos + gap]
        rpos += gap
        if ref == 0:  # ADD: next 32 residual bytes are the span
            if rpos + 32 > len(residual):
                raise FrameError("ADD op overruns residual")
            span = bytes(residual[rpos:rpos + 32])
            rpos += 32
            out += span
            dec.add(span)
        else:
            out += dec.get(ref - 1)
        if len(out) > MAX_FRAME:
            raise FrameError("decompressed frame exceeds cap")
    out += residual[rpos:]
    if len(out) > MAX_FRAME:
        raise FrameError("decompressed frame exceeds cap")
    return bytes(out)
