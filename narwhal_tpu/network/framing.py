"""Length-delimited framing: u32 little-endian length prefix + payload.

The reference uses tokio-util's LengthDelimitedCodec (4-byte prefix) over
TCP — reference network/src/receiver.rs:70, simple_sender.rs:107.
"""

from __future__ import annotations

import asyncio
import random
import struct

_LEN = struct.Struct("<I")

# Batches are ≤ ~500 kB; headers/certs are tiny. 32 MiB is a generous cap
# that still rejects garbage/hostile length prefixes.
MAX_FRAME = 32 * 1024 * 1024

# Stream buffer limit for every reader/writer that can carry batch frames.
# asyncio's default is 64 KiB, which turns each ~500 kB frame into ~8
# pause/resume event-loop round trips; when many node processes share few
# cores each round trip costs a scheduling quantum and the ACK RTT — and
# with it quorum throughput — collapses.  An 8 MiB window moves whole
# batches per wakeup.
STREAM_LIMIT = 8 * 1024 * 1024


def tune_writer(writer: "asyncio.StreamWriter") -> None:
    """Raise the transport's write high-water mark so large frames are
    buffered in one go instead of trickling out 64 KiB per drain cycle."""
    try:
        writer.transport.set_write_buffer_limits(high=STREAM_LIMIT)
    except (AttributeError, RuntimeError):  # non-socket transports (tests)
        pass


class FrameError(Exception):
    pass


async def read_frame(reader: asyncio.StreamReader) -> bytes:
    hdr = await reader.readexactly(_LEN.size)
    (n,) = _LEN.unpack(hdr)
    if n > MAX_FRAME:
        raise FrameError(f"frame of {n} bytes exceeds cap {MAX_FRAME}")
    if n == 0:
        return b""
    return await reader.readexactly(n)


def frame(data: bytes) -> bytes:
    return _LEN.pack(len(data)) + data


async def write_frame(writer: asyncio.StreamWriter, data: bytes) -> None:
    if len(data) > MAX_FRAME:
        # Enforced on write too: an oversized frame would otherwise make the
        # receiver kill the connection and a reliable sender retransmit the
        # same poison frame in a hot loop.
        raise FrameError(f"refusing to send {len(data)}-byte frame (cap {MAX_FRAME})")
    writer.write(_LEN.pack(len(data)))
    writer.write(data)
    await writer.drain()


def parse_address(addr: str):
    host, _, port = addr.rpartition(":")
    return host, int(port)


def sample_peers(addresses, nodes: int, rng: random.Random = random):  # type: ignore[assignment]
    """Pick `nodes` distinct random peers for lucky_broadcast.  ``rng``
    is injectable (the sim transport passes its seeded per-sender stream
    so lucky sampling replays bit-identically per (seed, spec); socketed
    senders default to the module RNG)."""
    addrs = list(addresses)
    return rng.sample(addrs, min(nodes, len(addrs)))
