"""Deterministic binary codec for protocol messages.

The reference serializes every protocol message with bincode (little-endian,
u64 length prefixes) — e.g. reference primary/src/core.rs:129 — and hashes
messages over a field-by-field byte encoding (reference
primary/src/messages.rs:70-84).  We use one deterministic codec for both
purposes: fixed-width little-endian integers, u32 length prefixes (cheaper
than bincode's u64 and sufficient: frames are < 4 GiB), and sorted maps/sets
(BTreeMap/BTreeSet semantics) so that encoding is canonical.
"""

from __future__ import annotations

import struct

_U8 = struct.Struct("<B")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


def write_uvarint(buf: bytearray, v: int) -> None:
    """LEB128 into an existing buffer — the one encoder loop shared by
    :meth:`Writer.uvarint` and the wire-v2 op stream builder."""
    if v < 0:
        raise ValueError("uvarint: negative value")
    while v >= 0x80:
        buf.append((v & 0x7F) | 0x80)
        v >>= 7
    buf.append(v)


class Writer:
    """Append-only byte sink."""

    __slots__ = ("_buf",)

    def __init__(self) -> None:
        self._buf = bytearray()

    def u8(self, v: int) -> "Writer":
        self._buf += _U8.pack(v)
        return self

    def u32(self, v: int) -> "Writer":
        self._buf += _U32.pack(v)
        return self

    def u64(self, v: int) -> "Writer":
        self._buf += _U64.pack(v)
        return self

    def uvarint(self, v: int) -> "Writer":
        """Unsigned LEB128 — the wire-v2 width for rounds, counts and
        lengths: protocol integers are tiny (rounds grow by one, counts
        are committee-sized) so the fixed u32/u64 widths of the legacy
        encoding are mostly zero bytes."""
        write_uvarint(self._buf, v)
        return self

    def raw(self, b: bytes) -> "Writer":
        """Fixed-size field; caller guarantees the width (e.g. 32-byte digest)."""
        self._buf += b
        return self

    def bytes(self, b: bytes) -> "Writer":
        """Variable-size field: u32 length prefix + payload."""
        self._buf += _U32.pack(len(b))
        self._buf += b
        return self

    def finish(self) -> bytes:
        return bytes(self._buf)


class Reader:
    """Sequential decoder over a byte buffer."""

    __slots__ = ("_buf", "_pos")

    def __init__(self, buf: bytes) -> None:
        self._buf = buf
        self._pos = 0

    def _take(self, n: int) -> bytes:
        p = self._pos
        if p + n > len(self._buf):
            raise ValueError("serde: buffer underrun")
        self._pos = p + n
        return self._buf[p : p + n]

    def u8(self) -> int:
        return _U8.unpack(self._take(1))[0]

    def u32(self) -> int:
        return _U32.unpack(self._take(4))[0]

    def u64(self) -> int:
        return _U64.unpack(self._take(8))[0]

    def uvarint(self) -> int:
        """Unsigned LEB128, capped at 64 bits so a hostile frame cannot
        make the decoder build an unbounded integer."""
        result = 0
        shift = 0
        while True:
            b = self._take(1)[0]
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                return result
            shift += 7
            if shift > 63:
                raise ValueError("serde: uvarint exceeds 64 bits")

    def tell(self) -> int:
        """Current decode offset (the wire-v2 digest-span walkers read
        this to record field positions while parsing)."""
        return self._pos

    def raw(self, n: int) -> bytes:
        return self._take(n)

    def bytes(self) -> bytes:
        n = self.u32()
        return self._take(n)

    def done(self) -> bool:
        return self._pos == len(self._buf)

    def expect_done(self) -> None:
        if not self.done():
            raise ValueError(
                f"serde: {len(self._buf) - self._pos} trailing bytes after decode"
            )
