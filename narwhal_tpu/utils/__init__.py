from . import serde  # noqa: F401
