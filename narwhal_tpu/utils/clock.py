"""``loop_now()`` — the protocol plane's one clock.

Every age/retry/deadline computation that lives ON the event loop reads
this instead of ``time.monotonic()``.  In production the two are the
same clock (asyncio's default ``loop.time()`` IS ``time.monotonic()``),
so this is a pure refactor there — but under the deterministic
simulation harness (``narwhal_tpu/sim``) the running loop is a
:class:`~narwhal_tpu.sim.clock.VirtualClockLoop` whose ``time()``
advances only at quiesce, and every retry window, sync age and wedge
timer rides the simulated clock with it.  A wall-clock read left behind
in a retry path would measure ~zero elapsed time across a 60-virtual-
second scenario and silently disable that path in simulation.

Callers off the loop (metrics snapshot threads) fall back to
``time.monotonic()`` — consistent in production, and simulation runs
everything on the one loop so the fallback never fires there.
"""

from __future__ import annotations

import asyncio
import time


def loop_now() -> float:
    """The running event loop's time, or ``time.monotonic()`` when called
    outside any loop (snapshot/scrape threads)."""
    try:
        return asyncio.get_running_loop().time()
    except RuntimeError:
        return time.monotonic()
