"""``loop_now()`` / ``wall_now()`` — the protocol plane's two clocks.

Every age/retry/deadline computation that lives ON the event loop reads
``loop_now()`` instead of ``time.monotonic()``.  In production the two
are the same clock (asyncio's default ``loop.time()`` IS
``time.monotonic()``), so this is a pure refactor there — but under the
deterministic simulation harness (``narwhal_tpu/sim``) the running loop
is a :class:`~narwhal_tpu.sim.clock.VirtualClockLoop` whose ``time()``
advances only at quiesce, and every retry window, sync age and wedge
timer rides the simulated clock with it.  A wall-clock read left behind
in a retry path would measure ~zero elapsed time across a 60-virtual-
second scenario and silently disable that path in simulation.

Callers off the loop (metrics snapshot threads) fall back to
``time.monotonic()`` — consistent in production, and simulation runs
everything on the one loop so the fallback never fires there.

``wall_now()`` is the TIMESTAMP clock: what gets written into trace
stamps and ACK payloads so cross-node joins can compare times.  In
production it is ``time.time()`` untouched.  The simulation installs a
virtual base (``set_wall_base``) so stamps are deterministic, and each
sim node may run inside a ``skew_scope`` — a contextvar offset modeling
that node's wall clock running ahead/behind true time.  The skew-
injection regression arm exists BECAUSE the two clocks differ: cross-
node comparisons of raw ``wall_now()`` stamps are only valid after the
clocksync offset correction (benchmark/metrics_check.py).
"""

from __future__ import annotations

import asyncio
import contextlib
import contextvars
import time
from typing import Callable, Iterator, Optional

_wall_base: Optional[Callable[[], float]] = None
_wall_skew: contextvars.ContextVar[float] = contextvars.ContextVar(
    "narwhal_wall_skew", default=0.0
)


def loop_now() -> float:
    """The running event loop's time, or ``time.monotonic()`` when called
    outside any loop (snapshot/scrape threads)."""
    try:
        return asyncio.get_running_loop().time()
    except RuntimeError:
        return time.monotonic()


def wall_now() -> float:
    """Epoch-style timestamp as THIS node's wall clock reads it: the
    installed base clock (``time.time()`` in production, the virtual
    loop clock under sim) plus the current context's injected skew."""
    base = _wall_base() if _wall_base is not None else time.time()
    return base + _wall_skew.get()


def set_wall_base(fn: Optional[Callable[[], float]]) -> None:
    """Install (or, with None, remove) the base wall clock.  The sim
    harness points this at its virtual loop's ``time()`` so every stamp
    is deterministic per (seed, spec); production never calls it."""
    global _wall_base
    _wall_base = fn


def current_skew() -> float:
    """The wall-clock skew (seconds) injected into the current context."""
    return _wall_skew.get()


@contextlib.contextmanager
def skew_scope(offset_s: float) -> Iterator[None]:
    """Run the enclosed code with ``wall_now()`` shifted by ``offset_s``
    seconds — the per-node virtual clock offset of the sim's skew-
    injection arm.  Contextvar-scoped, so tasks spawned inside inherit
    the node's skew and tasks outside are untouched."""
    token = _wall_skew.set(_wall_skew.get() + offset_s)
    try:
        yield
    finally:
        _wall_skew.reset(token)
