"""The NARWHAL_* environment-variable registry and its typed accessors.

Every env knob the runtime (or the bench harness) reads is DECLARED here
— name, type, documented default, one doc line — and read through the
typed accessors below.  Two consumers keep the registry honest:

- the invariant linter (``python -m narwhal_tpu.analysis``): any
  ``NARWHAL_*`` literal in the tree that is not declared here fails the
  ``env-var-registry`` rule, as does a direct ``os.environ`` read outside
  this module and a declared entry nothing reads;
- the README "Environment variables" table is generated from this
  registry (``python -m narwhal_tpu.analysis --env-table``) and
  drift-checked by the same lint run, so the doc cannot rot.

Parsing behavior shared by every accessor: accept a valid override, fall
back LOUDLY on garbage, and warn once per (name, raw value) rather than
at call-site frequency (some of these are read on hot paths — per retry
sweep, per inbound frame).  Flags parse uniformly: unset → the declared
default; set → false only for ``0``/empty/``false``/``no``/``off``
(case-insensitive), true otherwise.

The reconnect-backoff cap in network/reliable_sender.py keeps its own
parser on top of :func:`env_raw` — its semantics clamp to a float floor
rather than falling back on garbage.
"""

from __future__ import annotations

import functools
import logging
import os
from dataclasses import dataclass
from typing import Dict, Mapping, Optional

log = logging.getLogger("narwhal.config")

_UNSET = object()


@dataclass(frozen=True)
class EnvVar:
    """One declared knob.  ``default`` is the value the accessors fall
    back to when the variable is unset (``None`` = no value / feature
    off); ``shown_default`` overrides how the README table renders it
    when the effective default is computed at the call site."""

    name: str
    kind: str  # "flag" | "int" | "float" | "str"
    default: object
    doc: str
    shown_default: Optional[str] = None

    @property
    def rendered_default(self) -> str:
        if self.shown_default is not None:
            return self.shown_default
        if self.default is None:
            return "unset"
        if self.kind == "flag":
            return "1" if self.default else "0"
        return str(self.default)


_VARS = [
    # -- core runtime ---------------------------------------------------------
    EnvVar(
        "NARWHAL_LOG", "str", None,
        "Log level for the whole `narwhal.*` hierarchy (equivalent of "
        "`node run --log-level`; wins over `-v`).",
    ),
    EnvVar(
        "NARWHAL_BIND_ANY", "flag", False,
        "Listen on 0.0.0.0 instead of the advertised committee IP "
        "(NAT'd/cloud hosts); applies to every listener including the "
        "metrics endpoint.",
    ),
    EnvVar(
        "NARWHAL_VOTE_FAST_PATH", "flag", True,
        "`0` restores per-header vote persists instead of the coalesced "
        "once-per-burst vote-log flush (round-cadence fast path, PR 5).",
    ),
    EnvVar(
        "NARWHAL_WIRE_V2", "flag", True,
        "Wire-format v2 master switch (per-peer frame coalescing, "
        "per-connection digest-reference compression, compact varint/"
        "key-index encodings, residual deflate). `0` is the byte-"
        "identical legacy arm the paired wire A/B runs against; the "
        "flag is committee-wide — mixed-version committees are not "
        "supported.",
    ),
    EnvVar(
        "NARWHAL_NET_BACKOFF_MAX_S", "float", 60.0,
        "Reconnect-backoff ceiling in seconds (floor 0.2 s). Lower it "
        "for fault scenarios / latency-sensitive deployments so healed "
        "partitions are noticed quickly.",
    ),
    EnvVar(
        "NARWHAL_HELPER_MAX_DIGESTS", "int", 128,
        "Per-BatchRequest digest cap at the worker Helper; unique "
        "digests past the cap are truncated and counted as "
        "`worker.helper_rejected_requests`.",
    ),
    EnvVar(
        "NARWHAL_MAX_BATCH_BYTES", "int", None,
        "Inbound batch-frame size ceiling at the worker receiver; "
        "oversized frames are rejected before hashing into "
        "`worker.garbage_batches`.",
        shown_default="2×batch_size + 64 KiB",
    ),
    EnvVar(
        "NARWHAL_CONSENSUS_AUDIT", "str", None,
        "Path for the consensus insert/commit audit segment consumed by "
        "the golden-oracle safety replay; unset = no audit log.",
    ),
    EnvVar(
        "NARWHAL_COMMIT_RULE", "str", "classic",
        "Commit rule (equivalent of `node run --commit-rule`): `classic` "
        "(Tusk — leader commits at depth 3 on f+1 support), `lowdepth` "
        "(Mysticeti-style — leader commits the moment 2f+1 round-(L+1) "
        "certificates cite it), or `multileader` (Mysticeti multi-slot "
        "— 3 round-salted leader slots per even round, the commit "
        "anchors on the lowest 2f+1-supported slot); each non-classic "
        "rule is judged against its own frozen oracle. Committee-wide: "
        "mixed-rule committees diverge by design and fail the safety "
        "replay; checkpoints refuse a cross-rule restore.",
    ),
    EnvVar(
        "NARWHAL_CERT_SIG_SCHEME", "str", "individual",
        "Certificate signature scheme (equivalent of `node run "
        "--cert-sig-scheme`): `individual` (2f+1 ed25519 vote "
        "signatures per certificate) or `halfagg` (ed25519 "
        "half-aggregation — the vote quorum folds into one 32*(q+1)-"
        "byte blob at assembly and sanitization verifies ONE multiexp "
        "equation per certificate, at the `certificate_agg` crypto "
        "site). Committee-wide: a certificate frame from the other "
        "scheme refuses at decode (counted into "
        "primary.invalid_signatures) and a consensus checkpoint "
        "written under one scheme refuses to restore under the other. "
        "Default individual — the flip is gated on the ISSUE 20 "
        "measurement ladder (benchmark/trajectory_gate.json).",
    ),
    EnvVar(
        "NARWHAL_CHANNEL_CAPACITY", "int", 1_000,
        "Bounded-queue capacity for every inter-task channel "
        "(node/primary/worker planes; the quorum admission window keeps "
        "its own QUORUM_WINDOW depth). The knee matrix sweeps it; "
        "in-process harnesses may still pass an explicit per-node "
        "override.",
    ),
    # -- observability --------------------------------------------------------
    EnvVar(
        "NARWHAL_METRICS", "flag", True,
        "`0` swaps the per-process instrument registry for no-ops "
        "(instrumented code needs no enabled-checks).",
    ),
    EnvVar(
        "NARWHAL_METRICS_DUMP", "str", None,
        "Directory where the metrics-smoke and health-bench tests drop "
        "their registry snapshots / committee timelines for CI artifact "
        "upload.",
    ),
    EnvVar(
        "NARWHAL_TRACE", "flag", False,
        "Per-digest TRACE instrumentation plus worker heartbeat logs "
        "(hot-path cost; debugging aid).",
    ),
    EnvVar(
        "NARWHAL_TRACE_CAP", "int", 32_768,
        "Stage-trace table capacity before eviction "
        "(`metrics.trace_evictions` counts overflow).",
    ),
    EnvVar(
        "NARWHAL_HEALTH", "flag", True,
        "HealthMonitor master switch on node boot; `0` disables rule "
        "evaluation entirely.",
    ),
    EnvVar(
        "NARWHAL_HEALTH_INTERVAL", "float", 1.0,
        "Seconds between health-rule sweeps.",
    ),
    EnvVar(
        "NARWHAL_LOOP_WATCHDOG_MS", "int", 0,
        "Opt-in event-loop stall watchdog: >0 installs it with this "
        "threshold (ms). Stalls land in the "
        "`runtime.loop_stall_seconds` histogram with a stack excerpt in "
        "`runtime.loop_stall_last`; 0/unset = off.",
    ),
    EnvVar(
        "NARWHAL_PROFILE_HZ", "float", 67.0,
        "Sampling-profiler frequency (all-thread stack samples/s into "
        "the `profile.*` series, folded-stack + top-N tables in the "
        "snapshot detail); `0` disables the sampler thread.",
    ),
    EnvVar(
        "NARWHAL_FLIGHT", "flag", True,
        "`0` stubs the flight recorder (event ring, tick deltas, and "
        "the 503/SIGTERM/task-death dumps) without touching the rest "
        "of the metrics plane.",
    ),
    EnvVar(
        "NARWHAL_FLIGHT_CAP", "int", 512,
        "Flight-recorder ring capacity (events kept; oldest evicted).",
    ),
    EnvVar(
        "NARWHAL_FLIGHT_DIR", "str", None,
        "Directory for atomic flight-ring dump files "
        "(`flight-<node>-<n>-<reason>.json`) on the /healthz 503 "
        "transition, SIGTERM, and unhandled task death; unset = no "
        "file dumps (the ring stays pullable via `/debug/flight`).",
    ),
    EnvVar(
        "NARWHAL_FLIGHT_INTERVAL_S", "float", 1.0,
        "Seconds between flight-recorder `tick` events (per-tick "
        "wire/commit/queue deltas).",
    ),
    EnvVar(
        "NARWHAL_FAULTHANDLER_S", "float", 0.0,
        "Arm `faulthandler.dump_traceback_later` every N seconds "
        "(C-level stack dumps that fire even with a wedged event loop); "
        "0/unset = off.",
    ),
    EnvVar(
        "NARWHAL_PROFILE", "str", None,
        "cProfile the whole node, dumping stats into this directory on "
        "SIGTERM.",
    ),
    # -- health-rule thresholds (metrics.default_rules) -----------------------
    EnvVar(
        "NARWHAL_HEALTH_MAX_COMMIT_LAG", "float", 20,
        "`commit_lag` fires when `consensus.commit_lag_rounds` exceeds "
        "this.",
    ),
    EnvVar(
        "NARWHAL_HEALTH_COMMIT_STALL_S", "float", 10,
        "`commit_stall` fires when rounds advance but no certificate "
        "commits for this long.",
    ),
    EnvVar(
        "NARWHAL_HEALTH_PENDING_ACK_FLOOR", "float", 512,
        "`pending_acks` floor: backlog below this never fires.",
    ),
    EnvVar(
        "NARWHAL_HEALTH_PENDING_ACK_WINDOW_S", "float", 5,
        "`pending_acks` growth-rate window in seconds.",
    ),
    EnvVar(
        "NARWHAL_HEALTH_PEER_RETRANS_RATE", "float", 10,
        "`peer_retransmissions` fires above this many retransmits/s to "
        "one peer.",
    ),
    EnvVar(
        "NARWHAL_HEALTH_PEER_RETRANS_WINDOW_S", "float", 5,
        "`peer_retransmissions` rate window in seconds.",
    ),
    EnvVar(
        "NARWHAL_HEALTH_PEER_FAILURES", "float", 3,
        "`peer_unreachable` fires at this many consecutive connect "
        "failures against one peer (boot-grace gated).",
    ),
    EnvVar(
        "NARWHAL_HEALTH_QUORUM_WEDGE_S", "float", 10,
        "`quorum_wedge` fires when a sealed batch waits on its ACK "
        "quorum this long.",
    ),
    EnvVar(
        "NARWHAL_HEALTH_VOTE_SILENCE_WINDOW_S", "float", 8,
        "`peer_vote_silence` observation window in seconds.",
    ),
    EnvVar(
        "NARWHAL_HEALTH_VOTE_SILENCE_MIN_ROUNDS", "float", 3,
        "`peer_vote_silence` requires at least this much round progress "
        "inside the window.",
    ),
    EnvVar(
        "NARWHAL_HEALTH_STALE_RATE", "float", 6,
        "`stale_replay` fires above this many stale messages/s — sits "
        "~2× above the measured partition-heal catch-up burst "
        "(2.4-2.9/s) and under the 10/s replay-flood attack.",
    ),
    EnvVar(
        "NARWHAL_HEALTH_STALE_WINDOW_S", "float", 5,
        "`stale_replay` rate window in seconds.",
    ),
    EnvVar(
        "NARWHAL_HEALTH_SYNC_AGE_S", "float", 8,
        "`batch_withholding` fires when a requested-but-unserved batch "
        "ages past this (above the stock 5 s sync retry delay).",
    ),
    EnvVar(
        "NARWHAL_HEALTH_QUEUE_SAT_RATIO", "float", 0.9,
        "`queue_saturated` fires when an instrumented channel's depth "
        "reaches this fraction of its capacity.",
    ),
    EnvVar(
        "NARWHAL_HEALTH_QUEUE_SAT_MIN_CAP", "float", 16,
        "`queue_saturated` ignores channels with capacity below this: "
        "the quorum admission window and the sim's depth-1 channels run "
        "full as their backpressure mechanism.",
    ),
    EnvVar(
        "NARWHAL_HEALTH_QUEUE_SAT_INTERVALS", "float", 3,
        "`queue_saturated` hysteresis: consecutive over-threshold "
        "evaluations before the rule fires.",
    ),
    EnvVar(
        "NARWHAL_HEALTH_INGRESS_DROP_RATE", "float", 1.0,
        "`ingress_drops` fires above this many client-ingress "
        "overflows/s (`worker.ingress_overflow` rate).",
    ),
    EnvVar(
        "NARWHAL_HEALTH_INGRESS_DROP_WINDOW_S", "float", 5,
        "`ingress_drops` rate window in seconds.",
    ),
    # -- crypto backend (ROADMAP item 1) --------------------------------------
    EnvVar(
        "NARWHAL_CRYPTO_BACKEND", "str", "cpu",
        "Signature-verification backend selected at node boot (equivalent "
        "of `node run --crypto-backend`): `cpu` (serial OpenSSL / "
        "pure-Python fallback) or `jax`/`tpu` (the vmapped batched "
        "verifier in ops/ed25519.py — `jax` runs on whatever platform "
        "JAX has, incl. jax-cpu for the A/B fallback arm).",
    ),
    EnvVar(
        "NARWHAL_CRYPTO_BACKEND_STRICT", "flag", True,
        "`1` (default): a requested jax/tpu backend that fails to import "
        "raises at boot with the import error. `0`: log the error and "
        "fall back to the cpu backend — an explicit choice, never a "
        "silent downgrade mid-burst.",
    ),
    EnvVar(
        "NARWHAL_VERIFY_BATCH_WINDOW_MS", "float", 0.0,
        "Core verify-batch accumulation window: >0 coalesces signature "
        "claims from multiple drained bursts (headers, votes, certs) "
        "arriving within this many ms into ONE backend dispatch, run in "
        "a pipelined verify task so proposer/waiter work keeps flowing "
        "during the device round trip. 0 (default) = verify each "
        "drained burst inline (the pre-r19 behavior).",
    ),
    EnvVar(
        "NARWHAL_VERIFY_BATCH_MAX", "int", 256,
        "Max messages one coalesced verify dispatch may cover when the "
        "batch window is enabled (bounds device batch shape and the "
        "latency added ahead of the first message's replay).",
    ),
    EnvVar(
        "NARWHAL_VERIFY_MESH", "flag", False,
        "EXPERIMENTAL: shard the batched verify across every visible "
        "JAX device (jax.sharding.Mesh + shard_map over the batch axis) "
        "so crypto throughput scales with chips; single-device hosts "
        "fall back to the plain vmapped kernel.",
    ),
    # -- device plane ---------------------------------------------------------
    EnvVar(
        "NARWHAL_FIELD_DTYPE", "str", "int32",
        "Lane dtype of `ops/field25519` (`int32` or `float32`); read at "
        "import.",
    ),
    EnvVar(
        "NARWHAL_TPU_WARMUP_SHAPES", "str", None,
        "Extra comma-separated claim counts to pre-compile into the "
        "verify kernel's warmup sweep.",
    ),
    EnvVar(
        "NARWHAL_JAX_CACHE", "str", None,
        "Persistent XLA compilation-cache directory shared across node "
        "processes.",
        shown_default="~/.cache/narwhal_tpu_jax",
    ),
    # -- deterministic simulation (narwhal_tpu/sim) ---------------------------
    EnvVar(
        "NARWHAL_SIM_SEED", "int", None,
        "Overrides the base seed of `benchmark/sim_bench.py` sweeps "
        "(each point derives its run seed from this + its index); unset "
        "= the CLI's --seed-base.",
    ),
    EnvVar(
        "NARWHAL_SIM_COMPRESSION_CAP", "float", 60.0,
        "Ceiling on a single virtual-clock quiesce jump in simulated "
        "seconds; a forgotten far-future timer advances the clock in "
        "bounded non-blocking steps instead of one leap. 0 = uncapped.",
    ),
    EnvVar(
        "NARWHAL_SIM_MAX_VIRTUAL_S", "float", 600.0,
        "Ceiling on one sim run's total virtual duration, enforced as a "
        "virtual-time wait_for: a livelocked scenario terminates with a "
        "deterministic timeout instead of spinning forever.",
    ),
    # -- fault injection ------------------------------------------------------
    EnvVar(
        "NARWHAL_FAULT_PLAN", "str", None,
        "Path to a Byzantine plan JSON (equivalent of `node run "
        "--fault-plan`); makes the node ATTACK its committee.",
    ),
    EnvVar(
        "NARWHAL_FAULT_SEED", "int", None,
        "Overrides the fault plan's RNG seed (rogue keys, twin minting, "
        "fuzz draws).",
    ),
    EnvVar(
        "NARWHAL_FAULT_NETEM", "str", None,
        "Path to a WAN-emulation spec consumed by `faults/netem.py`; "
        "unset = no emulation.",
    ),
    EnvVar(
        "NARWHAL_FAULT_NODE", "str", "",
        "This node's name in the netem spec (selects its link profile).",
    ),
]

REGISTRY: Dict[str, EnvVar] = {v.name: v for v in _VARS}
assert len(REGISTRY) == len(_VARS), "duplicate EnvVar declaration"


def declared(name: str) -> EnvVar:
    """The declaration for ``name``; raises (the runtime half of the
    ``env-var-registry`` lint rule) on an undeclared knob."""
    try:
        return REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"{name} is not declared in narwhal_tpu/utils/env.py REGISTRY "
            "— declare it (name, type, default, doc) before reading it"
        ) from None


def env_raw(
    name: str, env: Optional[Mapping[str, str]] = None
) -> Optional[str]:
    """The raw string value (or None), with the declaration check.
    ``env`` overrides ``os.environ`` for injectable call sites."""
    declared(name)
    return (os.environ if env is None else env).get(name)


_FALSE = {"", "0", "false", "no", "off"}


def env_flag(
    name: str,
    default: object = _UNSET,
    env: Optional[Mapping[str, str]] = None,
) -> bool:
    raw = env_raw(name, env)
    if raw is None:
        d = REGISTRY[name].default if default is _UNSET else default
        return bool(d)
    return raw.strip().lower() not in _FALSE


def env_str(
    name: str,
    default: object = _UNSET,
    env: Optional[Mapping[str, str]] = None,
):
    raw = env_raw(name, env)
    if raw is not None:
        return raw
    return REGISTRY[name].default if default is _UNSET else default


@functools.lru_cache(maxsize=128)
def _parse_number(name: str, raw: str, caster, fallback) -> object:
    # Memoized per raw value: misconfiguration must warn once, not at
    # call-site frequency.
    try:
        return caster(raw)
    except (TypeError, ValueError):
        log.warning(
            "%s=%r is not a valid %s; using %r",
            name, raw, caster.__name__, fallback,
        )
        return fallback


def env_int(
    name: str,
    default: object = _UNSET,
    env: Optional[Mapping[str, str]] = None,
):
    raw = env_raw(name, env)
    d = REGISTRY[name].default if default is _UNSET else default
    if raw is None:
        return d
    if not isinstance(raw, str):  # injected mapping may carry parsed values
        return int(raw)
    return _parse_number(name, raw, int, d)


def env_float(
    name: str,
    default: object = _UNSET,
    env: Optional[Mapping[str, str]] = None,
):
    raw = env_raw(name, env)
    d = REGISTRY[name].default if default is _UNSET else default
    if raw is None:
        return d
    if not isinstance(raw, str):
        return float(raw)
    return _parse_number(name, raw, float, d)


@functools.lru_cache(maxsize=64)
def _parse_positive_int(name: str, raw: str, default: int) -> int:
    try:
        v = int(raw)
        if v > 0:
            return v
    except ValueError:
        pass
    log.warning(
        "%s=%r is not a positive integer; using %d", name, raw, default
    )
    return default


def positive_int(name: str, default: int) -> int:
    """``int(os.environ[name])`` when set and positive, else ``default``
    (with a once-per-value warning on garbage).  The default stays at the
    call site because these knobs compute it (e.g. from batch_size)."""
    raw = env_raw(name)
    if raw is None:
        return default
    return _parse_positive_int(name, raw, default)


# -- README table -------------------------------------------------------------

TABLE_BEGIN = "<!-- env-table:begin (generated: python -m narwhal_tpu.analysis --env-table) -->"
TABLE_END = "<!-- env-table:end -->"


def render_table() -> str:
    """The README 'Environment variables' markdown table, generated from
    the registry so the doc and the code cannot drift (the linter
    compares this output against the README section)."""
    lines = [
        "| Variable | Type | Default | Meaning |",
        "|---|---|---|---|",
    ]
    for v in sorted(REGISTRY.values(), key=lambda v: v.name):
        lines.append(
            f"| `{v.name}` | {v.kind} | {v.rendered_default} | {v.doc} |"
        )
    return "\n".join(lines)
