"""Shared env-var parsing for tunable limits.

Every knob of the form "positive integer with a sane default" needs the
same three behaviors: accept a valid override, fall back loudly on
garbage, and warn ONCE rather than at call-site frequency (some of these
are read on hot paths — per retry sweep, per inbound frame).  One
definition here instead of a per-module copy (the reconnect-backoff cap
in network/reliable_sender.py keeps its own parser: its semantics clamp
to a float floor rather than requiring a positive integer).
"""

from __future__ import annotations

import functools
import logging
import os

log = logging.getLogger("narwhal.config")


@functools.lru_cache(maxsize=64)
def _parse_positive_int(name: str, raw: str, default: int) -> int:
    try:
        v = int(raw)
        if v > 0:
            return v
    except ValueError:
        pass
    log.warning(
        "%s=%r is not a positive integer; using %d", name, raw, default
    )
    return default


def positive_int(name: str, default: int) -> int:
    """``int(os.environ[name])`` when set and positive, else ``default``
    (with a once-per-value warning on garbage)."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    return _parse_positive_int(name, raw, default)
