"""``spawn()`` — background tasks with a strong reference and a loud death.

``asyncio.create_task`` hands back the ONLY strong reference to the task:
the event loop keeps a weak one, so a fire-and-forget call site lets the
garbage collector silently destroy a live task mid-flight, and a task
that dies of an unhandled exception holds the traceback invisibly until
teardown (or forever).  Both failure shapes have bitten this codebase
enough times that the invariant linter's ``task-retention`` rule bans
bare ``create_task`` statements outright.

``spawn()`` is the sanctioned alternative for background work: it keeps a
strong reference in a module-level set until the task completes, and its
done-callback logs any non-cancellation exception immediately — a dead
pipeline stage names itself in the log the moment it dies instead of
stalling the committee silently.  Call sites that await/cancel their
task through a retained name (queue-get races in core/proposer) may keep
plain ``create_task``; everything launched into the background goes
through here.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Coroutine, Optional, Set

from .. import metrics

log = logging.getLogger("narwhal.tasks")

# The strong references. A plain set (not WeakSet — defeating GC is the
# whole point); _reap drops each task the moment it completes.
_TASKS: Set[asyncio.Task] = set()

metrics.gauge_fn("runtime.background_tasks", lambda: len(_TASKS))


def _reap(task: asyncio.Task) -> None:
    _TASKS.discard(task)
    if task.cancelled():
        return  # orderly teardown, not a death
    exc = task.exception()
    if exc is not None:
        log.error(
            "Background task %r died of an unhandled exception",
            task.get_name(),
            exc_info=exc,
        )
        # A dead pipeline stage is exactly what the flight recorder
        # exists for: record the death and dump the ring NOW, while the
        # events leading up to it are still in the window.
        flight = metrics.flight()
        flight.record("task_death", task=task.get_name(), exc=repr(exc))
        flight.dump("task-death")


def spawn(coro: Coroutine, *, name: Optional[str] = None) -> asyncio.Task:
    """Schedule ``coro`` on the running loop, strongly referenced until
    done, with unexpected-exception teardown logged.  Returns the task —
    callers that cancel at shutdown keep the handle as usual."""
    task = asyncio.get_running_loop().create_task(coro, name=name)
    _TASKS.add(task)
    task.add_done_callback(_reap)
    return task


def alive_count() -> int:
    """Live spawned-task count (also exported as the
    ``runtime.background_tasks`` gauge)."""
    return len(_TASKS)
