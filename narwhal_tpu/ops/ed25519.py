"""Batched ed25519 signature verification on TPU (vmapped JAX).

The reference's per-round crypto hot loop is `Signature::verify_batch`
(crypto/src/lib.rs:206-219), called with 2f+1 signatures per certificate ×
N certificates per round (primary/src/messages.rs:189-215).  Its dalek
backend runs 51-bit-limb u128 arithmetic on the CPU; here the same batch
maps to TPU vector lanes: field elements are 32×8-bit int32 limbs
(ops/field25519.py), points are extended twisted-Edwards coordinates
(X:Y:Z:T), and the double-scalar ladder [s]B + [k](-A) runs one shared
MSB-first windowed Horner loop for the whole batch.

Verification semantics (strict, a superset of RFC 8032 rejections —
deviations from specific CPU libraries are *more* rejections, never fewer):
- reject S ≥ L (non-canonical scalar; all mainstream verifiers agree),
- reject non-canonical point encodings (y ≥ p),
- reject encodings with no valid x (not on curve) or x=0 with sign=1,
- reject small-order A or R ([8]P = identity) — dalek `verify_strict`,
- accept iff [S]B = R + [k]A with k = SHA-512(R ‖ A ‖ M) mod L, checked as
  projective point equality (equivalent to compressed-byte equality since
  only canonical encodings are admitted).

SHA-512(R‖A‖M) and the scalar window decomposition run host-side during
batch prep (measured ~8-10 µs/signature on a 1-core host, overlappable
with device compute; see bench_crypto.py); every field/curve operation
runs on device.  Differential-tested against OpenSSL over random and
adversarial inputs (tests/test_ed25519.py).
"""

from __future__ import annotations

import hashlib
from typing import List, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..utils.env import env_flag, env_str
from . import field25519 as F

P = F.P
L_ORDER = (1 << 252) + 27742317777372353535851937790883648493

D_INT = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1_INT = pow(2, (P - 1) // 4, P)

_D = jnp.asarray(F.to_limbs(D_INT))
_2D = jnp.asarray(F.to_limbs((2 * D_INT) % P))
_SQRT_M1 = jnp.asarray(F.to_limbs(SQRT_M1_INT))
_ONE = jnp.asarray(F.to_limbs(1))
_ZERO = jnp.asarray(F.to_limbs(0))

# --------------------------------------------------------------- point ops
# A point is a tuple (X, Y, Z, T) of int32[..., LIMBS=32] with x=X/Z,
# y=Y/Z, T = XY/Z (extended homogeneous coords; Hisil–Wong–Carter–Dawson).

Point = Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]


def identity_like(x: jnp.ndarray) -> Point:
    shape = x.shape[:-1] + (F.LIMBS,)
    zero = jnp.broadcast_to(_ZERO, shape)
    one = jnp.broadcast_to(_ONE, shape)
    return (zero, one, one, zero)


def point_add(p: Point, q: Point) -> Point:
    """Unified add (add-2008-hwcd-3, a=-1): complete on the prime-order
    subgroup and correct for all curve points when q is not exceptional —
    we only ever add decompressed curve points, for which it is total."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = F.mul(F.sub(y1, x1), F.sub(y2, x2))
    b = F.mul(F.add(y1, x1), F.add(y2, x2))
    c = F.mul(F.mul(t1, _2D), t2)
    d = F.mul(F.add(z1, z1), z2)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def point_double(p: Point) -> Point:
    """dbl-2008-hwcd for a = -1."""
    x1, y1, z1, _ = p
    a = F.square(x1)
    b = F.square(y1)
    zz = F.square(z1)
    c = F.add(zz, zz)  # 2·z² via the 1-sweep add (mul_small carries 4×)
    h = F.add(a, b)
    e = F.sub(h, F.square(F.add(x1, y1)))
    g = F.sub(a, b)
    f = F.add(c, g)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def point_neg(p: Point) -> Point:
    x, y, z, t = p
    return (F.neg(x), y, z, F.neg(t))


def point_select(cond: jnp.ndarray, p: Point, q: Point) -> Point:
    return tuple(F.select(cond, a, b) for a, b in zip(p, q))


def point_eq(p: Point, q: Point) -> jnp.ndarray:
    """Projective equality: X1·Z2 == X2·Z1 and Y1·Z2 == Y2·Z1."""
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return F.eq(F.mul(x1, z2), F.mul(x2, z1)) & F.eq(
        F.mul(y1, z2), F.mul(y2, z1)
    )


def is_identity(p: Point) -> jnp.ndarray:
    x, y, z, _ = p
    return F.is_zero(x) & F.eq(y, z)


def is_small_order(p: Point) -> jnp.ndarray:
    """[8]P == identity (the 8-torsion subgroup)."""
    q = point_double(point_double(point_double(p)))
    return is_identity(q)


# ------------------------------------------------------------ decompression


def decompress(y_limbs: jnp.ndarray, sign: jnp.ndarray,
               y_canonical: jnp.ndarray) -> Tuple[Point, jnp.ndarray]:
    """Compressed Edwards y + sign bit → extended point and validity mask.

    Rejects: non-canonical y (y ≥ p, decided host-side from the raw bytes
    and passed as `y_canonical`), y²-1/(dy²+1) a non-square, and the
    x = 0 / sign = 1 encoding (RFC 8032 §5.1.3 step 4).
    """
    y = y_limbs
    yy = F.square(y)
    u = F.sub(yy, jnp.broadcast_to(_ONE, y.shape))
    v = F.add(F.mul(yy, jnp.broadcast_to(_D, y.shape)),
              jnp.broadcast_to(_ONE, y.shape))
    # x = u·v³·(u·v⁷)^((p-5)/8)  (RFC 8032 §5.1.3)
    v3 = F.mul(F.square(v), v)
    v7 = F.mul(F.square(v3), v)
    x = F.mul(F.mul(u, v3), F.pow_p58(F.mul(u, v7)))
    vxx = F.mul(v, F.square(x))
    ok_direct = F.eq(vxx, u)
    ok_twist = F.eq(vxx, F.neg(u))
    x = F.select(ok_direct, x,
                 F.mul(x, jnp.broadcast_to(_SQRT_M1, x.shape)))
    on_curve = ok_direct | ok_twist
    xc = F.canon(x)
    x_is_zero = jnp.all(xc == 0, axis=-1)
    # x = 0 with sign = 1 is invalid; otherwise flip x to match the sign.
    # Parity via % 2, not & 1: exact in both lane dtypes (f32 mod of an
    # exact integer < 2^24 is exact), and the comparison against the
    # int32 sign bit promotes losslessly.
    sign_ok = ~(x_is_zero & (sign == 1))
    flip = (xc[..., 0] % 2) != sign
    x = F.select(flip, F.neg(xc), xc)
    valid = on_curve & sign_ok & y_canonical
    point = (x, y, jnp.broadcast_to(_ONE, y.shape), F.mul(x, y))
    return point, valid


# ------------------------------------------------------- base point table

def _ref_scalarmult(k: int) -> Tuple[int, int]:
    """Host-side scalar mult with Python ints (table construction only)."""
    bx = 15112221349535400772501151409588531511454012693041857206046113283949847762202
    by = 46316835694926478169428394003475163141307993866256225615783033603165251855960

    def edwards_add(p, q):
        x1, y1 = p
        x2, y2 = q
        den = (D_INT * x1 * x2 * y1 * y2) % P
        x3 = (x1 * y2 + x2 * y1) * pow(1 + den, P - 2, P)
        y3 = (y1 * y2 + x1 * x2) * pow(1 - den, P - 2, P)
        return (x3 % P, y3 % P)

    q = (0, 1)
    b = (bx, by)
    while k > 0:
        if k & 1:
            q = edwards_add(q, b)
        b = edwards_add(b, b)
        k >>= 1
    return q


_B_TABLE_NP = np.zeros((16, 4, F.LIMBS), dtype=F.NP_DTYPE)
for _j in range(16):
    _x, _y = _ref_scalarmult(_j)
    _B_TABLE_NP[_j, 0] = F.to_limbs(_x)
    _B_TABLE_NP[_j, 1] = F.to_limbs(_y)
    _B_TABLE_NP[_j, 2] = F.to_limbs(1)
    _B_TABLE_NP[_j, 3] = F.to_limbs((_x * _y) % P)
_B_TABLE = jnp.asarray(_B_TABLE_NP)  # [16, 4, LIMBS]: j·B in extended coords


def _select_from_table(table: jnp.ndarray, w: jnp.ndarray) -> Point:
    """One-hot window select: table [..., 16, 4, LIMBS] (or constant
    [16, 4, LIMBS]), w int32[...] in [0, 16) → Point at w.

    Explicit broadcast-multiply + sum, NOT einsum: a dot_general would be
    eligible for the MXU, whose f32 matmuls run as bf16 passes — limbs
    reach 2^9, past bf16's 8-bit mantissa, so that path could silently
    round in float32 lane mode.  The elementwise form stays on the VPU
    and is exact in both dtypes (products are limb·{0,1})."""
    onehot = jax.nn.one_hot(w, 16, dtype=F.DTYPE)  # [..., 16]
    oh = onehot[..., :, None, None]  # [..., 16, 1, 1]
    sel = (oh * table).sum(axis=-3)  # [..., 4, LIMBS]
    return (sel[..., 0, :], sel[..., 1, :], sel[..., 2, :], sel[..., 3, :])


def _build_neg_a_table(neg_a: Point) -> jnp.ndarray:
    """[..., 16, 4, LIMBS]: j·(-A) for j in 0..15 (15 sequential adds)."""
    rows: List[Point] = [identity_like(neg_a[0])]
    for _ in range(15):
        rows.append(point_add(rows[-1], neg_a))
    stacked = jnp.stack(
        [jnp.stack(r, axis=-2) for r in rows], axis=-3
    )  # [..., 16, 4, LIMBS]
    return stacked


# ------------------------------------------------------------ verification


@jax.jit
def _verify_kernel(
    a_y: jnp.ndarray,       # int32[B, LIMBS] — A's y limbs (raw 255 bits)
    a_sign: jnp.ndarray,    # int32[B]
    a_canon: jnp.ndarray,   # bool[B] — A's y < p
    r_y: jnp.ndarray,       # int32[B, LIMBS]
    r_sign: jnp.ndarray,    # int32[B]
    r_canon: jnp.ndarray,   # bool[B]
    s_windows: jnp.ndarray,  # int32[B, 64] MSB-first 4-bit windows of S
    s_ok: jnp.ndarray,      # bool[B] — S < L
    k_windows: jnp.ndarray,  # int32[B, 64] MSB-first windows of k mod L
) -> jnp.ndarray:
    # Host prep always hands int32 limb rows; the field module's lane
    # dtype may be float32 (NARWHAL_FIELD_DTYPE) — cast once at entry.
    a_y = a_y.astype(F.DTYPE)
    r_y = r_y.astype(F.DTYPE)
    a_point, a_valid = decompress(a_y, a_sign, a_canon)
    r_point, r_valid = decompress(r_y, r_sign, r_canon)
    small = is_small_order(a_point) | is_small_order(r_point)

    neg_a = point_neg(a_point)
    a_table = _build_neg_a_table(neg_a)  # [B, 16, 4, LIMBS]

    def step(i, acc):
        acc = point_double(point_double(point_double(point_double(acc))))
        acc = point_add(acc, _select_from_table(_B_TABLE, s_windows[:, i]))
        acc = point_add(acc, _select_from_table(a_table, k_windows[:, i]))
        return acc

    start = identity_like(a_y)
    result = jax.lax.fori_loop(0, 64, step, start)

    return a_valid & r_valid & ~small & s_ok & point_eq(result, r_point)


# ----------------------------------------------------------- host-side prep
#
# Fully vectorized with numpy (the kernel's feed must not become a Python
# loop): bytes → bit matrix → 8-bit limbs / 4-bit windows via one matmul
# each.  Only SHA-512 (hashlib, C speed) and the 512→mod-L reduction touch
# Python objects per signature.

_NIBBLE_W = np.array([1, 2, 4, 8], dtype=np.int32)
_LIMB_W = (1 << np.arange(F.BITS, dtype=np.int32)).astype(np.int32)
_P_BYTES_BE = np.frombuffer(P.to_bytes(32, "big"), np.uint8)
_L_BYTES_BE = np.frombuffer(L_ORDER.to_bytes(32, "big"), np.uint8)


def _bits_le(raw: np.ndarray) -> np.ndarray:
    """uint8[B, 32] → bit matrix bool[B, 256], bit i = value bit i."""
    return np.unpackbits(raw, axis=1, bitorder="little")


def _field_limbs(bits: np.ndarray) -> np.ndarray:
    """bit matrix [B, 256] (low 255 bits used) → int32[B, LIMBS] limbs."""
    pad = F.LIMBS * F.BITS - 255
    padded = np.concatenate(
        [bits[:, :255], np.zeros((bits.shape[0], pad), bits.dtype)], axis=1
    )
    return padded.reshape(-1, F.LIMBS, F.BITS).astype(np.int32) @ _LIMB_W


def _msb_windows(bits: np.ndarray) -> np.ndarray:
    """bit matrix [B, 256] → int32[B, 64] 4-bit windows, MSB-first."""
    nib = bits.reshape(-1, 64, 4).astype(np.int32) @ _NIBBLE_W
    return nib[:, ::-1]


def _lt_be(raw_le: np.ndarray, bound_be: np.ndarray) -> np.ndarray:
    """value(raw little-endian bytes) < bound, vectorized per row."""
    be = raw_le[:, ::-1]
    diff = be.astype(np.int16) - bound_be.astype(np.int16)
    nz = diff != 0
    first = np.argmax(nz, axis=1)  # first (most significant) differing byte
    any_nz = nz.any(axis=1)
    picked = diff[np.arange(len(diff)), first]
    return np.where(any_nz, picked < 0, False)


def prepare_batch(
    messages: Sequence[bytes],
    keys: Sequence[bytes],
    sigs: Sequence[bytes],
    pad_to: int,
):
    """Host prep: unpack encodings, hash-to-scalar, window-decompose.

    One join + reshape per field instead of a frombuffer per row: the
    per-signature Python loop is the host-side throughput cap once the
    device is fast (measured 8 µs/sig looped vs ~2 µs for the
    irreducible SHA-512 + mod-L), and host prep overlaps device compute
    only if it keeps up."""
    n = len(messages)

    def rows(chunks) -> np.ndarray:
        out = np.zeros((pad_to, 32), np.uint8)
        if n:
            out[:n] = np.frombuffer(b"".join(chunks), np.uint8).reshape(n, 32)
        return out

    sig_bytes = [bytes(s) for s in sigs]
    key_bytes = [bytes(k) for k in keys]
    # Fail loud on malformed lengths: the join+reshape below would
    # otherwise silently misalign rows whenever wrong lengths happen to
    # sum to n·32 (the old per-row assignment raised; keep that contract).
    if any(len(k) != 32 for k in key_bytes):
        raise ValueError("prepare_batch: every key must be 32 bytes")
    if any(len(s) != 64 for s in sig_bytes):
        raise ValueError("prepare_batch: every signature must be 64 bytes")
    akeys = rows(key_bytes)
    r_raw = rows(s[:32] for s in sig_bytes)
    s_raw = rows(s[32:64] for s in sig_bytes)
    kb = bytearray()
    for akey, sig, msg in zip(key_bytes, sig_bytes, messages):
        k = int.from_bytes(
            hashlib.sha512(sig[:32] + akey + bytes(msg)).digest(), "little"
        ) % L_ORDER
        kb += k.to_bytes(32, "little")
    k_raw = rows((kb,))

    a_bits = _bits_le(akeys)
    r_bits = _bits_le(r_raw)
    s_bits = _bits_le(s_raw)
    k_bits = _bits_le(k_raw)
    # Mask the sign bit off the y-field before the canonicality compare.
    a_field = akeys.copy()
    a_field[:, 31] &= 0x7F
    r_field = r_raw.copy()
    r_field[:, 31] &= 0x7F
    return (
        _field_limbs(a_bits),
        a_bits[:, 255].astype(np.int32),
        _lt_be(a_field, _P_BYTES_BE),
        _field_limbs(r_bits),
        r_bits[:, 255].astype(np.int32),
        _lt_be(r_field, _P_BYTES_BE),
        _msb_windows(s_bits),
        _lt_be(s_raw, _L_BYTES_BE),
        _msb_windows(k_bits),
    )


# -- multi-device mesh (stretch, NARWHAL_VERIFY_MESH) -------------------------
#
# The kernel is elementwise over the batch axis, so sharding is trivial:
# a 1-D Mesh over every visible device, shard_map splitting the batch
# (pad shapes are powers of two ≥ 16 and device counts are powers of two
# on every real topology, so the split is always even — a non-dividing
# count falls back to the single-device kernel rather than re-padding).
# Throughput then scales with chips, not cores (SNIPPETS.md [1-3], the
# t5x/Tenstorrent mesh exemplars).

_mesh_kernel_cache: dict = {}


def _mesh_verify_kernel(n_dev: int):
    """shard_map-wrapped _verify_kernel over an ``n_dev``-device mesh;
    built once per device count (the wrapped fn keeps the jit cache)."""
    fn = _mesh_kernel_cache.get(n_dev)
    if fn is None:
        from jax.sharding import Mesh, PartitionSpec as P_
        try:  # moved out of experimental in newer JAX
            from jax.experimental.shard_map import shard_map
        except ImportError:  # pragma: no cover - version skew
            from jax.shard_map import shard_map
        mesh = Mesh(np.array(jax.devices()), ("batch",))
        spec = P_("batch")
        fn = jax.jit(
            shard_map(
                _verify_kernel.__wrapped__,  # the un-jitted kernel
                mesh=mesh,
                in_specs=(spec,) * 9,
                out_specs=spec,
            )
        )
        _mesh_kernel_cache[n_dev] = fn
    return fn


def mesh_devices() -> int:
    """How many devices a mesh-sharded verify would span: >1 only when
    the NARWHAL_VERIFY_MESH flag is on and JAX sees several devices."""
    if not env_flag("NARWHAL_VERIFY_MESH"):
        return 1
    try:
        return len(jax.devices())
    except RuntimeError:  # no backend initialized / unreachable
        return 1


def verify_batch_arrays(messages, keys, sigs) -> np.ndarray:
    """Bool mask for a batch of (message, key, signature) triples.  The
    batch is padded to a power of two ≥ 16 so XLA compiles a small set of
    shapes (cached across calls).  With NARWHAL_VERIFY_MESH and several
    visible devices, the padded batch is sharded across the device mesh
    (pad floor raised to 16 × devices so every shard keeps a lane-filling
    row count)."""
    n = len(messages)
    if n == 0:
        return np.zeros(0, dtype=bool)
    n_dev = mesh_devices()
    floor = 16 * n_dev if n_dev > 1 else 16
    pad = floor
    while pad < n:
        pad <<= 1
    args = prepare_batch(messages, keys, sigs, pad)
    if n_dev > 1 and pad % n_dev == 0:
        kernel = _mesh_verify_kernel(n_dev)
    else:
        kernel = _verify_kernel
    mask = np.asarray(kernel(*(jnp.asarray(a) for a in args)))
    return mask[:n]


class TpuBackend:
    """crypto.backend-compatible verification backend (see
    narwhal_tpu/crypto/backend.py)."""

    name = "tpu"

    def __init__(self) -> None:
        # One dedicated dispatch thread: keeps device calls ordered, and
        # run_in_executor from the event loop never blocks it for the
        # device round trip (host prep + dispatch + result sync all happen
        # on this thread; numpy/hashlib/JAX release the GIL for the bulk).
        from concurrent.futures import ThreadPoolExecutor

        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="tpu-verify"
        )

    def verify(self, message: bytes, key, sig) -> bool:
        return bool(self.verify_batch_mask([message], [key], [sig])[0])

    def verify_batch_mask(
        self, messages: Sequence[bytes], keys, sigs
    ) -> List[bool]:
        return list(verify_batch_arrays(messages, keys, sigs))

    async def averify_batch_mask(
        self, messages: Sequence[bytes], keys, sigs
    ) -> List[bool]:
        mask, _ = await self.averify_batch_mask_timed(messages, keys, sigs)
        return mask

    async def averify_batch_mask_timed(
        self, messages: Sequence[bytes], keys, sigs
    ) -> Tuple[List[bool], float]:
        """(mask, compute_seconds): compute time is measured ON the
        dispatch thread around host prep + device round trip — the wall
        the caller observes additionally includes executor queueing and
        the event-loop wakeup, which is pipelining headroom, not crypto
        cost (the `crypto.verify.device_seconds` split)."""
        import asyncio
        import time

        def timed() -> Tuple[List[bool], float]:
            t0 = time.perf_counter()
            mask = self.verify_batch_mask(messages, keys, sigs)
            return mask, time.perf_counter() - t0

        return await asyncio.get_running_loop().run_in_executor(
            self._executor, timed
        )

    def warmup(
        self, shapes: Sequence[int] = None, max_claims: int = None
    ) -> None:
        """Compile (or load from the persistent cache) the kernel for the
        padded batch shapes a live node will hit, so the first real burst
        doesn't pay tens of seconds of XLA compile on the critical path.

        ``max_claims`` is the largest claim batch the node can produce —
        Core.DRAIN_LIMIT items × one quorum (2f+1) of vote claims each; the
        caller (node boot) derives it from the committee so every power-of-
        two pad shape up to it is compiled before the node joins.  Explicit
        ``shapes`` or NARWHAL_TPU_WARMUP_SHAPES="16,64,256" override."""
        if shapes is None:
            env = env_str("NARWHAL_TPU_WARMUP_SHAPES")
            if env:
                shapes = [int(s) for s in env.split(",") if s]
            else:
                top = 64 if max_claims is None else max(16, max_claims)
                shapes, pad = [], 16
                while True:
                    shapes.append(pad)
                    if pad >= top:
                        break
                    pad <<= 1
        from ..crypto import KeyPair
        from ..crypto.digest import Digest

        kp = KeyPair.generate()
        msg = bytes(Digest(b"\x05" * 32))
        sig = kp.sign(Digest(msg))
        for n in shapes:
            verify_batch_arrays([msg] * n, [kp.name] * n, [sig] * n)
