"""Tusk's DAG traversals as one jitted boolean-matrix scan on device.

The reference commit rule (consensus/src/lib.rs:224-303) does two kinds of
graph walk per candidate leader:

- ``order_leaders`` calls ``linked()`` once per earlier leader — each call a
  round-by-round BFS over the whole certificate window (lib.rs:247-259);
- ``order_dag`` flattens the causal history of every newly committed leader
  (lib.rs:263-303).

Both are frontier propagations through the round-structured DAG.  Here the
window is a dense tensor — ``exists[w, n]`` (certificate present at slot w,
authority n) and ``parent[w, n, m]`` (cert (w, n) references cert (w-1, m)) —
and a single ``lax.scan`` down the window computes the ENTIRE leader chain:
the frontier is a length-N boolean vector, each step is a vector–matrix
product (int32 matmul → MXU), and when the frontier reaches the leader of an
even round the scan records a committed leader and resets the frontier to
that leader alone (exactly the ``leader = prev_leader`` rebinding in
``order_leaders``).  The same scan emits the per-slot reach masks used to
bound the host-side emission DFS.

Slots are fixed-size (static shapes for XLA): slot w holds round
``base_round + w``.  The committee axis N is padded to the committee size;
the window W to a static power-of-two ≥ gc_depth.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax


@partial(jax.jit, static_argnames=("window",))
def leader_chain_scan(
    parent: jax.Array,  # bool[W, N, N]
    exists: jax.Array,  # bool[W, N]
    leader_onehot: jax.Array,  # bool[W, N] — leader identity of slot w's round
    is_leader_slot: jax.Array,  # bool[W] — even round in (last_committed, anchor)
    anchor_slot: jax.Array,  # i32 scalar
    anchor_onehot: jax.Array,  # bool[N]
    window: int,
) -> Tuple[jax.Array, jax.Array]:
    """One descending scan = the whole ``order_leaders`` chain.

    Returns ``(committed[W], reach[W, N])``: committed[w] marks the round at
    slot w as a linked (to-commit) leader round; reach[w] is the certificate
    frontier at slot w (the causal cone of the current chain head), which
    upper-bounds the certificates ``order_dag`` can emit from that slot.
    """
    W = window

    def step(frontier, xs):
        w, parent_up, exists_w, leader_w, is_lead_w = xs
        # Certificates at slot w referenced by the frontier one round up.
        # int32 matvec: lands on the MXU for large committees, exact for bool.
        hit = (
            jnp.matmul(
                frontier.astype(jnp.int32),
                parent_up.astype(jnp.int32),
                preferred_element_type=jnp.int32,
            )
            > 0
        )
        g = hit & exists_w
        g = jnp.where(w == anchor_slot, anchor_onehot, g)
        lead_here = is_lead_w & (w < anchor_slot) & jnp.any(g & leader_w)
        # Frontier reset: the chain head becomes this leader (order_leaders'
        # ``leader = prev_leader``), so deeper reachability is from it alone.
        new_frontier = jnp.where(lead_here, g & leader_w, g)
        return new_frontier, (lead_here, g)

    slots = jnp.arange(W - 1, -1, -1, dtype=jnp.int32)
    # Step at slot w consumes parent[w+1] (edges slot w+1 → slot w).
    parent_up = jnp.concatenate(
        [parent[1:], jnp.zeros((1,) + parent.shape[1:], parent.dtype)], axis=0
    )
    xs = (
        slots,
        parent_up[slots],
        exists[slots],
        leader_onehot[slots],
        is_leader_slot[slots],
    )
    _, (committed_rev, reach_rev) = lax.scan(
        step, jnp.zeros(exists.shape[1], dtype=bool), xs
    )
    return committed_rev[::-1], reach_rev[::-1]


@partial(jax.jit, static_argnames=("window",))
def causal_mask_scan(
    parent: jax.Array,  # bool[W, N, N]
    exists: jax.Array,  # bool[W, N]
    start_slot: jax.Array,  # i32 scalar
    start_onehot: jax.Array,  # bool[N]
    window: int,
) -> jax.Array:
    """Full causal cone of one certificate: bool[W, N] mask of every
    certificate reachable from (start_slot, start_onehot) through parent
    links — the set ``order_dag`` flattens (lib.rs:263-303).  Unlike
    :func:`leader_chain_scan` the frontier accumulates (no resets)."""
    W = window

    def step(frontier, xs):
        w, parent_up, exists_w = xs
        hit = (
            jnp.matmul(
                frontier.astype(jnp.int32),
                parent_up.astype(jnp.int32),
                preferred_element_type=jnp.int32,
            )
            > 0
        )
        g = hit & exists_w
        g = g | jnp.where(w == start_slot, start_onehot, False)
        return g, g

    slots = jnp.arange(W - 1, -1, -1, dtype=jnp.int32)
    parent_up = jnp.concatenate(
        [parent[1:], jnp.zeros((1,) + parent.shape[1:], parent.dtype)], axis=0
    )
    xs = (slots, parent_up[slots], exists[slots])
    _, mask_rev = lax.scan(step, jnp.zeros(exists.shape[1], dtype=bool), xs)
    return mask_rev[::-1]


@partial(jax.jit, static_argnames=("window",))
def support_stake(
    parent: jax.Array,  # bool[W, N, N]
    exists: jax.Array,  # bool[W, N]
    stake: jax.Array,  # i32[N]
    leader_slot: jax.Array,  # i32 scalar
    leader_onehot: jax.Array,  # bool[N]
    window: int,
) -> jax.Array:
    """Stake of slot leader_slot+1 certificates referencing the leader —
    the f+1 support gate (lib.rs:141-157)."""
    child = parent[leader_slot + 1]  # bool[N, N]: child cert → its parents
    votes = jnp.any(child & leader_onehot[None, :], axis=1)
    votes = votes & exists[leader_slot + 1]
    return jnp.sum(jnp.where(votes, stake, 0))


from ..consensus.tusk import Tusk
from ..primary.messages import genesis


class KernelTusk(Tusk):
    """Tusk with ``order_leaders`` executed on device: same decisions as the
    golden Python implementation (consensus/tusk.py, validated
    certificate-for-certificate by tests/test_reachability.py), with the
    window traversals collapsed into one :func:`leader_chain_scan`.  The
    emission DFS (``order_dag``) stays host-side — it is O(output) and must
    produce the exact reference DFS tie-order.

    The dense window (``exists[W, N]``, ``parent[W, N, N]``) is maintained
    INCREMENTALLY as certificates arrive — O(parents) dict work per insert —
    instead of being rebuilt from the dict DAG per commit attempt: the
    rebuild was O(window · N · parents) of Python dict traffic and dominated
    the kernel's end-to-end time ~1000× over the scan itself (round-5
    artifact).  The arrays are anchored at ``last_committed_round``; commits
    shift them down (one memmove) and pull in any certificates that arrived
    beyond the window during a stall.  The protocol guarantees at most one
    certificate per (round, author) — inserts never need to retract edges.

    The scan runs at ONE static window shape — the smallest power of two
    covering gc_depth+2 rounds, compiled once by :meth:`prewarm` — because
    GC bounds the live DAG span to gc_depth rounds (consensus/src/lib.rs:
    56-61).  A span beyond that (only possible transiently, e.g. a commit
    stall racing GC) falls back to the golden Python walk instead of
    triggering a fresh XLA compile of a bigger shape on the consensus
    critical path."""

    def __init__(self, committee, gc_depth, fixed_coin: bool = False) -> None:
        super().__init__(committee, gc_depth, fixed_coin=fixed_coin)
        w = 8
        while w < gc_depth + 2:
            w <<= 1
        self.max_window = w
        self.python_fallbacks = 0  # observability: stalls beyond the window
        n = len(self._sorted_keys)
        self._n = n
        self._index = {name: i for i, name in enumerate(self._sorted_keys)}
        self._win_base = 0  # round held by slot 0; == last_committed_round
        self._exists = np.zeros((w, n), dtype=bool)
        self._parent = np.zeros((w, n, n), dtype=bool)
        # digest → (absolute round, authority index), all inserts ever seen
        # in or above the window (pruned below base on shift)
        self._digest_pos: Dict[bytes, Tuple[int, int]] = {}
        # parent digest → [(child round, child index)]: children that
        # arrived before their parent (edge repaired on parent insert)
        self._waiting_child: Dict[bytes, List[Tuple[int, int]]] = {}
        # certificates at slots ≥ window during a stall; inserted for real
        # when a commit shifts the window down far enough
        self._overflow: List = []
        for cert in genesis(committee):  # State.__init__ already holds them
            self._win_insert(cert)

    # -- incremental window maintenance --------------------------------

    def insert_certificate(self, certificate) -> None:
        super().insert_certificate(certificate)
        self._win_insert(certificate)

    def process_certificate(self, certificate) -> List:
        sequence = super().process_certificate(certificate)
        if sequence:
            self._win_shift()
        return sequence

    def _win_insert(self, cert) -> None:
        r = cert.round
        i = self._index[cert.origin]
        self._digest_pos[bytes(cert.digest())] = (r, i)
        w = r - self._win_base
        if w >= self.max_window:
            self._overflow.append(cert)
            return
        if w < 0:
            return
        self._exists[w, i] = True
        if w >= 1:
            for pd in cert.header.parents:
                pos = self._digest_pos.get(bytes(pd))
                if pos is not None and pos[0] == r - 1:
                    self._parent[w, i, pos[1]] = True
                else:
                    self._waiting_child.setdefault(bytes(pd), []).append(
                        (r, i)
                    )
        # Repair edges from children that arrived before this certificate.
        for cr, ci in self._waiting_child.pop(bytes(cert.digest()), ()):
            cw = cr - self._win_base
            if cr == r + 1 and 0 <= cw < self.max_window:
                self._parent[cw, ci, i] = True

    def _win_shift(self) -> None:
        new_base = max(0, self.state.last_committed_round)
        d = new_base - self._win_base
        if d <= 0:
            return
        W = self.max_window
        if d >= W:
            self._exists[:] = False
            self._parent[:] = False
        else:
            self._exists[: W - d] = self._exists[d:]
            self._exists[W - d :] = False
            self._parent[: W - d] = self._parent[d:]
            self._parent[W - d :] = False
        self._win_base = new_base
        # Prune host maps below the window (slot-0 certs resolve no parents).
        self._digest_pos = {
            k: v for k, v in self._digest_pos.items() if v[0] >= new_base
        }
        self._waiting_child = {
            k: kept
            for k, v in self._waiting_child.items()
            if (kept := [e for e in v if e[0] > new_base])
        }
        # Certificates that arrived beyond the window during the stall now
        # (possibly) fit: insert them for real.
        overflow, self._overflow = self._overflow, []
        for cert in overflow:
            self._win_insert(cert)

    # -- device order_leaders ------------------------------------------

    def prewarm(self) -> None:
        """Compile (or cache-load) the scan at its one static shape off the
        commit critical path (call at node boot)."""
        n = self._n
        W = self.max_window
        leader_chain_scan(
            jnp.zeros((W, n, n), bool),
            jnp.zeros((W, n), bool),
            jnp.zeros((W, n), bool),
            jnp.zeros((W,), bool),
            jnp.int32(0),
            jnp.zeros((n,), bool),
            W,
        )

    def _leader_name(self, round_: int):
        coin = 0 if self.fixed_coin else round_
        return self._sorted_keys[coin % len(self._sorted_keys)]

    def order_leaders(self, leader) -> List:
        state = self.state
        n = self._n
        base = max(0, state.last_committed_round)
        span = leader.round - base + 1
        window = self.max_window
        if span > window or base != self._win_base:
            self.python_fallbacks += 1
            return super().order_leaders(leader)

        leader_onehot = np.zeros((window, n), dtype=bool)
        is_leader_slot = np.zeros(window, dtype=bool)
        for r in range(leader.round - 2, state.last_committed_round, -2):
            name = self._leader_name(r)
            if state.dag.get(r, {}).get(name) is not None:
                leader_onehot[r - base, self._index[name]] = True
                is_leader_slot[r - base] = True

        anchor_onehot = np.zeros(n, dtype=bool)
        anchor_onehot[self._index[leader.origin]] = True
        committed, _reach = leader_chain_scan(
            jnp.asarray(self._parent),
            jnp.asarray(self._exists),
            jnp.asarray(leader_onehot),
            jnp.asarray(is_leader_slot),
            jnp.int32(leader.round - base),
            jnp.asarray(anchor_onehot),
            window,
        )
        committed = np.asarray(committed)

        # Newest-first chain, exactly as the golden order_leaders returns it.
        to_commit = [leader]
        for w in range(window - 1, -1, -1):
            if committed[w]:
                r = base + w
                _, cert = state.dag[r][self._leader_name(r)]
                to_commit.append(cert)
        return to_commit
