"""Tusk's DAG traversals as one jitted boolean-matrix scan on device.

The reference commit rule (consensus/src/lib.rs:224-303) does two kinds of
graph walk per candidate leader:

- ``order_leaders`` calls ``linked()`` once per earlier leader — each call a
  round-by-round BFS over the whole certificate window (lib.rs:247-259);
- ``order_dag`` flattens the causal history of every newly committed leader
  (lib.rs:263-303).

Both are frontier propagations through the round-structured DAG.  Here the
window is a dense tensor — ``exists[w, n]`` (certificate present at slot w,
authority n) and ``parent[w, n, m]`` (cert (w, n) references cert (w-1, m)) —
and a single ``lax.scan`` down the window computes the ENTIRE leader chain:
the frontier is a length-N boolean vector, each step is a vector–matrix
product (int32 matmul → MXU), and when the frontier reaches the leader of an
even round the scan records a committed leader and resets the frontier to
that leader alone (exactly the ``leader = prev_leader`` rebinding in
``order_leaders``).

Slots are fixed-size (static shapes for XLA): slot w holds round
``base_round + w``.  The committee axis N is padded to the committee size;
the window W to a static power-of-two ≥ gc_depth.

Execution model (round 6, the device-resident rewrite): the dense window
LIVES ON DEVICE across calls.  Certificate arrivals stage host-side (an
O(1) list append); the staged batch is flushed in one donated scatter
dispatch per even-round commit opportunity (``window_apply``,
``donate_argnums`` so XLA updates the buffers in place — no host round
trip and no reallocation); commits shift the window with a donated gather
(``window_shift_op``); and the ONLY device→host transfer on the commit
path is the W-bool committed bitmap out of ``leader_commit_scan_counts``.  The
round-5 engine instead kept the window in host numpy, re-uploaded the full
W×N×N parent tensor per ``order_leaders`` call, and paid per-certificate
numpy scatter work on the arrival path — measured 40-450× slower end to
end than the Python dict walk on a tunneled chip
(artifacts/consensus_bench_r05.json); this model is what VERDICT.md §2
prescribed to make the kernel performance-positive.
"""

from __future__ import annotations

import warnings
from functools import partial
from typing import Dict, List, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .. import metrics


_donation_warning_handled = False

# Kernel observability: how large the staged flush batches are and how
# many device dispatches the commit path actually issues — the numbers the
# r05→r06 rebuild had to reconstruct from ad-hoc prints.
_m_flush_batch = metrics.histogram(
    "consensus.kernel.flush_batch_size", metrics.COUNT_BUCKETS
)
_m_dispatches = metrics.counter("consensus.kernel.dispatches")
_m_shifts = metrics.counter("consensus.kernel.window_shifts")
_m_fallbacks = metrics.counter("consensus.kernel.python_fallbacks")


def _silence_cpu_donation_warning() -> None:
    """Buffer donation is a no-op (with a warning) on the CPU backend; the
    donated path is still correct there, just copying.  Filter the noise —
    but ONLY on CPU: on a real accelerator that same warning is the one
    diagnostic for a donation regression (a stray live reference forcing
    XLA back to per-flush window copies, the r05 pathology), so it must
    stay visible there.  Called from KernelTusk.__init__, after the
    instance's buffer allocation has already initialized the backend;
    installs at most one process-global filter entry."""
    global _donation_warning_handled
    if _donation_warning_handled:
        return
    _donation_warning_handled = True
    if jax.default_backend() == "cpu":
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable"
        )


def _chain_scan(
    parent: jax.Array,  # bool[W, N, N]
    exists: jax.Array,  # bool[W, N]
    leader_onehot: jax.Array,  # bool[W, N] — leader identity of slot w's round
    is_leader_slot: jax.Array,  # bool[W] — even round in (last_committed, anchor)
    anchor_slot: jax.Array,  # i32 scalar
    anchor_onehot: jax.Array,  # bool[N]
    window: int,
) -> Tuple[jax.Array, jax.Array]:
    """One descending scan = the whole ``order_leaders`` chain.

    Returns ``(committed[W], reach[W, N])``: committed[w] marks the round at
    slot w as a linked (to-commit) leader round; reach[w] is the certificate
    frontier at slot w (the causal cone of the current chain head), which
    upper-bounds the certificates ``order_dag`` can emit from that slot.
    """
    W = window

    def step(frontier, xs):
        w, parent_up, exists_w, leader_w, is_lead_w = xs
        # Certificates at slot w referenced by the frontier one round up.
        # int32 matvec: lands on the MXU for large committees, exact for bool.
        hit = (
            jnp.matmul(
                frontier.astype(jnp.int32),
                parent_up.astype(jnp.int32),
                preferred_element_type=jnp.int32,
            )
            > 0
        )
        g = hit & exists_w
        g = jnp.where(w == anchor_slot, anchor_onehot, g)
        lead_here = is_lead_w & (w < anchor_slot) & jnp.any(g & leader_w)
        # Frontier reset: the chain head becomes this leader (order_leaders'
        # ``leader = prev_leader``), so deeper reachability is from it alone.
        new_frontier = jnp.where(lead_here, g & leader_w, g)
        return new_frontier, (lead_here, g)

    slots = jnp.arange(W - 1, -1, -1, dtype=jnp.int32)
    # Step at slot w consumes parent[w+1] (edges slot w+1 → slot w).
    parent_up = jnp.concatenate(
        [parent[1:], jnp.zeros((1,) + parent.shape[1:], parent.dtype)], axis=0
    )
    xs = (
        slots,
        parent_up[slots],
        exists[slots],
        leader_onehot[slots],
        is_leader_slot[slots],
    )
    _, (committed_rev, reach_rev) = lax.scan(
        step, jnp.zeros(exists.shape[1], dtype=bool), xs
    )
    return committed_rev[::-1], reach_rev[::-1]


@partial(jax.jit, static_argnames=("window",))
def leader_chain_scan(
    parent: jax.Array,
    exists: jax.Array,
    leader_onehot: jax.Array,
    is_leader_slot: jax.Array,
    anchor_slot: jax.Array,
    anchor_onehot: jax.Array,
    window: int,
) -> Tuple[jax.Array, jax.Array]:
    """Full scan output (committed chain + per-slot reach masks) — the
    program the multichip dry-run shards (__graft_entry__.py) and the
    reach-mask consumers use."""
    return _chain_scan(
        parent, exists, leader_onehot, is_leader_slot, anchor_slot,
        anchor_onehot, window,
    )


@partial(jax.jit, donate_argnums=(0, 1))
def window_apply(
    exists: jax.Array,  # i32[W, N] counts — DONATED, updated in place
    parent: jax.Array,  # i32[W, N, N] counts — DONATED, updated in place
    ins_w: jax.Array,  # i32[C] — slot of each staged certificate
    ins_i: jax.Array,  # i32[C] — authority index of each staged certificate
    row_w: jax.Array,  # i32[C] — slot of each staged parent row
    row_c: jax.Array,  # i32[C] — child authority index of each row
    row_v: jax.Array,  # i32[C, N] — the row: 1 where the child cites parent
) -> Tuple[jax.Array, jax.Array]:
    """One batched insert flush.  The window buffers hold presence COUNTS
    (nonzero = present): scatter-ADD makes duplicate and late (waiting-
    child repair) updates order-independent, so a repair is just a one-hot
    row through the same path as a full certificate row.  Row-granular
    updates (one N-wide row per certificate, not one scatter index per
    edge) keep the XLA scatter at C indices instead of C·N.  Padding
    entries carry slot index W (out of bounds) and are dropped.  The
    buffers are donated: on device the scatter happens in place, and
    nothing returns to the host."""
    exists = exists.at[ins_w, ins_i].add(1, mode="drop")
    parent = parent.at[row_w, row_c].add(row_v, mode="drop")
    return exists, parent


@partial(jax.jit, static_argnames=("window",), donate_argnums=(0, 1))
def window_shift_op(
    exists: jax.Array,  # i32[W, N] — DONATED
    parent: jax.Array,  # i32[W, N, N] — DONATED
    d: jax.Array,  # i32 scalar — rounds to shift down (0 < d < W)
    window: int,
) -> Tuple[jax.Array, jax.Array]:
    """Shift the window down by ``d`` slots after a commit (slot 0 becomes
    the new last-committed round); vacated top slots zero-fill.  Runs as a
    donated device gather — the host never sees the buffers."""
    src = jnp.arange(window, dtype=jnp.int32) + d
    valid = src < window
    src = jnp.minimum(src, window - 1)
    exists = jnp.where(valid[:, None], exists[src], 0)
    # Slot 0 keeps no parent edges: the scan never consumes parent[0]
    # (edges point slot w → w-1), and zeroing it keeps the window an exact
    # dense rendering of the dict DAG (tests/test_reachability.py).
    keep = valid & (jnp.arange(window) > 0)
    parent = jnp.where(keep[:, None, None], parent[src], 0)
    return exists, parent


@partial(jax.jit, static_argnames=("window",))
def leader_commit_scan_counts(
    parent: jax.Array,  # i32[W, N, N] presence counts
    exists: jax.Array,  # i32[W, N] presence counts
    leader_onehot: jax.Array,
    is_leader_slot: jax.Array,
    anchor_slot: jax.Array,
    anchor_onehot: jax.Array,
    window: int,
) -> jax.Array:
    """The commit-path scan over the count-typed device window: the bool
    cast happens inside the same dispatch, and only the W-bool committed
    bitmap leaves the device — the reach masks never materialize a
    transfer, keeping the per-commit fetch at W bytes instead of W×N×N."""
    committed, _ = _chain_scan(
        parent > 0, exists > 0, leader_onehot, is_leader_slot, anchor_slot,
        anchor_onehot, window,
    )
    return committed


@partial(jax.jit, static_argnames=("window",))
def causal_mask_scan(
    parent: jax.Array,  # bool[W, N, N]
    exists: jax.Array,  # bool[W, N]
    start_slot: jax.Array,  # i32 scalar
    start_onehot: jax.Array,  # bool[N]
    window: int,
) -> jax.Array:
    """Full causal cone of one certificate: bool[W, N] mask of every
    certificate reachable from (start_slot, start_onehot) through parent
    links — the set ``order_dag`` flattens (lib.rs:263-303).  Unlike
    :func:`leader_chain_scan` the frontier accumulates (no resets)."""
    W = window

    def step(frontier, xs):
        w, parent_up, exists_w = xs
        hit = (
            jnp.matmul(
                frontier.astype(jnp.int32),
                parent_up.astype(jnp.int32),
                preferred_element_type=jnp.int32,
            )
            > 0
        )
        g = hit & exists_w
        g = g | jnp.where(w == start_slot, start_onehot, False)
        return g, g

    slots = jnp.arange(W - 1, -1, -1, dtype=jnp.int32)
    parent_up = jnp.concatenate(
        [parent[1:], jnp.zeros((1,) + parent.shape[1:], parent.dtype)], axis=0
    )
    xs = (slots, parent_up[slots], exists[slots])
    _, mask_rev = lax.scan(step, jnp.zeros(exists.shape[1], dtype=bool), xs)
    return mask_rev[::-1]


@partial(jax.jit, static_argnames=("window",))
def support_stake(
    parent: jax.Array,  # bool[W, N, N]
    exists: jax.Array,  # bool[W, N]
    stake: jax.Array,  # i32[N]
    leader_slot: jax.Array,  # i32 scalar
    leader_onehot: jax.Array,  # bool[N]
    window: int,
) -> jax.Array:
    """Stake of slot leader_slot+1 certificates referencing the leader —
    the f+1 support gate (lib.rs:141-157)."""
    child = parent[leader_slot + 1]  # bool[N, N]: child cert → its parents
    votes = jnp.any(child & leader_onehot[None, :], axis=1)
    votes = votes & exists[leader_slot + 1]
    return jnp.sum(jnp.where(votes, stake, 0))


from ..consensus.tusk import Tusk
from ..primary.messages import genesis


class KernelTusk(Tusk):
    """Tusk with ``order_leaders`` executed on device: same decisions as the
    golden Python implementation (consensus/tusk.py, validated
    certificate-for-certificate by tests/test_reachability.py), with the
    window traversals collapsed into one :func:`leader_commit_scan_counts`.  The
    emission DFS (``order_dag``) stays host-side — it is O(output) and must
    produce the exact reference DFS tie-order.

    The dense window (``exists[W, N]``, ``parent[W, N, N]``) is
    DEVICE-RESIDENT across calls.  The execution model, phase by phase:

    - **Arrival** (``insert_certificate``): O(1) — the certificate is
      appended to a host staging list.  No device dispatch, no numpy
      scatter, no digest bookkeeping; the arrival path costs the same as
      the golden Python dict insert.
    - **Commit opportunity** (``order_leaders``, reached only when the
      host-side f+1 support gate passes): the staged batch is resolved
      (digest → (round, authority) positions, out-of-order children
      repaired via the waiting-child map) and flushed to the device in
      chunked :func:`window_apply` dispatches — donated buffers, one
      static shape, padding dropped via out-of-bounds slot indices.  Then
      ONE :func:`leader_commit_scan_counts` dispatch computes the whole linked-
      leader chain, and only the W-bool committed bitmap is fetched; the
      commit sequence is reconstructed host-side from the dict DAG.
    - **Commit** (``_win_shift``): the window shifts down to the new
      ``last_committed_round`` via a donated :func:`window_shift_op`
      gather; host maps prune below the new base; certificates that
      arrived beyond the window during a stall re-stage.

    The scan runs at ONE static window shape — the smallest power of two
    covering gc_depth+2 rounds, compiled once by :meth:`prewarm` — because
    GC bounds the live DAG span to gc_depth rounds (consensus/src/lib.rs:
    56-61).  A span beyond that (only possible transiently, e.g. a commit
    stall racing GC) falls back to the golden Python walk instead of
    triggering a fresh XLA compile of a bigger shape on the consensus
    critical path."""

    def __init__(self, committee, gc_depth, fixed_coin: bool = False) -> None:
        super().__init__(committee, gc_depth, fixed_coin=fixed_coin)
        w = 8
        while w < gc_depth + 2:
            w <<= 1
        self.max_window = w
        self.python_fallbacks = 0  # observability: stalls beyond the window
        n = len(self._sorted_keys)
        self._n = n
        self._index = {name: i for i, name in enumerate(self._sorted_keys)}
        self._win_base = 0  # round held by slot 0; == last_committed_round
        # Static flush-chunk shape: a steady-state commit opportunity
        # covers ~2 rounds (≤ 2N certificates + a few repair rows), so one
        # chunk is one dispatch; a long catch-up flush loops chunks at the
        # same compiled shape.
        cap = 64
        while cap < 4 * n:
            cap <<= 1
        self._cap = cap
        # The device-resident dense window: presence COUNTS (nonzero =
        # present) so flush updates are order-independent scatter-adds.
        self._dev_exists = jnp.zeros((w, n), dtype=jnp.int32)
        self._dev_parent = jnp.zeros((w, n, n), dtype=jnp.int32)
        _silence_cpu_donation_warning()
        # Certificates staged since the last flush (arrival path is a bare
        # append; all resolution happens per commit opportunity).
        self._pending: List = []
        # digest → (absolute round, authority index), resolved at flush for
        # every certificate at or above the window base (pruned on shift)
        self._digest_pos: Dict[bytes, Tuple[int, int]] = {}
        # parent digest → [(child round, child index)]: children that
        # arrived before their parent (edge repaired on parent flush)
        self._waiting_child: Dict[bytes, List[Tuple[int, int]]] = {}
        # certificates at slots ≥ window during a stall; re-staged when a
        # commit shifts the window down far enough
        self._overflow: List = []
        self._pending.extend(genesis(committee))

    # -- arrival path: O(1) staging ------------------------------------

    def insert_certificate(self, certificate) -> None:
        super().insert_certificate(certificate)
        self._pending.append(certificate)

    def process_certificate(self, certificate) -> List:
        sequence = super().process_certificate(certificate)
        if sequence:
            self._win_shift()
        return sequence

    # -- flush: one batched dispatch per commit opportunity ------------

    def _flush_pending(self) -> None:
        if not self._pending:
            return
        pending, self._pending = self._pending, []
        _m_flush_batch.observe(len(pending))
        # Parents (round r-1) before children (round r) within one flush;
        # cross-flush out-of-order arrivals go through the waiting map.
        pending.sort(key=lambda c: c.round)
        W = self.max_window
        n = self._n
        base = self._win_base
        digest_pos = self._digest_pos
        index = self._index
        # Each in-window certificate contributes one (slot, child, row)
        # update: its full resolved parent row.  Waiting-child repairs
        # (parent arrived in a later flush than the child) are one-hot
        # rows through the same scatter-add.
        ins_w: List[int] = []
        ins_i: List[int] = []
        rows: List[Tuple[int, int, List[int]]] = []  # (slot, child, parents)
        for cert in pending:
            r = cert.round
            if r < base:
                # Below the window (restored frontier / late straggler):
                # slot-0 certificates resolve no parent edges, so nothing
                # below base is ever referenced.
                continue
            i = index[cert.origin]
            d = cert.digest()
            digest_pos[d] = (r, i)
            w = r - base
            if w >= W:
                self._overflow.append(cert)
                continue
            ins_w.append(w)
            ins_i.append(i)
            if w >= 1:
                parents = cert.header.parents
                # Fast path: every parent already known (the overwhelmingly
                # common case — causal delivery).  The comprehension is
                # ~2× the explicit loop; stragglers take the slow path to
                # register waiting-child repairs.
                prow = [
                    pos[1]
                    for pd in parents
                    if (pos := digest_pos.get(pd)) is not None
                    and pos[0] == r - 1
                ]
                if len(prow) != len(parents):
                    for pd in parents:
                        pos = digest_pos.get(pd)
                        if pos is None or pos[0] != r - 1:
                            self._waiting_child.setdefault(pd, []).append(
                                (r, i)
                            )
                if prow:
                    rows.append((w, i, prow))
            # Repair rows for children that arrived in earlier flushes.
            for cr, ci in self._waiting_child.pop(d, ()):
                cw = cr - base
                if cr == r + 1 and 0 <= cw < W:
                    rows.append((cw, ci, [i]))
        if not ins_w and not rows:
            return
        C = self._cap
        chunks = max(-(-len(ins_w) // C), -(-len(rows) // C), 1)
        # Padding entries target slot W — out of bounds, dropped by XLA.
        iw = np.full(chunks * C, W, dtype=np.int32)
        ii = np.zeros(chunks * C, dtype=np.int32)
        iw[: len(ins_w)] = ins_w
        ii[: len(ins_i)] = ins_i
        rw = np.full(chunks * C, W, dtype=np.int32)
        rc = np.zeros(chunks * C, dtype=np.int32)
        rv = np.zeros((chunks * C, n), dtype=np.int32)
        for j, (w, i, prow) in enumerate(rows):
            rw[j] = w
            rc[j] = i
            rv[j, prow] = 1
        for k in range(chunks):
            sl = slice(k * C, (k + 1) * C)
            _m_dispatches.inc()
            self._dev_exists, self._dev_parent = window_apply(
                self._dev_exists,
                self._dev_parent,
                iw[sl],
                ii[sl],
                rw[sl],
                rc[sl],
                rv[sl],
            )

    def _win_shift(self) -> None:
        new_base = max(0, self.state.last_committed_round)
        d = new_base - self._win_base
        if d <= 0:
            return
        W = self.max_window
        if d >= W:
            # Nothing in the old window survives: fresh zero buffers beat
            # a shift dispatch.
            self._dev_exists = jnp.zeros((W, self._n), dtype=jnp.int32)
            self._dev_parent = jnp.zeros((W, self._n, self._n), dtype=jnp.int32)
        else:
            _m_shifts.inc()
            self._dev_exists, self._dev_parent = window_shift_op(
                self._dev_exists, self._dev_parent, jnp.int32(d), W
            )
        self._win_base = new_base
        # Prune host maps below the window (slot-0 certs resolve no parents).
        self._digest_pos = {
            k: v for k, v in self._digest_pos.items() if v[0] >= new_base
        }
        self._waiting_child = {
            k: kept
            for k, v in self._waiting_child.items()
            if (kept := [e for e in v if e[0] > new_base])
        }
        # Certificates that arrived beyond the window during the stall now
        # (possibly) fit: re-stage them for the next flush.
        overflow, self._overflow = self._overflow, []
        self._pending.extend(overflow)

    # -- device order_leaders ------------------------------------------

    def prewarm(self) -> None:
        """Compile (or cache-load) every kernel on the commit path —
        flush scatter, shift gather, commit scan — at their one static
        shape, off the critical path (call at node boot).  Scratch buffers
        only: the instance window is untouched."""
        n = self._n
        W = self.max_window
        C = self._cap
        e = jnp.zeros((W, n), dtype=jnp.int32)
        p = jnp.zeros((W, n, n), dtype=jnp.int32)
        iw = np.full(C, W, dtype=np.int32)
        ii = np.zeros(C, dtype=np.int32)
        rw = np.full(C, W, dtype=np.int32)
        rc = np.zeros(C, dtype=np.int32)
        rv = np.zeros((C, n), dtype=np.int32)
        e, p = window_apply(e, p, iw, ii, rw, rc, rv)
        e, p = window_shift_op(e, p, jnp.int32(1), W)
        leader_commit_scan_counts(
            p,
            e,
            np.zeros((W, n), dtype=bool),
            np.zeros((W,), dtype=bool),
            jnp.int32(0),
            np.zeros((n,), dtype=bool),
            W,
        ).block_until_ready()

    # _leader_name is inherited from Tusk (the indexed base class).

    def order_leaders(self, leader) -> List:
        state = self.state
        n = self._n
        base = max(0, state.last_committed_round)
        span = leader.round - base + 1
        window = self.max_window
        if span > window or base != self._win_base:
            self.python_fallbacks += 1
            _m_fallbacks.inc()
            return super().order_leaders(leader)

        self._flush_pending()

        leader_onehot = np.zeros((window, n), dtype=bool)
        is_leader_slot = np.zeros(window, dtype=bool)
        for r in range(leader.round - 2, state.last_committed_round, -2):
            name = self._leader_name(r)
            if state.dag.get(r, {}).get(name) is not None:
                leader_onehot[r - base, self._index[name]] = True
                is_leader_slot[r - base] = True

        anchor_onehot = np.zeros(n, dtype=bool)
        anchor_onehot[self._index[leader.origin]] = True
        # The ONLY device→host transfer on the commit path: W bools.
        committed = np.asarray(
            leader_commit_scan_counts(
                self._dev_parent,
                self._dev_exists,
                leader_onehot,
                is_leader_slot,
                jnp.int32(leader.round - base),
                anchor_onehot,
                window,
            )
        )

        # Newest-first chain, exactly as the golden order_leaders returns it.
        to_commit = [leader]
        for w in range(window - 1, -1, -1):
            if committed[w]:
                r = base + w
                _, cert = state.dag[r][self._leader_name(r)]
                to_commit.append(cert)
        return to_commit
