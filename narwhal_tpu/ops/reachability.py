"""Tusk's DAG traversals as one jitted boolean-matrix scan on device.

The reference commit rule (consensus/src/lib.rs:224-303) does two kinds of
graph walk per candidate leader:

- ``order_leaders`` calls ``linked()`` once per earlier leader — each call a
  round-by-round BFS over the whole certificate window (lib.rs:247-259);
- ``order_dag`` flattens the causal history of every newly committed leader
  (lib.rs:263-303).

Both are frontier propagations through the round-structured DAG.  Here the
window is a dense tensor — ``exists[w, n]`` (certificate present at slot w,
authority n) and ``parent[w, n, m]`` (cert (w, n) references cert (w-1, m)) —
and a single ``lax.scan`` down the window computes the ENTIRE leader chain:
the frontier is a length-N boolean vector, each step is a vector–matrix
product (int32 matmul → MXU), and when the frontier reaches the leader of an
even round the scan records a committed leader and resets the frontier to
that leader alone (exactly the ``leader = prev_leader`` rebinding in
``order_leaders``).  The same scan emits the per-slot reach masks used to
bound the host-side emission DFS.

Slots are fixed-size (static shapes for XLA): slot w holds round
``base_round + w``.  The committee axis N is padded to the committee size;
the window W to a static power-of-two ≥ gc_depth.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax


@partial(jax.jit, static_argnames=("window",))
def leader_chain_scan(
    parent: jax.Array,  # bool[W, N, N]
    exists: jax.Array,  # bool[W, N]
    leader_onehot: jax.Array,  # bool[W, N] — leader identity of slot w's round
    is_leader_slot: jax.Array,  # bool[W] — even round in (last_committed, anchor)
    anchor_slot: jax.Array,  # i32 scalar
    anchor_onehot: jax.Array,  # bool[N]
    window: int,
) -> Tuple[jax.Array, jax.Array]:
    """One descending scan = the whole ``order_leaders`` chain.

    Returns ``(committed[W], reach[W, N])``: committed[w] marks the round at
    slot w as a linked (to-commit) leader round; reach[w] is the certificate
    frontier at slot w (the causal cone of the current chain head), which
    upper-bounds the certificates ``order_dag`` can emit from that slot.
    """
    W = window

    def step(frontier, xs):
        w, parent_up, exists_w, leader_w, is_lead_w = xs
        # Certificates at slot w referenced by the frontier one round up.
        # int32 matvec: lands on the MXU for large committees, exact for bool.
        hit = (
            jnp.matmul(
                frontier.astype(jnp.int32),
                parent_up.astype(jnp.int32),
                preferred_element_type=jnp.int32,
            )
            > 0
        )
        g = hit & exists_w
        g = jnp.where(w == anchor_slot, anchor_onehot, g)
        lead_here = is_lead_w & (w < anchor_slot) & jnp.any(g & leader_w)
        # Frontier reset: the chain head becomes this leader (order_leaders'
        # ``leader = prev_leader``), so deeper reachability is from it alone.
        new_frontier = jnp.where(lead_here, g & leader_w, g)
        return new_frontier, (lead_here, g)

    slots = jnp.arange(W - 1, -1, -1, dtype=jnp.int32)
    # Step at slot w consumes parent[w+1] (edges slot w+1 → slot w).
    parent_up = jnp.concatenate(
        [parent[1:], jnp.zeros((1,) + parent.shape[1:], parent.dtype)], axis=0
    )
    xs = (
        slots,
        parent_up[slots],
        exists[slots],
        leader_onehot[slots],
        is_leader_slot[slots],
    )
    _, (committed_rev, reach_rev) = lax.scan(
        step, jnp.zeros(exists.shape[1], dtype=bool), xs
    )
    return committed_rev[::-1], reach_rev[::-1]


@partial(jax.jit, static_argnames=("window",))
def causal_mask_scan(
    parent: jax.Array,  # bool[W, N, N]
    exists: jax.Array,  # bool[W, N]
    start_slot: jax.Array,  # i32 scalar
    start_onehot: jax.Array,  # bool[N]
    window: int,
) -> jax.Array:
    """Full causal cone of one certificate: bool[W, N] mask of every
    certificate reachable from (start_slot, start_onehot) through parent
    links — the set ``order_dag`` flattens (lib.rs:263-303).  Unlike
    :func:`leader_chain_scan` the frontier accumulates (no resets)."""
    W = window

    def step(frontier, xs):
        w, parent_up, exists_w = xs
        hit = (
            jnp.matmul(
                frontier.astype(jnp.int32),
                parent_up.astype(jnp.int32),
                preferred_element_type=jnp.int32,
            )
            > 0
        )
        g = hit & exists_w
        g = g | jnp.where(w == start_slot, start_onehot, False)
        return g, g

    slots = jnp.arange(W - 1, -1, -1, dtype=jnp.int32)
    parent_up = jnp.concatenate(
        [parent[1:], jnp.zeros((1,) + parent.shape[1:], parent.dtype)], axis=0
    )
    xs = (slots, parent_up[slots], exists[slots])
    _, mask_rev = lax.scan(step, jnp.zeros(exists.shape[1], dtype=bool), xs)
    return mask_rev[::-1]


@partial(jax.jit, static_argnames=("window",))
def support_stake(
    parent: jax.Array,  # bool[W, N, N]
    exists: jax.Array,  # bool[W, N]
    stake: jax.Array,  # i32[N]
    leader_slot: jax.Array,  # i32 scalar
    leader_onehot: jax.Array,  # bool[N]
    window: int,
) -> jax.Array:
    """Stake of slot leader_slot+1 certificates referencing the leader —
    the f+1 support gate (lib.rs:141-157)."""
    child = parent[leader_slot + 1]  # bool[N, N]: child cert → its parents
    votes = jnp.any(child & leader_onehot[None, :], axis=1)
    votes = votes & exists[leader_slot + 1]
    return jnp.sum(jnp.where(votes, stake, 0))


class DagWindow:
    """Dense tensor view of a Tusk DAG window, built from the live dict DAG.

    Host-side glue: maps (round, authority) → (slot, index), resolves parent
    digests, and hands fixed-shape arrays to the jitted scans.  Rebuilt per
    commit attempt — O(window · N · parents) dict work, replacing up to
    window/2 independent BFS passes of the same cost each.
    """

    def __init__(
        self,
        dag,  # Dag: round → {authority → (digest, certificate)}
        names: List,  # sorted authority public keys
        base_round: int,
        window: int,
    ) -> None:
        self.names = names
        self.index = {name: i for i, name in enumerate(names)}
        self.base_round = base_round
        self.window = window
        n = len(names)
        self.exists = np.zeros((window, n), dtype=bool)
        self.parent = np.zeros((window, n, n), dtype=bool)
        # digest → (slot, authority index) for every cert in the window
        digest_pos: Dict[bytes, Tuple[int, int]] = {}
        for r, certs in dag.items():
            w = r - base_round
            if 0 <= w < window:
                for name, (digest, _) in certs.items():
                    i = self.index[name]
                    self.exists[w, i] = True
                    digest_pos[bytes(digest)] = (w, i)
        for r, certs in dag.items():
            w = r - base_round
            if not (1 <= w < window):
                continue
            for name, (_, cert) in certs.items():
                i = self.index[name]
                for pd in cert.header.parents:
                    pos = digest_pos.get(bytes(pd))
                    if pos is not None and pos[0] == w - 1:
                        self.parent[w, i, pos[1]] = True

    def slot(self, round_: int) -> int:
        return round_ - self.base_round

    def onehot(self, name) -> np.ndarray:
        v = np.zeros(len(self.names), dtype=bool)
        v[self.index[name]] = True
        return v


from ..consensus.tusk import Tusk


class KernelTusk(Tusk):
    """Tusk with ``order_leaders`` executed on device: same decisions as the
    golden Python implementation (consensus/tusk.py, validated
    certificate-for-certificate by tests/test_reachability.py), with the
    window traversals collapsed into one :func:`leader_chain_scan`.  The
    emission DFS (``order_dag``) stays host-side — it is O(output) and must
    produce the exact reference DFS tie-order.

    The scan runs at ONE static window shape — the smallest power of two
    covering gc_depth+2 rounds, compiled once by :meth:`prewarm` — because
    GC bounds the live DAG span to gc_depth rounds (consensus/src/lib.rs:
    56-61).  A span beyond that (only possible transiently, e.g. a commit
    stall racing GC) falls back to the golden Python walk instead of
    triggering a fresh XLA compile of a bigger shape on the consensus
    critical path."""

    def __init__(self, committee, gc_depth, fixed_coin: bool = False) -> None:
        super().__init__(committee, gc_depth, fixed_coin=fixed_coin)
        w = 8
        while w < gc_depth + 2:
            w <<= 1
        self.max_window = w
        self.python_fallbacks = 0  # observability: stalls beyond the window

    def prewarm(self) -> None:
        """Compile (or cache-load) the scan at its one static shape off the
        commit critical path (call at node boot)."""
        n = len(self._sorted_keys)
        W = self.max_window
        leader_chain_scan(
            jnp.zeros((W, n, n), bool),
            jnp.zeros((W, n), bool),
            jnp.zeros((W, n), bool),
            jnp.zeros((W,), bool),
            jnp.int32(0),
            jnp.zeros((n,), bool),
            W,
        )

    def _leader_name(self, round_: int):
        coin = 0 if self.fixed_coin else round_
        return self._sorted_keys[coin % len(self._sorted_keys)]

    def order_leaders(self, leader) -> List:
        state = self.state
        names = self._sorted_keys
        n = len(names)
        base = max(0, state.last_committed_round)
        span = leader.round - base + 1
        window = self.max_window
        if span > window:
            self.python_fallbacks += 1
            return super().order_leaders(leader)
        win = DagWindow(state.dag, names, base, window)

        leader_onehot = np.zeros((window, n), dtype=bool)
        is_leader_slot = np.zeros(window, dtype=bool)
        for w in range(window):
            r = base + w
            if r % 2 == 0 and state.last_committed_round < r < leader.round:
                name = self._leader_name(r)
                if state.dag.get(r, {}).get(name) is not None:
                    leader_onehot[w, win.index[name]] = True
                    is_leader_slot[w] = True

        committed, _reach = leader_chain_scan(
            jnp.asarray(win.parent),
            jnp.asarray(win.exists),
            jnp.asarray(leader_onehot),
            jnp.asarray(is_leader_slot),
            jnp.int32(win.slot(leader.round)),
            jnp.asarray(win.onehot(leader.origin)),
            window,
        )
        committed = np.asarray(committed)

        # Newest-first chain, exactly as the golden order_leaders returns it.
        to_commit = [leader]
        for w in range(window - 1, -1, -1):
            if committed[w]:
                r = base + w
                _, cert = state.dag[r][self._leader_name(r)]
                to_commit.append(cert)
        return to_commit
