"""TPU-resident kernels (JAX/XLA) behind the framework's CPU seams.

- reachability: the Tusk commit rule's graph traversals (linked()/order_dag
  frontier walks, reference consensus/src/lib.rs:247-303) as one jitted
  boolean matrix scan over the (gc_depth x committee) certificate window.
- ed25519: batched on-device signature verification (reference
  crypto/src/lib.rs:206-219 verify_batch) — field/point arithmetic from
  32-bit lanes, vmapped over the batch.

Import is deferred by callers (crypto.backend, consensus) so the pure-CPU
protocol path never pays the JAX import cost.
"""

import os as _os

import jax as _jax

# Persistent XLA compilation cache: the verify/commit kernels take tens of
# seconds to compile on a TPU terminal; cache them across node processes
# (every primary spawns fresh in the bench harness).
from ..utils.env import env_str as _env_str

_cache_dir = _env_str("NARWHAL_JAX_CACHE") or _os.path.join(
    _os.path.expanduser("~"), ".cache", "narwhal_tpu_jax"
)
try:
    _jax.config.update("jax_compilation_cache_dir", _cache_dir)
    _jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:  # older jax without the knob: compile per-process
    pass
