"""TPU-resident kernels (JAX/XLA) behind the framework's CPU seams.

- reachability: the Tusk commit rule's graph traversals (linked()/order_dag
  frontier walks, reference consensus/src/lib.rs:247-303) as one jitted
  boolean matrix scan over the (gc_depth x committee) certificate window.
- ed25519: batched on-device signature verification (reference
  crypto/src/lib.rs:206-219 verify_batch) — field/point arithmetic from
  32-bit lanes, vmapped over the batch.

Import is deferred by callers (crypto.backend, consensus) so the pure-CPU
protocol path never pays the JAX import cost.
"""
