"""GF(2^255 - 19) arithmetic from 32-bit vector lanes, batch-first.

TPU has no native 64-bit multiply, so field elements are 32 limbs of 8
bits (radix 2^8) held in 32-bit lanes.  The radix keeps every
intermediate exactly representable: weak limbs < 2^9, pairwise products
< 2^18, a 32-term convolution row < 2^23.  The schoolbook convolution
runs as 32 fused shifted multiply-accumulates on the VPU (see mul() for
why this beats the MXU matmul formulation on v5e); carries, folds and
comparisons are elementwise, also VPU.  This is the TPU-shaped answer to
the reference's ed25519-dalek (crypto/src/lib.rs:206-219), whose Rust
backend uses 51-bit limbs in u128 — a layout that cannot map to vector
lanes.

Lane dtype is selected by ``NARWHAL_FIELD_DTYPE`` at import: ``int32``
(default) or ``float32``.  The f32 variant exists because the VPU is an
f32 machine first — if 32-bit integer multiply is emulated or
rate-limited, the same algorithm in floats wins.  Every f32 intermediate
is an INTEGER kept strictly below 2^24 (the f32 exact-integer range):
the 2^23 convolution-row bound fits as-is; carries use an exact
power-of-two scale + floor instead of shifts; mul's ×38 fold is split
into two sub-2^24 halves (see mul()).  The same differential suite
proves either dtype against Python big ints: the default test run
covers int32 plus an f32 field-op subprocess check
(tests/test_ed25519.py::test_float32_lane_mode_field_ops); the FULL
suite under f32 is `make test-f32` — run it after touching any op here.

All functions are batch-first: an element is ``[..., 32]`` of DTYPE and
every op vmaps/broadcasts over leading axes.  Limb i holds bits
[8i, 8i+8).  Outputs of mul/add/sub are *weakly reduced* (limbs < 2^9 —
see carry(); value possibly ≥ p); ``canon`` fully reduces into [0, p)
with limbs < 2^8.

Correctness strategy: every op is differential-tested against Python big
ints over random + boundary values, and every intermediate has a proven
magnitude bound (2^31 budget in int32 mode, 2^24 in float32 mode — the
tighter f32 bounds are noted where they differ).
"""

from __future__ import annotations


import numpy as np

import jax
import jax.numpy as jnp

from ..utils.env import env_str

BITS = 8
LIMBS = 32
MASK = (1 << BITS) - 1
P = (1 << 255) - 19

# 2^(BITS·LIMBS) = 2^256 ≡ 38 (mod p): folding multiplier for limbs ≥ LIMBS.
FOLD = 38

_DTYPE_ENV = env_str("NARWHAL_FIELD_DTYPE")
if _DTYPE_ENV not in ("int32", "float32"):
    # Fail loud: a typo ("f32", "fp32") silently falling back to int32
    # would mislabel every measurement made under it.
    raise ValueError(
        f"NARWHAL_FIELD_DTYPE must be 'int32' or 'float32', got "
        f"{_DTYPE_ENV!r}"
    )
FP = _DTYPE_ENV == "float32"
DTYPE = jnp.float32 if FP else jnp.int32
NP_DTYPE = np.float32 if FP else np.int32
_INV_RADIX = 1.0 / (1 << BITS)  # exact power-of-two scale for f32 carries


def to_limbs(x: int) -> np.ndarray:
    """Python int → limb vector (host-side prep)."""
    return np.array([(x >> (BITS * i)) & MASK for i in range(LIMBS)],
                    dtype=NP_DTYPE)


def from_limbs(limbs) -> int:
    """Limb vector → Python int (host-side check); accepts unreduced."""
    arr = np.asarray(limbs, dtype=np.int64)
    return sum(int(v) << (BITS * i) for i, v in enumerate(arr))


def _split(c: jnp.ndarray):
    """(carry, low 8 bits) of every limb.  int32: shift/mask.  float32:
    exact scale-by-2^-8 + floor, then subtract back — every step is exact
    for integer-valued c < 2^24 (scaling by a power of two never rounds,
    floor of an exact value is exact, and hi·256 < 2^24)."""
    if FP:
        hi = jnp.floor(c * _INV_RADIX)
        lo = c - hi * (1 << BITS)
    else:
        hi = c >> BITS
        lo = c & MASK
    return hi, lo


def _carry_once(c: jnp.ndarray) -> jnp.ndarray:
    """One vectorized carry sweep; the carry out of the top limb wraps to
    limb 0 multiplied by 38 (2^256 ≡ 38 mod p)."""
    hi, lo = _split(c)
    out = lo.at[..., 1:].add(hi[..., :-1])
    return out.at[..., 0].add(hi[..., -1] * FOLD)


def carry(c: jnp.ndarray, sweeps: int = 4) -> jnp.ndarray:
    """Propagate carries until every limb is weakly reduced: **< 2^9**
    (NOT < 2^8 — the final sweep can both leave a limb at 255 + carry-in
    and add the ×38 top-limb wrap to limb 0, so limb 0 reaches up to
    255 + 38 = 293).  With the default 4 sweeps, input limbs may be up to
    2^31 (int32 mode; < 2^24 in float32 mode — every in-tree caller stays
    under 2^23.3): the sweep bounds are ≤ 255 + 2^23, ≤ 255 + 2^15,
    ≤ 255 + 2^7, then < 2^9.  Every consumer is dimensioned for the 2^9
    weak bound (see mul's exactness note and sub's ZP offset).

    ``sweeps`` lets callers with tighter input bounds skip work (each
    sweep is ~5 vector ops on the hot path); every reduced-sweep call
    site must carry its own bound proof (see add/sub)."""
    for _ in range(sweeps):
        c = _carry_once(c)
    return c


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Field multiply, weakly reduced output.

    The schoolbook convolution c[k] = Σ_{i+j=k} a_i·b_j is computed as 32
    fused shifted multiply-accumulates on the VPU in DTYPE lanes.
    Exactness: weak limbs are < 2^9 (carry()'s bound), so pairwise
    products are < 2^18 and a convolution row accumulates ≤ 32 of them →
    < 2^23 — inside int32's 2^31 budget and f32's 2^24 exact-integer
    range alike.

    Why not the MXU?  The "one-hot convolution tensor" formulation — a
    single [B·32², 63] f32 matmul — was measured 1.4× SLOWER end-to-end
    on v5e: it must materialize the [B, 32²] outer product through HBM
    (66 MB round trip per multiply at B=8192) and its useful-FLOP ratio
    is 1/63, while the shifted-MAC chain fuses into one VPU kernel whose
    only HBM traffic is the operands and the result."""
    shape = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    conv = jnp.zeros(shape + (2 * LIMBS - 1,), DTYPE)
    pad_base = [(0, 0)] * (b.ndim - 1)
    for i in range(LIMBS):
        conv = conv + a[..., i : i + 1] * jnp.pad(
            b, pad_base + [(i, LIMBS - 1 - i)]
        )
    # Fold limbs ≥ 32: 2^(8(32+j)) ≡ 38·2^(8j) (mod p).
    hi = conv[..., LIMBS:]
    lo = conv[..., :LIMBS]
    if FP:
        # Direct ×38 would reach 38·2^23 ≈ 2^28.3 — outside f32's exact
        # range.  Split each hi limb into 8-bit halves first: hi_hi < 2^15
        # lands one limb higher (2^8·38·2^(8j) = 38·2^(8(j+1))), so both
        # products stay < 2^21 and every folded limb < 2^23 + 2^13.3 +
        # 2^20.3 < 2^23.3 — exact.  hi has 31 entries (j ≤ 30), so j+1 ≤
        # 31 never needs a secondary fold.
        hi_hi, hi_lo = _split(hi)
        folded = lo.at[..., : LIMBS - 1].add(hi_lo * FOLD)
        folded = folded.at[..., 1:LIMBS].add(hi_hi * FOLD)
    else:
        # conv < 2^23 so the ×38 (< 2^29) stays inside int32.
        folded = lo.at[..., : LIMBS - 1].add(hi * FOLD)
    return carry(folded)


def square(a: jnp.ndarray) -> jnp.ndarray:
    """Deliberately just mul(a, a): the symmetry-specialized square
    (≤16 doubled cross terms per convolution row instead of 32) was a
    measured 1.4× win ONLY in the abandoned limbs-major layout, where the
    accumulate slices ran along the compute-mapped sublane axis and
    shorter slices meant fewer tile ops.  Here the limb axis sits on
    lanes: every shifted-accumulate row is one full-width vector op
    whether half its entries are zero or not, so halving the *terms*
    saves no *ops* — the specialization buys nothing and costs an extra
    concatenate per row (see benchmark/field_layout_probe.py for the
    layout story)."""
    return mul(a, a)


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a + b (mod p), weakly reduced.  One carry sweep suffices: both
    operands are weak (< 2^9), so the sum is < 2^10, the per-limb carry
    out is ≤ 3, and after one sweep limbs 1..31 are ≤ 255 + 3 and limb 0
    is ≤ 255 + 3·38 = 369 — all < 2^9."""
    return carry(a + b, sweeps=1)


# Borrow-free subtraction needs a limb vector ZP whose value is ≡ 0 (mod p)
# with EVERY limb ≥ 2^9 (the weak bound on an operand's limbs, see carry()):
# then (a + ZP - b) is non-negative per limb and carry() reduces it.
# Construct: put 2·MASK = 510 in every limb, then add the canonical limbs of
# the complement that makes the total a multiple of p — every final limb is
# ≥ 510 + 0... asserted ≥ 512 below via the 637 minimum that construction
# actually yields.
_base = sum(2 * MASK << (BITS * i) for i in range(LIMBS))
_comp = (-_base) % P
_zp = [2 * MASK + ((_comp >> (BITS * i)) & MASK) for i in range(LIMBS)]
assert sum(v << (BITS * i) for i, v in enumerate(_zp)) % P == 0
assert all((1 << 9) <= v < (1 << 15) for v in _zp), _zp
_ZP = jnp.asarray(np.array(_zp, dtype=NP_DTYPE))


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b (mod p): the ZP offset keeps every limb non-negative.

    Two carry sweeps suffice: a + ZP - b < 2^9 + 2^15 = 33280 per limb,
    so sweep 1's carries are ≤ 130, leaving limbs 1..31 ≤ 255 + 130 and
    limb 0 ≤ 255 + 130·38 = 5195; sweep 2's carries are then ≤ 20
    (limb 0) / ≤ 1 (rest), leaving limb 1 ≤ 275, limbs 2..31 ≤ 256, and
    limb 0 ≤ 255 + 1·38 = 293 — all < 2^9."""
    return carry(a + _ZP - b, sweeps=2)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    """-a (mod p); same bound argument as sub (a ≤ ZP + 2^9 per limb)."""
    return carry(_ZP - a, sweeps=2)


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small non-negative constant (k ≤ 2^17).

    float32 mode splits k > 2^14 into 8-bit chunks (k·2^9 would pass the
    2^24 exact range): a·k_lo < 2^17 and a·k_hi < 2^18 land one limb
    apart, the top chunk folds ×38 into limb 0 (38·2^18 < 2^23.3), and
    every partial stays exact."""
    assert 0 <= k <= (1 << 17), k
    if FP and k > (1 << 14):
        k_hi, k_lo = k >> BITS, k & MASK
        lo_part = a * jnp.asarray(k_lo, DTYPE)
        hi_part = a * jnp.asarray(k_hi, DTYPE)
        c = lo_part.at[..., 1:].add(hi_part[..., :-1])
        c = c.at[..., 0].add(hi_part[..., -1] * FOLD)
        return carry(c)
    return carry(a * jnp.asarray(k, DTYPE))


def pow2k(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """a^(2^k) — k repeated squarings (fori_loop: one compiled body)."""
    return jax.lax.fori_loop(0, k, lambda _, x: square(x), a)


def invert(a: jnp.ndarray) -> jnp.ndarray:
    """a^(p-2) — Fermat inversion, standard 2^255-21 addition chain."""
    x1 = a
    x2 = mul(square(x1), x1)          # 2^2 - 1
    x4 = mul(pow2k(x2, 2), x2)        # 2^4 - 1
    x5 = mul(square(x4), x1)          # 2^5 - 1
    x10 = mul(pow2k(x5, 5), x5)       # 2^10 - 1
    x20 = mul(pow2k(x10, 10), x10)    # 2^20 - 1
    x40 = mul(pow2k(x20, 20), x20)    # 2^40 - 1
    x50 = mul(pow2k(x40, 10), x10)    # 2^50 - 1
    x100 = mul(pow2k(x50, 50), x50)   # 2^100 - 1
    x200 = mul(pow2k(x100, 100), x100)  # 2^200 - 1
    x250 = mul(pow2k(x200, 50), x50)  # 2^250 - 1
    # p - 2 = 2^255 - 21 = (2^250-1)·2^5 + 11;  11 = 0b01011
    t = pow2k(x250, 5)
    return mul(t, mul(mul(square(square(square(x1))), square(x1)), x1))


def pow_p58(a: jnp.ndarray) -> jnp.ndarray:
    """a^((p-5)/8) = a^(2^252 - 3) = (a^(2^250-1))^4 · a."""
    x1 = a
    x2 = mul(square(x1), x1)
    x4 = mul(pow2k(x2, 2), x2)
    x5 = mul(square(x4), x1)
    x10 = mul(pow2k(x5, 5), x5)
    x20 = mul(pow2k(x10, 10), x10)
    x40 = mul(pow2k(x20, 20), x20)
    x50 = mul(pow2k(x40, 10), x10)
    x100 = mul(pow2k(x50, 50), x50)
    x200 = mul(pow2k(x100, 100), x100)
    x250 = mul(pow2k(x200, 50), x50)
    return mul(pow2k(x250, 2), x1)


_P_LIMBS = jnp.asarray(to_limbs(P))


def _sub_p(c: jnp.ndarray):
    """(c - p) with full borrow propagation.  Returns (limbs, underflow):
    underflow True means c < p (result invalid, keep c)."""
    d = c - _P_LIMBS
    d_first = jnp.moveaxis(d, -1, 0)  # [LIMBS, ...]

    def step(borrow, d_i):
        v = d_i - borrow
        neg_ = v < 0
        v = v + jnp.where(
            neg_, jnp.asarray(1 << BITS, DTYPE), jnp.asarray(0, DTYPE)
        )
        return (
            jnp.where(neg_, jnp.asarray(1, DTYPE), jnp.asarray(0, DTYPE)),
            v,
        )

    borrow0 = jnp.zeros(c.shape[:-1], dtype=DTYPE)
    borrow, limbs = jax.lax.scan(step, borrow0, d_first)
    return jnp.moveaxis(limbs, 0, -1), borrow > 0


def canon(a: jnp.ndarray) -> jnp.ndarray:
    """Fully reduce into [0, p) with strictly canonical limbs (< 2^8)."""
    c = carry(a)
    # carry() only guarantees the weak bound (limbs < 2^9, i.e. up to one
    # carry bit above a full 2^8-1 limb), and one sweep only moves such a
    # spike up one position — run LIMBS+2 sweeps so any spike exits the
    # top and wraps to a small limb-0 term, leaving every limb < 2^8.
    for _ in range(LIMBS + 2):
        c = _carry_once(c)
    # Value is now < 2^256 < 3p: strip multiples of p by conditional
    # subtraction until below p (3 rounds give margin).
    for _ in range(3):
        d, under = _sub_p(c)
        c = jnp.where(under[..., None], c, d)
    return c


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(canon(a) == 0, axis=-1)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(canon(a) == canon(b), axis=-1)


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """cond ? a : b, with cond shaped [...] and a/b [..., LIMBS]."""
    return jnp.where(cond[..., None], a, b)
