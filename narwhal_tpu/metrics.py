"""In-process metrics: counters, gauges, histograms, and stage tracing.

The reference ships no metrics at all — every number in its paper tables is
scraped from four log lines (benchmark/benchmark/logs.py), and this repo
inherited that: round 5's mis-measurement (32.6k tx/s at 3 s latency because
queues silently flooded, VERDICT.md §1) had to be reconstructed from log
archaeology.  This module is the first-class replacement: a dependency-free,
near-zero-overhead per-process registry that every layer (worker, network,
primary, consensus, store) writes into, plus

- :class:`SnapshotWriter` — periodic atomic rewrite of one
  ``metrics-<node>.json`` per process (write-temp + ``os.replace``, same
  pattern as the consensus checkpoint), final snapshot flushed on cancel;
- :class:`MetricsServer` — an optional Prometheus-text HTTP endpoint gated
  behind ``--metrics-port`` (hand-rolled over ``asyncio.start_server``:
  no http framework dependency);
- :class:`TraceTable` — a bounded per-digest stage-timestamp table that
  threads a sample-transaction trace through the whole pipeline
  (batch-sealed → quorum → digest-at-primary → header → certificate →
  committed), the per-stage latency breakdown the Narwhal paper uses to
  argue the digest-only critical path;
- :class:`HealthMonitor` — a declarative anomaly-rules engine evaluated
  on a timer over registry values (absolute ceilings, rate-of-change
  windows, per-peer thresholds) with hysteresis, feeding structured
  anomaly events to the log, a ``health`` section in snapshots, and the
  ``/healthz`` route (200/503) on the :class:`MetricsServer` — live
  detection of the wedges (stalled peer, quorum-waiter at 2f, backoff
  storm) that post-mortem snapshot archaeology only finds after the run.

Hot-path cost model: a counter ``inc`` is one attribute add, a histogram
``observe`` is one ``bisect`` + two adds; queue depths and sender backlogs
are *callback* gauges evaluated only when a snapshot is taken, so the hot
path never pays for them.  ``NARWHAL_METRICS=0`` swaps the whole registry
for shared no-op instruments — the stub the bench harness uses to measure
the instrumentation overhead itself.

Everything here assumes the single-event-loop execution model of the node
(like the Store): plain attribute updates need no locks.
"""

from __future__ import annotations

import asyncio
import collections
import json
import logging
import os
import time
from bisect import bisect_left
from typing import (
    Callable,
    Deque,
    Dict,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
)

from .utils.clock import wall_now
from .utils.env import env_flag, env_float, env_int, env_str

log = logging.getLogger("narwhal.metrics")

# Latency buckets (seconds): 1 ms … 10 s, roughly log-spaced.  Chosen to
# straddle the measured pipeline: quorum ACKs sit in the 1-50 ms range on
# loopback, end-to-end commits in the 100 ms-3 s range (BASELINE.md).
LATENCY_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

# Size/count buckets (e.g. KernelTusk flush batch sizes, queue bursts).
COUNT_BUCKETS: Tuple[float, ...] = (
    1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024,
)

# Millisecond-scaled latency buckets for series published in ms
# (consensus.support_arrival_ms): same spread as LATENCY_BUCKETS, 1 ms
# to 10 s, so the two families bucket identically up to the unit.
LATENCY_MS_BUCKETS: Tuple[float, ...] = tuple(
    1000.0 * b for b in LATENCY_BUCKETS
)

# Pipeline stages, in causal order.  TraceTable.mark validates against this
# so a typo'd stage name fails loudly in tests instead of silently skewing
# the bench breakdown.  The last four stages subdivide the old opaque
# cert→commit span (77% of seal→commit in the r07 breakdown) so the bench
# attributes where that time goes: protocol cadence (cert_inserted →
# commit_trigger, rounds until the odd-round trigger), walk cost
# (commit_trigger → walk_done), and delivery (walk_done → commit).
STAGES: Tuple[str, ...] = (
    "seal",               # worker: batch sealed (BatchMaker._seal)
    "quorum",             # worker: 2f+1 ACK stake reached (QuorumWaiter)
    "digest_at_primary",  # primary: own digest reached the Proposer
    "header",             # primary: digest included in a created header
    "cert",               # primary: own header's certificate assembled
    "cert_inserted",      # consensus: containing certificate entered Tusk
    "commit_trigger",     # consensus: the arrival that fired the commit rule
    "walk_done",          # consensus: chain walk + causal flatten finished
    "commit",             # consensus: committed certificate delivered
)

# Round-cadence sub-stages, in causal order.  The r09 cert→commit
# attribution showed 97-98% of commit latency is protocol cadence —
# `primary.round_advance_seconds` × commit depth — so the round period
# itself needs the same decomposition cert→commit got.  Each PRIMARY
# stamps these into a second, per-ROUND trace table (key = the decimal
# round number, one entry per round of its own header lifecycle):
#
#   header_proposed   proposer minted our round-r header
#   header_broadcast  core handed the header to the reliable sender
#   first_vote        first vote (incl. our own) for our round-r header
#   vote_quorum       2f+1 vote stake reached — our certificate assembled
#   cert_broadcast    our certificate handed to the reliable sender
#   parent_quorum     2f+1 certificate stake for round r — parents ready
#   round_advance     proposer moved to round r+1
#
# Unlike STAGES (joined committee-wide by digest), these are PER-NODE:
# every primary runs its own cadence loop, so the bench aggregates legs
# across (node, round) pairs without cross-node joining.  The leg from
# round r-1's round_advance to round r's header_proposed (the proposer's
# min/max-header-delay wait) is derived at analysis time, which makes the
# legs telescope to exactly the measured round period.
ROUND_STAGES: Tuple[str, ...] = (
    "header_proposed",
    "header_broadcast",
    "first_vote",
    "vote_quorum",
    "cert_broadcast",
    "parent_quorum",
    "round_advance",
)


class Counter:
    """Monotone counter.  ``inc`` is the hot-path primitive: one add."""

    __slots__ = ("name", "value")

    def __init__(self, name: str) -> None:
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


# Detection-plane counters eligible for per-node attribution (the rule
# each feeds, for the judge's rule→observer join, lives with the sim
# verdict code).  Only consulted at instrument CONSTRUCTION time and only
# when a node scope is active — the production hot path never branches.
DETECTION_COUNTERS = frozenset({
    "primary.equivocations_detected",
    "primary.invalid_signatures",
    "primary.stale_messages",
    "worker.garbage_batches",
    "worker.helper_rejected_requests",
})


class _AttributedCounter:
    """Facade pairing the shared committee-wide counter with a per-node
    ``detect.<counter>.<node>`` shadow.  Handed out by
    ``Registry.counter`` instead of the base counter when a node scope
    (``Registry.node_scope``) is active at construction — which, in the
    single-process simulation, is exactly while one authority's
    components are being built, the only moment the observing node's
    identity exists.  The component holds the facade; readers (health
    rules, snapshots, tests) see the base counter through the registry
    as always."""

    __slots__ = ("_base", "_shadow")

    def __init__(self, base: Counter, shadow: Counter) -> None:
        self._base = base
        self._shadow = shadow

    @property
    def name(self) -> str:
        return self._base.name

    @property
    def value(self) -> int:
        return self._base.value

    def inc(self, n: int = 1) -> None:
        self._base.value += n
        self._shadow.value += n


class Gauge:
    """Point-in-time value, set by the instrumented code."""

    __slots__ = ("name", "value")

    def __init__(self, name: str) -> None:
        self.name = name
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = v

    def inc(self, n: float = 1) -> None:
        self.value += n

    def dec(self, n: float = 1) -> None:
        self.value -= n


class Histogram:
    """Fixed-bucket histogram: count, sum, and per-bucket counts.

    Buckets are upper bounds; values above the last bound land in the
    implicit +Inf bucket.  Internal counts are per-bucket (not cumulative);
    snapshots and the Prometheus rendering emit the cumulative form.
    """

    __slots__ = ("name", "bounds", "counts", "sum", "count")

    def __init__(self, name: str, buckets: Sequence[float] = LATENCY_BUCKETS) -> None:
        self.name = name
        self.bounds: Tuple[float, ...] = tuple(buckets)
        self.counts: List[int] = [0] * (len(self.bounds) + 1)  # +Inf last
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        self.counts[bisect_left(self.bounds, v)] += 1
        self.sum += v
        self.count += 1

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def cumulative(self) -> List[Tuple[float, int]]:
        """[(upper_bound, cumulative_count), ...] ending with (inf, count)."""
        out, acc = [], 0
        for bound, c in zip(self.bounds, self.counts):
            acc += c
            out.append((bound, acc))
        out.append((float("inf"), self.count))
        return out


class TraceTable:
    """Bounded key → {stage: timestamp} table (plus per-key extras like
    the sealed byte count).  Two instances exist per registry: the
    per-digest pipeline trace (``stages=STAGES``, keys are digest hex)
    and the per-round cadence trace (``stages=ROUND_STAGES``, keys are
    decimal round numbers).

    ``mark`` keeps the FIRST timestamp per (key, stage) — matching the
    log parser's earliest-across-nodes convention — and evicts the oldest
    keys FIFO once ``cap`` is exceeded, so a long-lived node's table
    stays bounded.  Timestamps are wall-clock (``utils/clock.wall_now``
    — ``time.time()`` in production): the bench joins stages across
    *processes*, which monotonic clocks cannot do.  Cross-NODE joins of
    these stamps additionally go through the clocksync offset correction
    (benchmark/metrics_check) — raw wall clocks skew across hosts.
    Under the sim, ``wall_now`` rides the virtual clock plus any
    injected per-node skew, so traces stay bit-reproducible per seed.
    """

    __slots__ = ("cap", "entries", "evictions", "stages")

    def __init__(
        self, cap: int = 32_768, stages: Tuple[str, ...] = STAGES
    ) -> None:
        self.cap = cap
        self.stages = stages
        self.entries: Dict[str, Dict[str, float]] = {}
        # Evictions past the cap: each one is a digest the bench-side
        # stage join will silently miss, so the count is exported (see
        # Registry.__init__) and the harness warns loudly when > 0
        # instead of computing a biased breakdown (ROADMAP item).
        self.evictions = 0

    def mark(
        self, digest_hex: str, stage: str, ts: Optional[float] = None, **extra
    ) -> None:
        if stage not in self.stages:
            raise ValueError(f"unknown pipeline stage {stage!r}")
        entry = self.entries.get(digest_hex)
        if entry is None:
            if len(self.entries) >= self.cap:
                # FIFO eviction: dicts iterate in insertion order.
                self.entries.pop(next(iter(self.entries)))
                self.evictions += 1
            entry = self.entries[digest_hex] = {}
        entry.setdefault(stage, ts if ts is not None else wall_now())
        for k, v in extra.items():
            entry.setdefault(k, v)


class WireLedger:
    """Per-(direction, message-type, peer) wire accounting.

    The network layer moves opaque frames; the serialization seam
    (narwhal_tpu/messages.py, primary/messages.py) is where bytes acquire
    a protocol meaning — so senders/receivers are handed the message type
    explicitly (senders at the call site that just encoded it, receivers
    via a plane-appropriate tag classifier) and this ledger turns every
    frame into four numbers:

    - ``wire.out.frames.<type>`` / ``wire.out.bytes.<type>`` — FIRST
      transmissions only;
    - ``wire.out.retransmit_frames.<type>`` / ``_bytes.<type>`` — every
      re-write of an un-ACKed frame after a reconnect (ReliableSender).
      Kept apart so goodput math can never confuse "bytes the protocol
      needed" with "bytes a flapping link cost" — the denominator of the
      goodput ratio uses their SUM, the per-type protocol cost uses only
      the first-transmission counters;
    - ``wire.in.frames.<type>`` / ``wire.in.bytes.<type>`` — receiver
      side, which is how sender-vs-receiver totals reconcile per type.

    Per-peer detail rides in one ``wire.peers`` detail_fn (snapshot-only,
    excluded from Prometheus):
    ``{"out"|"in": {type: {peer: [frames, bytes, re_frames, re_bytes]}}}``.

    Counted bytes are frame PAYLOAD bytes as they ride the wire: the
    framing length prefix and the tiny ACK replies are excluded on both
    sides, so the two directions measure the same thing.  Under wire v2
    the payload is COMPRESSED (per-connection digest references +
    residual deflate), so every account also carries the frame's
    pre-compression logical size into ``wire.<dir>.raw_bytes.<type>`` —
    protocol-composition metrics (cert signature fraction, per-type
    frame anatomy) read the raw series, goodput reads the wire series,
    and their ratio is the measured compression win.
    """

    __slots__ = ("registry", "peers", "_flat", "_raw")

    def __init__(self, reg: "Registry") -> None:
        self.registry = reg
        # direction -> type -> peer -> [frames, bytes, re_frames, re_bytes]
        self.peers: Dict[str, Dict[str, Dict[str, List[int]]]] = {
            "out": {},
            "in": {},
        }
        # (direction, type, retransmit) -> (frames Counter, bytes Counter)
        self._flat: Dict[Tuple[str, str, bool], Tuple[Counter, Counter]] = {}
        # (direction, type) -> pre-compression bytes Counter
        self._raw: Dict[Tuple[str, str], Counter] = {}
        if reg.enabled:
            reg.detail_fn("wire.peers", lambda: self.peers)

    def _counters(
        self, direction: str, msg_type: str, retransmit: bool
    ) -> Tuple[Counter, Counter]:
        key = (direction, msg_type, retransmit)
        pair = self._flat.get(key)
        if pair is None:
            stem = (
                f"wire.{direction}.retransmit"
                if retransmit
                else f"wire.{direction}"
            )
            pair = self._flat[key] = (
                self.registry.counter(
                    f"{stem}_frames.{msg_type}"
                    if retransmit
                    else f"{stem}.frames.{msg_type}"
                ),
                self.registry.counter(
                    f"{stem}_bytes.{msg_type}"
                    if retransmit
                    else f"{stem}.bytes.{msg_type}"
                ),
            )
        return pair

    def account(
        self,
        direction: str,
        msg_type: str,
        peer: str,
        nbytes: int,
        retransmit: bool = False,
        raw_nbytes: Optional[int] = None,
    ) -> None:
        if not self.registry.enabled:
            return
        frames, nbytes_c = self._counters(direction, msg_type, retransmit)
        frames.inc()
        nbytes_c.inc(nbytes)
        if not retransmit:
            key = (direction, msg_type)
            raw_c = self._raw.get(key)
            if raw_c is None:
                raw_c = self._raw[key] = self.registry.counter(
                    f"wire.{direction}.raw_bytes.{msg_type}"
                )
            raw_c.inc(nbytes if raw_nbytes is None else raw_nbytes)
        cell = (
            self.peers[direction]
            .setdefault(msg_type, {})
            .setdefault(peer, [0, 0, 0, 0])
        )
        idx = 2 if retransmit else 0
        cell[idx] += 1
        cell[idx + 1] += nbytes

    def reset(self) -> None:
        for d in self.peers.values():
            d.clear()
        # Flat counters keep identity (they live in the registry's pools
        # and are zeroed by Registry.reset's counter sweep).


class FlightRecorder:
    """Bounded ring of recent structured events — the per-node black box.

    The post-mortem snapshot says *what the totals were*; the scraper
    timeline says *what the rates were*; neither says what the node was
    DOING in its last seconds.  The flight recorder keeps a bounded ring
    of recent structured events:

    - protocol landmarks — commit bursts (``Consensus.run``), round
      advances (``Proposer._advance``);
    - health-rule FIRING/cleared transitions (:class:`HealthMonitor`);
    - event-loop stalls (analysis/watchdog.py) and unhandled background
      task deaths (utils/tasks.py);
    - one ``tick`` per interval with the deltas that contextualize the
      rest: wire bytes in/out, commits, sealed txs, round, pending ACKs
      (the :meth:`run` loop, spawned by node/main.py).

    The ring rides in every registry snapshot (``flight.ring`` detail),
    answers live on ``GET /debug/flight`` (MetricsServer), and **dumps
    atomically to a file** (``NARWHAL_FLIGHT_DIR``) at the moments a
    post-mortem needs it most: the /healthz ok→failing (503) transition,
    SIGTERM, and an unhandled task death — the bench/fault harnesses set
    the directory and attach the dumps to failed verdict artifacts.

    Recording is one dict append into a deque; safe from any thread
    (deque.append is atomic), free when the registry is stubbed.
    """

    __slots__ = ("registry", "enabled", "events", "dumps", "dir", "node_id",
                 "_m_events", "_m_dumps", "_last_tick", "_seq")

    def __init__(self, reg: "Registry", cap: Optional[int] = None) -> None:
        self.registry = reg
        # NARWHAL_FLIGHT=0 stubs the recorder alone (the A/B overhead
        # arm's knob), NARWHAL_METRICS=0 stubs it with everything else.
        self.enabled = reg.enabled and env_flag("NARWHAL_FLIGHT")
        if cap is None:
            cap = env_int("NARWHAL_FLIGHT_CAP")
        self.events: Deque[dict] = collections.deque(maxlen=max(16, cap))
        self.dumps: List[dict] = []  # [{reason, ts, path}] — dump markers
        self.dir: Optional[str] = env_str("NARWHAL_FLIGHT_DIR")
        self.node_id = ""  # node/main.py stamps role-keyprefix
        self._last_tick: Dict[str, float] = {}
        self._seq = 0
        if self.enabled:
            self._m_events = reg.counter("flight.events")
            self._m_dumps = reg.counter("flight.dumps")
            reg.detail_fn("flight.ring", self.snapshot)
        else:
            self._m_events = _NULL  # type: ignore[assignment]
            self._m_dumps = _NULL  # type: ignore[assignment]

    def record(self, kind: str, **fields) -> None:
        if not self.enabled:
            return
        event = {"t": round(time.time(), 4), "kind": kind}
        event.update(fields)
        self.events.append(event)
        self._m_events.inc()

    def tick(self) -> None:
        """One per-interval sample: deltas of the counters that explain
        the landmark events around them (wire/queue pressure, progress).
        Cheap — a handful of dict lookups over the live registry."""
        if not self.enabled:
            return
        reg = self.registry
        cur: Dict[str, float] = {
            "wire_out_b": sum(
                c.value for n, c in reg.counters.items()
                if n.startswith("wire.out.bytes.")
                or n.startswith("wire.out.retransmit_bytes.")
            ),
            "wire_in_b": sum(
                c.value for n, c in reg.counters.items()
                if n.startswith("wire.in.bytes.")
            ),
            "commits": float(
                reg.counters.get(
                    "consensus.committed_certificates", _NULL
                ).value
            ),
            "batches": float(
                reg.counters.get(
                    "consensus.committed_batch_digests", _NULL
                ).value
            ),
            "txs_sealed": float(
                reg.counters.get("worker.txs_sealed", _NULL).value
            ),
        }
        deltas = {
            k: round(v - self._last_tick.get(k, 0.0), 1)
            for k, v in cur.items()
        }
        self._last_tick = cur
        gauges = {}
        rnd = reg.gauges.get("primary.round")
        if rnd is not None:
            gauges["round"] = rnd.value
        acks = reg.gauges.get("net.reliable.pending_acks")
        if acks is not None:
            gauges["pending_acks"] = acks.value
        # InstrumentedQueue depths: only the non-empty channels, so the
        # ring entry stays small in steady state and a filling queue is
        # visible in the last-seconds record a crash dump preserves.
        qdepth = {
            n[len("queue."):-len(".depth")]: g.value
            for n, g in reg.gauges.items()
            if n.startswith("queue.") and n.endswith(".depth") and g.value
        }
        if qdepth:
            gauges["queues"] = qdepth
        self.record("tick", d=deltas, **gauges)

    async def run(self, interval_s: Optional[float] = None) -> None:
        """The tick loop (node/main.py spawns one per process)."""
        if interval_s is None:
            interval_s = env_float("NARWHAL_FLIGHT_INTERVAL_S")
        while True:
            await asyncio.sleep(interval_s)
            self.tick()

    def snapshot(self) -> dict:
        return {
            "node": self.node_id,
            "cap": self.events.maxlen,
            "events": list(self.events),
            "dumps": list(self.dumps),
        }

    def dump(self, reason: str) -> Optional[str]:
        """Atomically write the current ring to ``NARWHAL_FLIGHT_DIR``
        (no-op without a directory — the ring is still pullable via
        /debug/flight).  Returns the path written, if any.  Never raises:
        the recorder fires from teardown paths (SIGTERM, task death)
        where a secondary failure must not mask the primary one."""
        if not self.enabled:
            return None
        self.record("dump", reason=reason)
        self._m_dumps.inc()
        if not self.dir:
            return None
        self._seq += 1
        # node_id embeds a base64 key prefix ('/' and '+' are legal
        # there, not in a filename component) — sanitize for the path.
        stem = "".join(
            c if c.isalnum() or c in "._-" else "_"
            for c in (self.node_id or f"pid{os.getpid()}")
        )
        path = os.path.join(
            self.dir, f"flight-{stem}-{self._seq}-{reason}.json"
        )
        try:
            os.makedirs(self.dir, exist_ok=True)
            body = json.dumps(
                {"reason": reason, "ts": time.time(), **self.snapshot()}
            )
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(body)
            os.replace(tmp, path)
        except OSError:
            log.exception("flight dump to %s failed", path)
            return None
        self.dumps.append(
            {"reason": reason, "ts": round(time.time(), 3), "path": path}
        )
        log.warning("FLIGHT ring dumped (%s) -> %s", reason, path)
        return path

    def reset(self) -> None:
        self.events.clear()
        self.dumps.clear()
        self._last_tick.clear()
        self._seq = 0


class _Null:
    """Shared no-op instrument for the stubbed registry (NARWHAL_METRICS=0).
    One class serves every instrument type: all mutators are no-ops and all
    reads return zeros, so instrumented code needs no enabled-checks."""

    __slots__ = ()
    name = "null"
    value = 0
    sum = 0.0
    count = 0
    mean = 0.0
    bounds: Tuple[float, ...] = ()
    counts: List[int] = []
    cap = 0
    entries: Dict[str, Dict[str, float]] = {}
    evictions = 0
    stages: Tuple[str, ...] = ()

    def inc(self, n=1) -> None: ...
    def dec(self, n=1) -> None: ...
    def set(self, v) -> None: ...
    def observe(self, v) -> None: ...
    def mark(self, digest_hex, stage, ts=None, **extra) -> None: ...
    def cumulative(self) -> list: return []


_NULL = _Null()


class Registry:
    """Per-process instrument registry.

    Instruments are memoized by name (dotted ``layer.metric`` hierarchy),
    so modules fetch them once at init and hold direct references — lookup
    never sits on a hot path.  ``gauge_fn`` registers a zero-cost callback
    gauge evaluated only at snapshot time (queue depths, sender backlogs);
    ``detail_fn`` is the same but may return any JSON value (e.g. a
    per-peer dict) and is excluded from the Prometheus rendering, which is
    scalar-only.
    """

    def __init__(self, enabled: bool = True, trace_cap: int = 32_768) -> None:
        self.enabled = enabled
        # Active node-attribution scope (see node_scope): None in
        # production; the sim sets it around each authority's spawn.
        self._node_scope: Optional[str] = None
        self.counters: Dict[str, Counter] = {}
        self.gauges: Dict[str, Gauge] = {}
        self.histograms: Dict[str, Histogram] = {}
        self.gauge_fns: Dict[str, Callable[[], float]] = {}
        self.detail_fns: Dict[str, Callable[[], object]] = {}
        self.trace: TraceTable = (
            TraceTable(trace_cap) if enabled else _NULL  # type: ignore
        )
        # Per-round cadence trace (ROUND_STAGES): one entry per round the
        # local primary's header lifecycle passes through.  Bounded much
        # tighter than the digest trace — rounds arrive at ~10/s, so 4096
        # covers runs far longer than any bench window.
        self.round_trace: TraceTable = (
            TraceTable(4096, stages=ROUND_STAGES)
            if enabled
            else _NULL  # type: ignore
        )
        # Attached HealthMonitor (node/main.py wires one per process);
        # snapshots then carry a `health` section and the MetricsServer
        # answers /healthz from it.
        self.health: Optional["HealthMonitor"] = None
        # Per-(direction, message-type, peer) wire accounting; the
        # network senders/receiver feed it (see WireLedger).
        self.wire = WireLedger(self)
        # Flight recorder: bounded ring of recent structured events,
        # dumped on 503/SIGTERM/task-death (see FlightRecorder).
        self.flight = FlightRecorder(self)
        if enabled:
            self.gauge_fn(
                "metrics.trace_evictions", lambda: self.trace.evictions
            )

    def counter(self, name: str) -> Counter:
        if not self.enabled:
            return _NULL  # type: ignore[return-value]
        c = self.counters.get(name)
        if c is None:
            c = self.counters[name] = Counter(name)
        if self._node_scope is not None and name in DETECTION_COUNTERS:
            shadow = f"detect.{name}.{self._node_scope}"
            s = self.counters.get(shadow)
            if s is None:
                s = self.counters[shadow] = Counter(shadow)
            return _AttributedCounter(c, s)  # type: ignore[return-value]
        return c

    def node_scope(self, label: str):
        """Scope instrument construction to one node of an in-process
        committee: DETECTION_COUNTERS fetched inside the scope also feed
        a per-node ``detect.<counter>.<label>`` shadow, so a shared-
        registry harness can name WHICH validator observed the evidence
        behind a fired rule instead of only that the committee did.
        Spawns are sequential in the sim, so a plain attribute (no
        contextvar) is sufficient; production node processes never open
        a scope and pay nothing."""
        registry = self

        class _Scope:
            def __enter__(self):
                self._prev = registry._node_scope
                registry._node_scope = label
                return registry

            def __exit__(self, *exc):
                registry._node_scope = self._prev

        return _Scope()

    def gauge(self, name: str) -> Gauge:
        if not self.enabled:
            return _NULL  # type: ignore[return-value]
        g = self.gauges.get(name)
        if g is None:
            g = self.gauges[name] = Gauge(name)
        return g

    def histogram(
        self, name: str, buckets: Sequence[float] = LATENCY_BUCKETS
    ) -> Histogram:
        if not self.enabled:
            return _NULL  # type: ignore[return-value]
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = Histogram(name, buckets)
        return h

    def gauge_fn(self, name: str, fn: Callable[[], float]) -> None:
        """Callback gauge, polled at snapshot/scrape time only.
        Re-registration overwrites (in-process multi-node tests)."""
        if self.enabled:
            self.gauge_fns[name] = fn

    def detail_fn(self, name: str, fn: Callable[[], object]) -> None:
        """Like gauge_fn but may return structured JSON (snapshot only)."""
        if self.enabled:
            self.detail_fns[name] = fn

    def reset(self) -> None:
        """Zero every instrument IN PLACE (test isolation).  Module-level
        code holds direct references fetched at import time (e.g. the
        network counters), so instruments must keep their identity — only
        their values reset.  Callback gauges are kept too: a callback over
        a torn-down object fails in-band at snapshot time.  Production
        code never calls this."""
        for c in self.counters.values():
            c.value = 0
        for g in self.gauges.values():
            g.value = 0.0
        for h in self.histograms.values():
            h.counts = [0] * (len(h.bounds) + 1)
            h.sum = 0.0
            h.count = 0
        if self.enabled:
            self.trace.entries.clear()
            self.trace.evictions = 0
            self.round_trace.entries.clear()
            self.round_trace.evictions = 0
        self.wire.reset()
        self.flight.reset()
        # A monitor attached by a previous test would otherwise keep
        # reporting rule state over the zeroed instruments.
        self.health = None

    # -- export --------------------------------------------------------------

    def snapshot(self, include_trace: bool = True) -> dict:
        """One JSON-serializable dict of everything, callback gauges
        evaluated now.  A failing callback is reported in-band (under
        ``errors``) instead of killing the snapshot loop.

        ``include_trace=False`` omits the stage-trace table — it dominates
        the serialized size (hundreds of kB on a bench run, ~12 ms of
        json.dumps on a slow core), and the periodic writer skips it on
        most rewrites to keep the 1 Hz snapshot cost off the committee's
        shared core."""
        errors: List[str] = []

        def call(name, fn):
            try:
                return fn()
            except Exception as e:  # a dead queue/sender must not kill us
                errors.append(f"{name}: {e!r}")
                return None

        snap = {
            "ts": time.time(),
            "pid": os.getpid(),
            "enabled": self.enabled,
            "counters": {n: c.value for n, c in self.counters.items()},
            "gauges": {
                **{n: g.value for n, g in self.gauges.items()},
                **{n: call(n, fn) for n, fn in self.gauge_fns.items()},
            },
            "histograms": {
                n: {
                    "count": h.count,
                    "sum": h.sum,
                    "mean": h.mean,
                    "buckets": [
                        [b if b != float("inf") else "inf", c]
                        for b, c in h.cumulative()
                    ],
                }
                for n, h in self.histograms.items()
            },
            "detail": {n: call(n, fn) for n, fn in self.detail_fns.items()},
            "trace": (
                dict(self.trace.entries)
                if self.enabled and include_trace
                else {}
            ),
            # Small (one entry per round, not per digest) but gated with
            # the digest trace anyway: the bench attribution reads the
            # final cancellation flush, which always includes it.
            "round_trace": (
                dict(self.round_trace.entries)
                if self.enabled and include_trace
                else {}
            ),
        }
        if self.health is not None:
            health = call("health", self.health.health_snapshot)
            if health is not None:
                snap["health"] = health
        if errors:
            snap["errors"] = errors
        return snap

    def render_prometheus(self) -> str:
        """Prometheus text exposition (version 0.0.4).  Dotted names become
        underscore-joined with a ``narwhal_`` prefix; counters get the
        ``_total`` suffix, histograms the ``_bucket/_sum/_count`` triple."""

        def mangle(name: str) -> str:
            # ':' covers per-peer instruments whose names embed a peer
            # address (net.reliable.peer.*.<host:port>).
            return "narwhal_" + (
                name.replace(".", "_").replace("-", "_").replace(":", "_")
            )

        lines: List[str] = []
        for n, c in sorted(self.counters.items()):
            m = mangle(n) + "_total"
            lines.append(f"# TYPE {m} counter")
            lines.append(f"{m} {c.value}")
        gauges = {n: g.value for n, g in self.gauges.items()}
        for n, fn in self.gauge_fns.items():
            try:
                gauges[n] = fn()
            except Exception:
                continue
        for n in sorted(gauges):
            m = mangle(n)
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {gauges[n]}")
        for n, h in sorted(self.histograms.items()):
            m = mangle(n)
            lines.append(f"# TYPE {m} histogram")
            for bound, acc in h.cumulative():
                le = "+Inf" if bound == float("inf") else repr(float(bound))
                lines.append(f'{m}_bucket{{le="{le}"}} {acc}')
            lines.append(f"{m}_sum {h.sum}")
            lines.append(f"{m}_count {h.count}")
        return "\n".join(lines) + "\n"


# -- live health: declarative anomaly rules over the registry -----------------

class HealthRule:
    """One anomaly rule with hysteresis.

    ``check(ctx)`` returns ``{subject: detail}`` for every breaching
    subject — ``""`` for node-wide rules, a peer address for per-peer
    rules — where ``detail`` is a small JSON dict (observed value,
    threshold).  The monitor owns the hysteresis: a subject must breach
    ``for_intervals`` consecutive evaluations to start FIRING and pass
    ``clear_intervals`` consecutive clean evaluations to clear, so one
    noisy sample can neither raise nor silence an anomaly (no flapping).

    ``series`` names counters/gauges whose history the monitor must keep
    (exact names or ``prefix.*`` patterns) so the rule can ask for rates
    and change ages; rules reading only instantaneous values leave it
    empty.
    """

    def __init__(
        self,
        name: str,
        check: Callable[["HealthContext"], Dict[str, dict]],
        for_intervals: int = 1,
        clear_intervals: int = 2,
        series: Sequence[str] = (),
    ) -> None:
        self.name = name
        self.check = check
        self.for_intervals = max(1, for_intervals)
        self.clear_intervals = max(1, clear_intervals)
        self.series = tuple(series)


def _lookup_value(reg: Registry, name: str) -> Optional[float]:
    """One definition of the instrument-resolution chain every health
    read uses: counter → plain gauge → callback gauge (a failing
    callback reads as absent, same policy as the snapshot path)."""
    c = reg.counters.get(name)
    if c is not None:
        return float(c.value)
    g = reg.gauges.get(name)
    if g is not None:
        return float(g.value)
    fn = reg.gauge_fns.get(name)
    if fn is not None:
        try:
            return float(fn())
        except Exception:
            return None
    return None


class HealthContext:
    """What a rule's ``check`` sees: instantaneous registry values plus
    the monitor's sampled history (rates, change ages)."""

    def __init__(self, monitor: "HealthMonitor", now: float) -> None:
        self._m = monitor
        self.now = now

    def counter(self, name: str) -> Optional[float]:
        c = self._m.registry.counters.get(name)
        return float(c.value) if c is not None else None

    def gauge(self, name: str) -> Optional[float]:
        g = self._m.registry.gauges.get(name)
        if g is not None or name in self._m.registry.gauge_fns:
            return _lookup_value(self._m.registry, name)
        return None

    def gauges_prefixed(self, prefix: str) -> Dict[str, float]:
        """{suffix: value} for every plain gauge under ``prefix``."""
        return {
            n[len(prefix):]: float(g.value)
            for n, g in self._m.registry.gauges.items()
            if n.startswith(prefix)
        }

    def rate(self, name: str, window_s: float) -> Optional[float]:
        """Per-second net change of a sampled series over ``window_s``.
        None until the history actually SPANS the window: a rate
        computed over a shorter early span would over-weight one bursty
        tick (e.g. boot-time reconnect retransmissions) against a
        threshold tuned for the full window — rules stay silent for the
        first ``window_s`` after monitor start instead of false-firing.
        """
        hist = self._m._history.get(name)
        if not hist or len(hist) < 2:
            return None
        newest_t, newest_v = hist[-1]
        for t, v in reversed(hist):
            if newest_t - t >= window_s:
                return (newest_v - v) / (newest_t - t)
        return None

    def rates_prefixed(
        self, prefix: str, window_s: float
    ) -> Dict[str, float]:
        out = {}
        for name in self._m._history:
            if name.startswith(prefix):
                r = self.rate(name, window_s)
                if r is not None:
                    out[name[len(prefix):]] = r
        return out

    def last_change_age(self, name: str) -> Optional[float]:
        """Seconds since the sampled series last changed value (first
        sample counts as a change, so the age is bounded by monitor
        uptime)."""
        rec = self._m._last_change.get(name)
        if rec is None:
            return None
        return self.now - rec[1]


def default_rules(env: Optional[Mapping[str, str]] = None) -> List[HealthRule]:
    """The built-in rule set; every threshold has a NARWHAL_HEALTH_* env
    override (documented in README 'Observability')."""
    def f(key: str, default: float) -> float:
        # The registry (utils/env.py) declares the same default; passing
        # it here too keeps each threshold readable next to its rule.
        return float(env_float(key, default, env=env))

    lag_max = f("NARWHAL_HEALTH_MAX_COMMIT_LAG", 20)
    stall_s = f("NARWHAL_HEALTH_COMMIT_STALL_S", 10)
    ack_floor = f("NARWHAL_HEALTH_PENDING_ACK_FLOOR", 512)
    ack_window = f("NARWHAL_HEALTH_PENDING_ACK_WINDOW_S", 5)
    retrans_max = f("NARWHAL_HEALTH_PEER_RETRANS_RATE", 10)
    retrans_window = f("NARWHAL_HEALTH_PEER_RETRANS_WINDOW_S", 5)
    peer_failures = f("NARWHAL_HEALTH_PEER_FAILURES", 3)
    quorum_wedge_s = f("NARWHAL_HEALTH_QUORUM_WEDGE_S", 10)
    vote_window = f("NARWHAL_HEALTH_VOTE_SILENCE_WINDOW_S", 8)
    vote_min_rounds = f("NARWHAL_HEALTH_VOTE_SILENCE_MIN_ROUNDS", 3)
    # 6/s, not the original 2/s: a node catching up after a healed
    # partition replays its backlog at a measured 2.4-2.9 stale
    # messages/s (the wan_partition_heal scenario's healed node FIRED
    # transiently at the old default — ROADMAP item 4's named
    # follow-up), while the replay-flood attack this rule exists for
    # measures an order of magnitude higher (byz_replay_stale re-sends
    # at 10/s per peer).  6/s sits ~2x above the heal burst and still
    # comfortably under the attack floor.
    stale_rate_max = f("NARWHAL_HEALTH_STALE_RATE", 6)
    stale_window = f("NARWHAL_HEALTH_STALE_WINDOW_S", 5)
    # Worker plane: how long a requested-but-unserved batch may age
    # before it reads as withholding.  The default sits above the stock
    # sync_retry_delay (5 s) so an ordinary first-retry window stays
    # silent; withholding scenarios lower it alongside a raised retry
    # delay to make the starvation unambiguous.
    sync_age_max = f("NARWHAL_HEALTH_SYNC_AGE_S", 8)
    # Backpressure plane (InstrumentedQueue channels).  A channel reads
    # as saturated when its live depth crosses RATIO of capacity; the
    # MIN_CAP floor excludes channels that run full BY DESIGN — the
    # worker's QUORUM_WINDOW admission queue (depth 8) and the sim's
    # depth-1 race-forcing channels use fullness as their backpressure
    # MECHANISM, so fullness there is operation, not anomaly.
    queue_sat_ratio = f("NARWHAL_HEALTH_QUEUE_SAT_RATIO", 0.9)
    queue_sat_min_cap = f("NARWHAL_HEALTH_QUEUE_SAT_MIN_CAP", 16)
    queue_sat_intervals = f("NARWHAL_HEALTH_QUEUE_SAT_INTERVALS", 3)
    ingress_drop_rate = f("NARWHAL_HEALTH_INGRESS_DROP_RATE", 1.0)
    ingress_drop_window = f("NARWHAL_HEALTH_INGRESS_DROP_WINDOW_S", 5)

    def commit_lag(ctx: HealthContext) -> Dict[str, dict]:
        v = ctx.gauge("consensus.commit_lag_rounds")
        if v is not None and v > lag_max:
            return {"": {"commit_lag_rounds": v, "threshold": lag_max}}
        return {}

    def commit_stall(ctx: HealthContext) -> Dict[str, dict]:
        # Guarded on round > 2: a freshly booted or idle committee has
        # legitimately committed nothing yet; once the DAG is past its
        # first leader round, zero commit progress means a wedge.
        rnd = ctx.gauge("primary.round")
        if rnd is None or rnd <= 2:
            return {}
        age = ctx.last_change_age("consensus.committed_certificates")
        if age is not None and age > stall_s:
            return {
                "": {
                    "seconds_without_commit": round(age, 1),
                    "threshold": stall_s,
                    "round": rnd,
                }
            }
        return {}

    def pending_acks(ctx: HealthContext) -> Dict[str, dict]:
        v = ctx.gauge("net.reliable.pending_acks")
        if v is None or v < ack_floor:
            return {}
        growth = ctx.rate("net.reliable.pending_acks", ack_window)
        if growth is not None and growth > 0:
            return {
                "": {
                    "pending_acks": v,
                    "floor": ack_floor,
                    "growth_per_s": round(growth, 2),
                }
            }
        return {}

    def peer_retransmissions(ctx: HealthContext) -> Dict[str, dict]:
        out = {}
        for peer, rate in ctx.rates_prefixed(
            "net.reliable.peer.retransmissions.", retrans_window
        ).items():
            if rate > retrans_max:
                out[peer] = {
                    "retransmissions_per_s": round(rate, 2),
                    "threshold": retrans_max,
                }
        return out

    def quorum_wedge(ctx: HealthContext) -> Dict[str, dict]:
        # A worker's QuorumWaiter stuck mid-batch (e.g. at 2f stake with
        # the last ACK never arriving) previously showed only indirectly
        # via pending-ACK growth; the wait-age gauge names the wedge
        # directly, with the acked stake vs threshold in the detail.
        age = ctx.gauge("worker.quorum_wait_age_seconds")
        if age is None or age <= quorum_wedge_s:
            return {}
        detail = {
            "seconds_waiting": round(age, 1),
            "threshold": quorum_wedge_s,
        }
        stake = ctx.gauge("worker.quorum_acked_stake")
        need = ctx.gauge("worker.quorum_threshold")
        if stake is not None:
            detail["acked_stake"] = stake
        if need is not None:
            detail["quorum_threshold"] = need
        return {"": detail}

    # -- Byzantine-fault detections (fault-injection suite, ISSUE 6).
    # The first two latch: they read monotone counters of events that a
    # healthy committee NEVER produces, so once proven the anomaly stays
    # raised (there is no "un-equivocating").

    def equivocation(ctx: HealthContext) -> Dict[str, dict]:
        v = ctx.counter("primary.equivocations_detected")
        if v:
            return {"": {"equivocations_detected": v}}
        return {}

    def invalid_signature(ctx: HealthContext) -> Dict[str, dict]:
        v = ctx.counter("primary.invalid_signatures")
        if v:
            return {"": {"invalid_signatures": v}}
        return {}

    def peer_vote_silence(ctx: HealthContext) -> Dict[str, dict]:
        # A peer that votes for NONE of our headers while the DAG keeps
        # advancing is withholding (or wedged) — either way a named
        # anomaly.  Gated on real round progress over the window so an
        # idle or booting committee stays silent.
        rnd_rate = ctx.rate("primary.round", vote_window)
        if rnd_rate is None or rnd_rate * vote_window < vote_min_rounds:
            return {}
        out = {}
        for peer, rate in ctx.rates_prefixed(
            "primary.peer_votes.", vote_window
        ).items():
            if rate <= 0:
                out[peer] = {
                    "rounds_advanced": round(rnd_rate * vote_window, 1),
                    "window_s": vote_window,
                }
        return out

    def stale_replay(ctx: HealthContext) -> Dict[str, dict]:
        # Past-GC-horizon messages trickling in is normal for a lagging
        # peer; a sustained RATE of them is a replay flood.
        rate = ctx.rate("primary.stale_messages", stale_window)
        if rate is not None and rate > stale_rate_max:
            return {
                "": {
                    "stale_per_s": round(rate, 2),
                    "threshold": stale_rate_max,
                }
            }
        return {}

    # -- worker-plane availability detections (fault suite, ISSUE 8).
    # The first reads the synchronizer's oldest-unserved age (a live
    # gauge: it clears when the batch finally lands); the other two latch
    # on monotone counters of events an honest committee never produces,
    # like the equivocation/invalid_signature pair.

    def batch_withholding(ctx: HealthContext) -> Dict[str, dict]:
        # A certificate is a proof of batch availability — a requested
        # digest that stays unserved past the threshold means some quorum
        # ACKer is not serving the bytes it vouched for (or the fetch
        # plane is wedged); either way the availability claim is being
        # violated live.
        age = ctx.gauge("worker.unserved_sync_age_seconds")
        if age is not None and age > sync_age_max:
            return {
                "": {
                    "unserved_sync_age_s": round(age, 1),
                    "threshold": sync_age_max,
                }
            }
        return {}

    def helper_abuse(ctx: HealthContext) -> Dict[str, dict]:
        # Over-limit BatchRequests: the honest requesting side chunks
        # under the Helper cap, so any truncation is a peer exploiting
        # the request→reply amplification (sync_flood).
        v = ctx.counter("worker.helper_rejected_requests")
        if v:
            return {"": {"rejected_requests": v}}
        return {}

    def garbage_batches(ctx: HealthContext) -> Dict[str, dict]:
        # Oversized batch frames rejected by the size gate: an honest
        # worker's seals are bounded by batch_size, so these bytes are
        # junk someone is trying to make us hash and persist.
        v = ctx.counter("worker.garbage_batches")
        if v:
            return {"": {"garbage_batches": v}}
        return {}

    def peer_unreachable(ctx: HealthContext) -> Dict[str, dict]:
        out = {}
        for peer, v in ctx.gauges_prefixed(
            "net.reliable.peer.consecutive_failures."
        ).items():
            if v >= peer_failures:
                out[peer] = {
                    "consecutive_failures": v,
                    "threshold": peer_failures,
                }
        return out

    def queue_saturated(ctx: HealthContext) -> Dict[str, dict]:
        # One subject per channel, so a firing names the saturating
        # channel directly — the health-side mirror of the knee matrix's
        # first_saturating attribution.  Depth and capacity are the
        # plain gauges InstrumentedQueue maintains on every put/get.
        out = {}
        prefixed = ctx.gauges_prefixed("queue.")
        for name, depth in prefixed.items():
            if not name.endswith(".depth"):
                continue
            channel = name[: -len(".depth")]
            cap = prefixed.get(channel + ".capacity")
            if not cap or cap < queue_sat_min_cap:
                continue
            if depth >= queue_sat_ratio * cap:
                detail = {
                    "depth": depth,
                    "capacity": cap,
                    "fill_ratio": round(depth / cap, 3),
                    "threshold_ratio": queue_sat_ratio,
                }
                hw = prefixed.get(channel + ".high_water")
                if hw is not None:
                    detail["high_water"] = hw
                out[channel] = detail
        return out

    def ingress_drops(ctx: HealthContext) -> Dict[str, dict]:
        # Client-ingress overflow RATE, not the monotone total: a brief
        # burst parked by the BatchMaker's pause/drain cycle is normal
        # operation; a sustained overflow rate means offered load is
        # past the admission plane's capacity.
        rate = ctx.rate("worker.ingress_overflow", ingress_drop_window)
        if rate is not None and rate > ingress_drop_rate:
            return {
                "": {
                    "overflows_per_s": round(rate, 2),
                    "threshold": ingress_drop_rate,
                    "window_s": ingress_drop_window,
                }
            }
        return {}

    return [
        HealthRule("commit_lag", commit_lag, for_intervals=2),
        HealthRule(
            "commit_stall",
            commit_stall,
            series=("consensus.committed_certificates",),
        ),
        HealthRule(
            "pending_ack_growth",
            pending_acks,
            for_intervals=2,
            series=("net.reliable.pending_acks",),
        ),
        HealthRule(
            "peer_retransmission_spike",
            peer_retransmissions,
            for_intervals=2,
            series=("net.reliable.peer.retransmissions.*",),
        ),
        # for_intervals=1: a dead peer must be named within ONE
        # evaluation interval of the failure gauge crossing the
        # threshold (the failover tier-1 test pins this down).
        HealthRule("peer_unreachable", peer_unreachable, for_intervals=1),
        # for_intervals=2: the wait-age gauge is itself a duration (the
        # threshold debounces), but one extra interval rides out a
        # callback-gauge sample racing the waiter's release.
        HealthRule("quorum_wedge", quorum_wedge, for_intervals=2),
        # for_intervals=1: an equivocation/rogue signature is PROVEN by a
        # single event (we hold the signed statements) — no debounce.
        HealthRule("equivocation", equivocation),
        HealthRule("invalid_signature", invalid_signature),
        HealthRule(
            "peer_vote_silence",
            peer_vote_silence,
            for_intervals=2,
            series=("primary.round", "primary.peer_votes.*"),
        ),
        HealthRule(
            "stale_replay",
            stale_replay,
            for_intervals=2,
            series=("primary.stale_messages",),
        ),
        # for_intervals=2: the age gauge is a duration (the threshold
        # debounces) but one extra interval rides out a sample racing the
        # arrival-waiter's release, like quorum_wedge.
        HealthRule("batch_withholding", batch_withholding, for_intervals=2),
        # Latching, like equivocation: a single over-limit request or
        # oversized batch frame is already proof of hostile traffic.
        HealthRule("helper_abuse", helper_abuse),
        HealthRule("garbage_batches", garbage_batches),
        # Hysteresis (default 3 intervals): a channel legitimately
        # brushes its capacity during a burst-drain cycle; only a queue
        # that STAYS at the ceiling across evaluations is saturated.
        HealthRule(
            "queue_saturated",
            queue_saturated,
            for_intervals=max(1, int(queue_sat_intervals)),
        ),
        HealthRule(
            "ingress_drops",
            ingress_drops,
            for_intervals=2,
            series=("worker.ingress_overflow",),
        ),
    ]


class HealthMonitor:
    """Evaluates a rule set over the registry on a timer.

    Each evaluation samples the watched series (for rates and change
    ages), runs every rule, applies hysteresis per (rule, subject), and
    on FIRING/cleared transitions emits one structured anomaly event —
    a WARNING/INFO log line prefixed ``HEALTH`` plus an entry in the
    bounded ``events`` ring.  ``health_snapshot()`` is what lands in the
    registry snapshot's ``health`` section and behind ``/healthz``:

        {"status": "ok"|"failing", "evaluations": N, "interval_s": s,
         "firing": [{"rule", "subject", "since", "detail"}, …],
         "events": [last 64 transitions]}

    Not spawned by default: node/main.py attaches one per process
    (``registry().health = monitor``) unless NARWHAL_HEALTH=0.
    """

    HISTORY_CAP = 128  # samples kept per watched series

    def __init__(
        self,
        reg: Registry,
        rules: Optional[List[HealthRule]] = None,
        interval_s: Optional[float] = None,
    ) -> None:
        self.registry = reg
        self.rules = default_rules() if rules is None else rules
        self.interval_s = (
            env_float("NARWHAL_HEALTH_INTERVAL")
            if interval_s is None
            else interval_s
        )
        self.evaluations = 0
        self._was_ok = True
        self.events: Deque[dict] = collections.deque(maxlen=64)
        # (rule, subject) -> {breaches, oks, firing, since, detail}
        self._state: Dict[Tuple[str, str], dict] = {}
        self._history: Dict[str, Deque[Tuple[float, float]]] = {}
        self._last_change: Dict[str, Tuple[float, float]] = {}  # (value, t)
        self._watch_names: List[str] = []
        self._watch_prefixes: List[str] = []
        for rule in self.rules:
            for s in rule.series:
                if s.endswith(".*"):
                    self._watch_prefixes.append(s[:-1])  # keep the dot
                else:
                    self._watch_names.append(s)

    # -- sampling -------------------------------------------------------------

    def _watched_values(self) -> Dict[str, float]:
        reg = self.registry
        out: Dict[str, float] = {}
        for name in self._watch_names:
            v = _lookup_value(reg, name)
            if v is not None:
                out[name] = v
        for prefix in self._watch_prefixes:
            for pool in (reg.counters, reg.gauges):
                for name, inst in pool.items():
                    if name.startswith(prefix):
                        out[name] = float(inst.value)
        return out

    def _sample(self, now: float) -> None:
        for name, v in self._watched_values().items():
            hist = self._history.get(name)
            if hist is None:
                hist = self._history[name] = collections.deque(
                    maxlen=self.HISTORY_CAP
                )
            hist.append((now, v))
            last = self._last_change.get(name)
            if last is None or last[0] != v:
                self._last_change[name] = (v, now)

    # -- evaluation -----------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """One evaluation pass; returns the currently-firing anomalies.
        ``now`` is injectable so tests drive rate windows and stall ages
        deterministically."""
        now = time.time() if now is None else now
        self._sample(now)
        ctx = HealthContext(self, now)
        for rule in self.rules:
            try:
                breaches = rule.check(ctx)
            except Exception:
                # A rule crashing on a half-torn-down registry must not
                # kill the monitor loop.
                log.exception("health rule %s failed to evaluate", rule.name)
                continue
            subjects = set(breaches)
            subjects.update(
                s for (r, s) in self._state if r == rule.name
            )
            for subject in subjects:
                key = (rule.name, subject)
                st = self._state.get(key)
                if st is None:
                    st = self._state[key] = {
                        "breaches": 0,
                        "oks": 0,
                        "firing": False,
                        "since": None,
                        "detail": {},
                    }
                if subject in breaches:
                    st["breaches"] += 1
                    st["oks"] = 0
                    st["detail"] = breaches[subject]
                    if (
                        not st["firing"]
                        and st["breaches"] >= rule.for_intervals
                    ):
                        st["firing"] = True
                        st["since"] = now
                        self._transition("FIRING", rule.name, subject, st, now)
                else:
                    st["oks"] += 1
                    st["breaches"] = 0
                    if st["firing"] and st["oks"] >= rule.clear_intervals:
                        st["firing"] = False
                        self._transition(
                            "cleared", rule.name, subject, st, now
                        )
                        st["since"] = None
                    if not st["firing"] and st["oks"] >= rule.clear_intervals:
                        # Fully quiet subject: drop it so per-peer state
                        # stays bounded over churn.
                        self._state.pop(key, None)
        self.evaluations += 1
        # The /healthz ok→failing edge IS the 503 transition: the moment
        # the flight ring is most valuable (the events leading up to the
        # first firing rule), so it dumps right here — before anything
        # else can crash, restart, or truncate the node.
        now_ok = self.ok()
        if self._was_ok and not now_ok:
            self.registry.flight.dump("healthz-503")
        self._was_ok = now_ok
        return self.firing()

    def _transition(
        self, kind: str, rule: str, subject: str, st: dict, now: float
    ) -> None:
        # `now` is the evaluation clock (injectable in tests), so event
        # timestamps join against the firing entries' `since` values.
        event = {
            "event": kind,
            "rule": rule,
            "subject": subject,
            "t": round(now, 3),
            "detail": dict(st["detail"]),
        }
        self.events.append(event)
        # Health transitions are flight-ring landmarks: the recorder's
        # tick deltas around a FIRING edge are the post-mortem.
        self.registry.flight.record(
            "health", event=kind, rule=rule, subject=subject,
            detail=dict(st["detail"]),
        )
        msg = "HEALTH anomaly %s rule=%s%s detail=%s"
        sub = f" subject={subject}" if subject else ""
        if kind == "FIRING":
            log.warning(msg, kind, rule, sub, json.dumps(st["detail"]))
        else:
            log.info(msg, kind, rule, sub, json.dumps(st["detail"]))

    # -- export ---------------------------------------------------------------

    def firing(self) -> List[dict]:
        return [
            {
                "rule": rule,
                "subject": subject,
                "since": st["since"],
                "detail": dict(st["detail"]),
            }
            for (rule, subject), st in sorted(self._state.items())
            if st["firing"]
        ]

    def ok(self) -> bool:
        return not any(st["firing"] for st in self._state.values())

    def health_snapshot(self) -> dict:
        firing = self.firing()
        return {
            "status": "ok" if not firing else "failing",
            "evaluations": self.evaluations,
            "interval_s": self.interval_s,
            "firing": firing,
            "events": list(self.events),
        }

    async def run(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            self.evaluate()


# -- the per-process default registry ----------------------------------------

def _enabled_from_env() -> bool:
    return env_flag("NARWHAL_METRICS")


_REGISTRY = Registry(
    enabled=_enabled_from_env(),
    trace_cap=env_int("NARWHAL_TRACE_CAP"),
)


def registry() -> Registry:
    return _REGISTRY


def counter(name: str) -> Counter:
    return _REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return _REGISTRY.gauge(name)


def histogram(name: str, buckets: Sequence[float] = LATENCY_BUCKETS) -> Histogram:
    return _REGISTRY.histogram(name, buckets)


def gauge_fn(name: str, fn: Callable[[], float]) -> None:
    _REGISTRY.gauge_fn(name, fn)


def detail_fn(name: str, fn: Callable[[], object]) -> None:
    _REGISTRY.detail_fn(name, fn)


def trace() -> TraceTable:
    return _REGISTRY.trace  # type: ignore[return-value]


def round_trace() -> TraceTable:
    return _REGISTRY.round_trace  # type: ignore[return-value]


def wire() -> WireLedger:
    return _REGISTRY.wire


def wire_account(
    direction: str,
    msg_type: str,
    peer: str,
    nbytes: int,
    retransmit: bool = False,
    raw_nbytes: Optional[int] = None,
) -> None:
    """Module-level convenience for the network layer (one call per
    frame; no-op when the registry is stubbed).  ``raw_nbytes`` is the
    frame's pre-compression size when wire v2 compressed it (defaults
    to ``nbytes``)."""
    _REGISTRY.wire.account(
        direction, msg_type, peer, nbytes, retransmit, raw_nbytes
    )


def flight() -> FlightRecorder:
    return _REGISTRY.flight


def flight_event(kind: str, **fields) -> None:
    """Module-level convenience for the instrumented layers (one ring
    append; no-op when the registry is stubbed)."""
    _REGISTRY.flight.record(kind, **fields)


# -- instrumented channels ----------------------------------------------------

class InstrumentedQueue(asyncio.Queue):
    """Drop-in ``asyncio.Queue`` emitting per-channel backpressure series.

    Every inter-task channel in the node is constructed through this
    class with a stable ``channel`` name, so a saturation knee reads as
    a NAMED filling queue instead of an anonymous latency cliff.  All
    series live under ``queue.<channel>.``:

        depth       gauge     live qsize, written on every put/get (a
                              plain gauge, not a callback, so the health
                              monitor's plain-gauge scan and the scraped
                              sample timeline both see it)
        capacity    gauge     maxsize (0 = unbounded), set once
        high_water  gauge     maximum depth ever observed
        enqueued    counter   items accepted
        dequeued    counter   items removed
        full        counter   ``asyncio.QueueFull`` raised from
                              ``put_nowait`` (the drop/park signal — the
                              caller decides which; BatchMaker parks)
        put_wait_seconds   histogram  time a blocking ``put()`` spent
                                      suspended on a full queue (only
                                      blocked puts are observed, so the
                                      count is "puts that waited")
        residence_seconds  histogram  enqueue→dequeue age per item

    Cost: the enabled arm pays two counter increments, two gauge writes
    and one timestamp-deque append/popleft per item — ``time.monotonic``
    is called once on each side.  With ``NARWHAL_METRICS=0`` the
    constructor registers nothing and every override reduces to one
    attribute test before delegating, so the queue behaves like a plain
    ``asyncio.Queue`` (the measured A/B arm; artifact
    ``artifacts/queue_overhead_r21.json``).

    Interception points are asyncio.Queue's internal ``_put``/``_get``
    hooks: both the awaiting and the ``*_nowait`` paths funnel through
    them, so accounting cannot miss an item or double-count one.
    """

    def __init__(self, maxsize: int = 0, *, channel: str) -> None:
        self.channel = channel
        reg = _REGISTRY
        self._instrumented = reg.enabled
        if self._instrumented:
            self._m_depth = reg.gauge(f"queue.{channel}.depth")
            self._m_capacity = reg.gauge(f"queue.{channel}.capacity")
            self._m_high = reg.gauge(f"queue.{channel}.high_water")
            self._m_enqueued = reg.counter(f"queue.{channel}.enqueued")
            self._m_dequeued = reg.counter(f"queue.{channel}.dequeued")
            self._m_full = reg.counter(f"queue.{channel}.full")
            self._m_put_wait = reg.histogram(
                f"queue.{channel}.put_wait_seconds"
            )
            self._m_residence = reg.histogram(
                f"queue.{channel}.residence_seconds"
            )
            self._m_capacity.set(float(maxsize))
            # Enqueue timestamps in FIFO order.  asyncio.Queue IS FIFO,
            # so popleft pairs each dequeue with its enqueue exactly.
            self._enq_ts: Deque[float] = collections.deque()
        super().__init__(maxsize)

    def _put(self, item) -> None:
        super()._put(item)
        if self._instrumented:
            self._m_enqueued.inc()
            self._enq_ts.append(time.monotonic())
            depth = self.qsize()
            self._m_depth.set(float(depth))
            if depth > self._m_high.value:
                self._m_high.set(float(depth))

    def _get(self):
        item = super()._get()
        if self._instrumented:
            self._m_dequeued.inc()
            self._m_depth.set(float(self.qsize()))
            if self._enq_ts:
                self._m_residence.observe(
                    time.monotonic() - self._enq_ts.popleft()
                )
        return item

    async def put(self, item) -> None:
        if not self._instrumented or not self.full():
            # Fast path: one branch over a plain Queue — no clock call.
            await super().put(item)
            return
        start = time.monotonic()
        await super().put(item)
        self._m_put_wait.observe(time.monotonic() - start)

    def put_nowait(self, item) -> None:
        try:
            super().put_nowait(item)
        except asyncio.QueueFull:
            if self._instrumented:
                self._m_full.inc()
            raise


# -- snapshot writer ----------------------------------------------------------

class SnapshotWriter:
    """Periodically rewrite ``path`` with the registry snapshot.

    Atomic rewrite (write temp + ``os.replace``) so a reader — the bench
    harness polling mid-run, or an operator's ``watch cat`` — never sees a
    torn JSON document.  No fsync: the snapshot is an observability
    artifact, not durable state (unlike the consensus checkpoint, losing
    one interval to power loss costs nothing).  A final snapshot is
    flushed on cancellation so teardown captures the complete run.

    Cost control on the committee's shared core: counters/gauges/
    histograms are a few kB and serialize in <1 ms every interval, but the
    stage-trace table reaches hundreds of kB on a bench run (~12-22 ms of
    json.dumps per rewrite on a slow core — measured to dent committee
    TPS by ~10% at 1 Hz across 8 processes).  The trace is therefore
    included only every ``trace_every``-th rewrite (staleness bounded at
    ``trace_every × interval_s`` for a SIGKILLed node) and in the final
    cancellation flush, which is what the bench cross-validation reads.
    """

    def __init__(
        self,
        reg: Registry,
        path: str,
        interval_s: float = 1.0,
        trace_every: int = 10,
    ) -> None:
        self.registry = reg
        self.path = path
        self.interval_s = interval_s
        self.trace_every = max(1, trace_every)
        self._ticks = 0
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def write_once(self, include_trace: bool = True) -> None:
        # Serialize to one string first: json.dump streams thousands of
        # tiny f.write chunks (measured ~2× the dumps+single-write cost
        # with a loaded trace table).
        body = json.dumps(self.registry.snapshot(include_trace))
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            f.write(body)
        os.replace(tmp, self.path)

    async def run(self) -> None:
        try:
            while True:
                await asyncio.sleep(self.interval_s)
                self._ticks += 1
                try:
                    self.write_once(
                        include_trace=(self._ticks % self.trace_every == 0)
                    )
                except OSError:
                    # A transient write failure (ENOSPC clearing, tmp-dir
                    # hiccup) must not kill the loop for the rest of the
                    # run — the next interval retries.
                    log.exception(
                        "periodic metrics snapshot to %s failed", self.path
                    )
        finally:
            # Teardown flush: the harness reads post-mortem totals and the
            # full stage trace from this final write (cross-validation
            # needs the whole run, not the last whole interval).
            try:
                self.write_once(include_trace=True)
            except OSError:
                log.exception("final metrics snapshot to %s failed", self.path)


# -- Prometheus-text HTTP endpoint --------------------------------------------

class MetricsServer:
    """Minimal HTTP server: ``GET /metrics`` → Prometheus text,
    ``GET /metrics.json`` → the JSON snapshot (``?trace=0`` omits the
    heavyweight stage-trace table — what the bench scraper polls at
    1 Hz), ``GET /healthz`` → 200/503 + the attached HealthMonitor's
    JSON (503 iff any rule is firing; 200 with ``status: unmonitored``
    when no monitor is attached), ``GET /debug/flight`` → the flight
    recorder's live event ring (what the node was doing in its last
    seconds — pulled by the bench scraper at quiesce).  Anything else
    is 404.

    Hand-rolled over ``asyncio.start_server`` — the container bakes no
    http framework, and a scrape endpoint needs exactly one request per
    connection (Connection: close).

    Binds localhost by default: the endpoint is unauthenticated (and the
    snapshot's detail section names peer addresses), so it follows the
    same convention as every other listener here — NARWHAL_BIND_ANY=1
    widens it to 0.0.0.0 for scrapers on other hosts (receiver.py)."""

    def __init__(self, reg: Registry) -> None:
        self.registry = reg
        self._server: Optional[asyncio.AbstractServer] = None

    @classmethod
    async def spawn(
        cls, reg: Registry, port: int, host: Optional[str] = None
    ) -> "MetricsServer":
        if host is None:
            host = (
                "0.0.0.0"
                if env_flag("NARWHAL_BIND_ANY")
                else "127.0.0.1"
            )
        self = cls(reg)
        self._server = await asyncio.start_server(self._handle, host, port)
        log.info("Metrics endpoint listening on %s:%d", host, self.port)
        return self

    @property
    def port(self) -> int:
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            request = await asyncio.wait_for(reader.readline(), timeout=5.0)
            parts = request.decode("latin-1", "replace").split()
            target = parts[1] if len(parts) >= 2 else ""
            # Drain the header block (ignored) so the client sees a clean
            # close instead of a reset.  Bounded: a client streaming
            # endless garbage lines must not pin this handler forever.
            for _ in range(100):
                line = await asyncio.wait_for(reader.readline(), timeout=5.0)
                if line in (b"\r\n", b"\n", b""):
                    break
            else:
                return  # header flood; drop the connection
            path, _, query = target.partition("?")
            params = dict(
                kv.split("=", 1) for kv in query.split("&") if "=" in kv
            )
            if path == "/metrics":
                body = self.registry.render_prometheus().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
                status = "200 OK"
            elif path == "/metrics.json":
                body = json.dumps(
                    self.registry.snapshot(
                        include_trace=params.get("trace") != "0"
                    )
                ).encode()
                ctype = "application/json"
                status = "200 OK"
            elif path == "/debug/flight":
                # The flight ring, live: what the node was doing in its
                # last seconds, pullable without waiting for a dump
                # trigger (the scraper reads this at quiesce).
                body = json.dumps(
                    {
                        "ts": time.time(),
                        "pid": os.getpid(),
                        **self.registry.flight.snapshot(),
                    }
                ).encode()
                ctype = "application/json"
                status = "200 OK"
            elif path == "/healthz":
                monitor = self.registry.health
                if monitor is None:
                    payload: dict = {"status": "unmonitored", "firing": []}
                    status = "200 OK"
                else:
                    payload = monitor.health_snapshot()
                    status = (
                        "200 OK"
                        if payload["status"] == "ok"
                        else "503 Service Unavailable"
                    )
                body = json.dumps(payload).encode()
                ctype = "application/json"
            else:
                body = b"not found\n"
                ctype = "text/plain"
                status = "404 Not Found"
            writer.write(
                (
                    f"HTTP/1.1 {status}\r\n"
                    f"Content-Type: {ctype}\r\n"
                    f"Content-Length: {len(body)}\r\n"
                    "Connection: close\r\n\r\n"
                ).encode()
                + body
            )
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionError, OSError, ValueError):
            # ValueError: readline() on an over-long request/header line
            # (stream limit overrun) — scraping garbage must not leave an
            # unhandled-task ERROR in a benchmarked node's log.
            pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                pass

    async def shutdown(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
