"""ctypes bindings for the native data plane (native/dataplane.c), with a
pure-Python fallback so the protocol stack still runs where no C toolchain
exists.

The native library owns every per-transaction step of the worker hot path
(reference worker/src/batch_maker.rs:71-156): splitting the length-prefixed
tx stream, accumulating the batch body in wire encoding, sample-id scan, and
sealing the WorkerMessage::Batch.  Python code observes batches, never
transactions.

``ensure_built()`` compiles the library on first use (one ``make`` in
native/); the build is cached by mtime.
"""

from __future__ import annotations

import ctypes
import logging
import os
import struct
import subprocess
import threading
from typing import List, Optional, Tuple

log = logging.getLogger("narwhal.native")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libnarwhal_dp.so")
_SRC_PATH = os.path.join(_NATIVE_DIR, "dataplane.c")

_lib = None
_lib_lock = threading.Lock()
_build_attempted = False


def ensure_built() -> bool:
    """Build the native library if missing/stale. Returns availability.
    A stale library is never used: if the rebuild fails, we fall back to
    the Python twin rather than dlopen an ABI that may no longer match
    the ctypes signatures."""
    global _build_attempted
    if not os.path.exists(_SRC_PATH):
        return False

    def fresh() -> bool:
        return (
            os.path.exists(_LIB_PATH)
            and os.path.getmtime(_LIB_PATH) >= os.path.getmtime(_SRC_PATH)
        )

    if fresh():
        return True
    if _build_attempted:
        return False
    _build_attempted = True
    try:
        # Cross-process lock: the bench harness spawns many nodes at once
        # and they must not run `make` over the same output concurrently
        # (the Makefile also builds via tmp + atomic rename).
        import fcntl

        with open(os.path.join(_NATIVE_DIR, ".build.lock"), "w") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            if not fresh():
                subprocess.run(
                    ["make", "-s"], cwd=_NATIVE_DIR, check=True,
                    capture_output=True, timeout=120,
                )
        return fresh()
    except (subprocess.SubprocessError, OSError) as e:
        log.warning("native data plane build failed, using Python fallback: %s", e)
        return False


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not ensure_built():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            # Present but unloadable (wrong arch, truncated, ABI drift): the
            # Python twin keeps the stack running, as documented.
            log.warning("cannot load %s (%s); using Python fallback",
                        _LIB_PATH, e)
            return None
        lib.dp_batcher_new.restype = ctypes.c_void_p
        lib.dp_batcher_new.argtypes = [ctypes.c_uint32]
        lib.dp_batcher_free.argtypes = [ctypes.c_void_p]
        lib.dp_batcher_tx_bytes.restype = ctypes.c_uint32
        lib.dp_batcher_tx_bytes.argtypes = [ctypes.c_void_p]
        lib.dp_batcher_tx_count.restype = ctypes.c_uint32
        lib.dp_batcher_tx_count.argtypes = [ctypes.c_void_p]
        lib.dp_batcher_ready.restype = ctypes.c_int
        lib.dp_batcher_ready.argtypes = [ctypes.c_void_p]
        lib.dp_batcher_sealed_size.restype = ctypes.c_uint32
        lib.dp_batcher_sealed_size.argtypes = [ctypes.c_void_p]
        lib.dp_batcher_seal.restype = ctypes.c_int64
        lib.dp_batcher_seal.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.dp_validate_batch.restype = ctypes.c_int64
        lib.dp_validate_batch.argtypes = [ctypes.c_char_p, ctypes.c_uint32]
        lib.dp_framer_new.restype = ctypes.c_void_p
        lib.dp_framer_new.argtypes = []
        lib.dp_framer_free.argtypes = [ctypes.c_void_p]
        lib.dp_framer_feed.restype = ctypes.c_int
        lib.dp_framer_feed.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
        ]
        _lib = lib
        return _lib


class SealedBatch:
    """One sealed WorkerMessage::Batch plus its benchmark metadata."""

    __slots__ = ("message", "tx_count", "tx_bytes", "samples")

    def __init__(self, message: bytes, tx_count: int, tx_bytes: int,
                 samples: List[int]) -> None:
        self.message = message
        self.tx_count = tx_count
        self.tx_bytes = tx_bytes
        self.samples = samples


class _NativeBatcher:
    def __init__(self, lib, batch_size: int) -> None:
        self._lib = lib
        self._ptr = lib.dp_batcher_new(batch_size)
        if not self._ptr:
            raise MemoryError("dp_batcher_new failed")

    def __del__(self):
        if getattr(self, "_ptr", None):
            self._lib.dp_batcher_free(self._ptr)
            self._ptr = None

    @property
    def tx_bytes(self) -> int:
        return self._lib.dp_batcher_tx_bytes(self._ptr)

    @property
    def tx_count(self) -> int:
        return self._lib.dp_batcher_tx_count(self._ptr)

    def ready(self) -> bool:
        return bool(self._lib.dp_batcher_ready(self._ptr))

    def seal(self) -> Optional[SealedBatch]:
        lib = self._lib
        cap = lib.dp_batcher_sealed_size(self._ptr)
        n_tx = lib.dp_batcher_tx_count(self._ptr)
        out = ctypes.create_string_buffer(max(cap, 16))
        samples = (ctypes.c_uint64 * max(n_tx, 1))()
        n_samples = ctypes.c_uint32()
        n_txs = ctypes.c_uint32()
        tx_bytes = ctypes.c_uint32()
        n = lib.dp_batcher_seal(
            self._ptr, out, cap, samples, n_tx,
            ctypes.byref(n_samples), ctypes.byref(n_txs),
            ctypes.byref(tx_bytes),
        )
        if n == 0:
            return None
        if n < 0:
            raise RuntimeError("dp_batcher_seal: capacity error")
        return SealedBatch(
            out.raw[: int(n)],
            int(n_txs.value),
            int(tx_bytes.value),
            list(samples[: n_samples.value]),
        )


class _NativeFramer:
    def __init__(self, lib) -> None:
        self._lib = lib
        self._ptr = lib.dp_framer_new()
        if not self._ptr:
            raise MemoryError("dp_framer_new failed")

    def __del__(self):
        if getattr(self, "_ptr", None):
            self._lib.dp_framer_free(self._ptr)
            self._ptr = None

    def feed(self, batcher: _NativeBatcher, data: bytes) -> bool:
        """Feed a chunk; True means the batcher hit its seal threshold and
        bytes may remain — seal, then call ``feed(batcher, b"")`` to drain."""
        rc = self._lib.dp_framer_feed(self._ptr, batcher._ptr, data, len(data))
        if rc < 0:
            raise ValueError("malformed tx stream (oversized frame?)")
        return rc == 1


# ------------------------------------------------------------- Python twin

from .network.framing import MAX_FRAME as _MAX_FRAME  # single source of truth

_U32 = struct.Struct("<I")


class _PyBatcher:
    def __init__(self, batch_size: int) -> None:
        self.batch_size = batch_size
        self._body = bytearray()
        self.tx_count = 0
        self.tx_bytes = 0
        self._samples: List[int] = []

    def _push(self, tx) -> None:
        self._body += _U32.pack(len(tx)) + tx
        self.tx_count += 1
        self.tx_bytes += len(tx)
        if len(tx) >= 9 and tx[0] == 0:
            self._samples.append(int.from_bytes(tx[1:9], "little"))

    def ready(self) -> bool:
        return self.tx_bytes >= self.batch_size

    def seal(self) -> Optional[SealedBatch]:
        if self.tx_count == 0:
            return None
        msg = b"\x00" + _U32.pack(self.tx_count) + bytes(self._body)
        sealed = SealedBatch(msg, self.tx_count, self.tx_bytes, self._samples)
        self._body = bytearray()
        self.tx_count = 0
        self.tx_bytes = 0
        self._samples = []
        return sealed


class _PyFramer:
    def __init__(self) -> None:
        self._pend = b""

    def feed(self, batcher: _PyBatcher, data: bytes) -> bool:
        buf = self._pend + data if self._pend else data
        pos, n = 0, len(buf)
        ready = False
        while n - pos >= 4:
            if batcher.ready():
                ready = True
                break
            (flen,) = _U32.unpack_from(buf, pos)
            if flen > _MAX_FRAME:
                raise ValueError("malformed tx stream (oversized frame)")
            if n - pos - 4 < flen:
                break
            batcher._push(buf[pos + 4 : pos + 4 + flen])
            pos += 4 + flen
        self._pend = buf[pos:]
        return ready or batcher.ready()


def validate_batch(message: bytes) -> int:
    """Structural check of a serialized WorkerMessage::Batch without
    decoding: returns the tx count, or -1 if malformed.  C-backed when the
    native library is available; pure length-prefix walk otherwise."""
    lib = _load()
    if lib is not None:
        return int(lib.dp_validate_batch(message, len(message)))
    if len(message) < 5 or message[0] != 0:
        return -1
    (count,) = _U32.unpack_from(message, 1)
    pos, n = 5, len(message)
    for _ in range(count):
        if n - pos < 4:
            return -1
        (flen,) = _U32.unpack_from(message, pos)
        if flen > _MAX_FRAME or n - pos - 4 < flen:
            return -1
        pos += 4 + flen
    return count if pos == n else -1


# ------------------------------------------------------------- public API


def make_batcher(batch_size: int):
    lib = _load()
    if lib is not None:
        return _NativeBatcher(lib, batch_size)
    return _PyBatcher(batch_size)


def make_framer(for_batcher):
    if isinstance(for_batcher, _NativeBatcher):
        return _NativeFramer(for_batcher._lib)
    return _PyFramer()


def native_available() -> bool:
    return _load() is not None
