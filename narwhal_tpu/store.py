"""Persistent KV store with `notify_read` — the dependency-resolution primitive.

Reference store/src/lib.rs (94 LoC): a rocksdb behind an mpsc actor with three
commands — Write, Read, and NotifyRead, a read that parks the caller until the
key is written.  The obligations map is what the whole sync/recovery machinery
is built on (SURVEY.md §2.1 row 4, §3.5).

Here: an in-process map with an append-only log for crash recovery (replayed
on open), and parked asyncio futures per missing key.  Since the protocol
state machine runs on one event loop, plain-dict reads/writes are already
serialized — the actor boundary of the reference collapses to method calls,
which removes a channel hop from every hot-path store access.

Log persistence is a synchronous ``writev(2)`` straight from the caller:
one gather-list syscall per record, no serialization copy, page-cache
durability (power-loss durability would need fsync, which the reference's
rocksdb default also skips).  A writer thread was measured to be strictly
worse on shared-core hosts: every queue handoff forces a producer↔consumer
thread ping-pong through the GIL and scheduler (~1.4 ms per record), which
starves the event loop.
"""

from __future__ import annotations

import asyncio
import os
import struct
import weakref
from typing import Dict, List, Optional

from . import metrics

_REC = struct.Struct("<II")  # key length, value length

_m_puts = metrics.counter("store.puts")
_m_put_bytes = metrics.counter("store.put_bytes")
_m_gets = metrics.counter("store.gets")

# Parked notify_read obligations across every live store in the process —
# the depth of the dependency-resolution machinery (sync/recovery stalls
# show up here first).
_STORES: "weakref.WeakSet[Store]" = weakref.WeakSet()
metrics.gauge_fn(
    "store.parked_obligations",
    lambda: sum(len(s._obligations) for s in _STORES),
)


class Store:
    def __init__(self, path: Optional[str] = None) -> None:
        self._map: Dict[bytes, bytes] = {}
        self._obligations: Dict[bytes, List[asyncio.Future]] = {}
        self._fd: Optional[int] = None
        self._size = 0  # valid log length (single writer: we own the file)
        self._failed = False  # log lost its record boundary; writes refuse
        _STORES.add(self)
        if path is not None:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            if os.path.exists(path):
                self._replay(path)
            self._fd = os.open(
                path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644
            )

    def _replay(self, path: str) -> None:
        with open(path, "rb") as f:
            data = f.read()
        pos, n = 0, len(data)
        while pos + _REC.size <= n:
            klen, vlen = _REC.unpack_from(data, pos)
            end = pos + _REC.size + klen + vlen
            if end > n:
                break  # torn tail record from a crash; discard
            k = data[pos + _REC.size : pos + _REC.size + klen]
            self._map[k] = data[pos + _REC.size + klen : end]
            pos = end
        if pos < n:
            # Truncate the torn tail NOW: appending after the garbage would
            # make every post-recovery record unreachable to the next replay
            # (it stops at the first torn record).
            with open(path, "r+b") as f:
                f.truncate(pos)
        self._size = pos

    def write(self, key: bytes, value: bytes) -> None:
        if self._failed:
            # The log lost its record boundary (see below): refusing loudly
            # beats silently keeping memory-only state the next replay will
            # never see.  The reference aborts on storage failure too
            # (core.rs:392-395).
            raise OSError("store log is failed; refusing further writes")
        if self._fd is not None:
            # Log FIRST, memory after: a failed append must leave memory and
            # log agreeing (both without the record), not diverged.
            # One writev() per record: no serialization copy, atomic w.r.t.
            # our own replay logic (torn tails are discarded on open).
            # writev may write short (signal, ENOSPC cleared later): retry
            # the remainder, else the torn record would make every later
            # append unrecoverable on replay (truncation stops at it).
            bufs = [_REC.pack(len(key), len(value)), key, value]
            total = sum(len(b) for b in bufs)
            try:
                written = os.writev(self._fd, bufs)
                if written < total:
                    flat = b"".join(bufs)
                    while written < total:
                        written += os.write(self._fd, flat[written:])
            except OSError:
                # A torn record would strand every later append behind it on
                # replay (truncation stops at the first torn record): roll
                # the file back to the record boundary before propagating.
                try:
                    os.ftruncate(self._fd, self._size)
                except OSError:
                    # Boundary unrecoverable — poison the store so later
                    # writes fail instead of appending unreachable records.
                    # The fd must end up cleared even if close() itself
                    # fails on the dying device (else Store.close() would
                    # double-close a reused fd number).
                    self._failed = True
                    try:
                        os.close(self._fd)
                    except OSError:
                        pass
                    finally:
                        self._fd = None
                raise
            self._size += total
        _m_puts.inc()
        _m_put_bytes.inc(len(key) + len(value))
        self._map[key] = value
        # Wake every parked notify_read on this key.
        waiters = self._obligations.pop(key, None)
        if waiters:
            for fut in waiters:
                if not fut.done():
                    fut.set_result(value)

    def read(self, key: bytes) -> Optional[bytes]:
        _m_gets.inc()
        return self._map.get(key)

    async def notify_read(self, key: bytes) -> bytes:
        """Return the value for `key`, parking until it is written if absent
        (reference store/src/lib.rs:47-58)."""
        val = self._map.get(key)
        if val is not None:
            return val
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._obligations.setdefault(key, []).append(fut)
        try:
            return await fut
        finally:
            # A cancelled waiter must not leak its obligation entry (keys that
            # never arrive would otherwise accumulate futures forever).
            if fut.cancelled():
                waiters = self._obligations.get(key)
                if waiters is not None:
                    try:
                        waiters.remove(fut)
                    except ValueError:
                        pass
                    if not waiters:
                        del self._obligations[key]

    def flush(self) -> None:
        """Records hit the OS on every write(); nothing is buffered here."""

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None
