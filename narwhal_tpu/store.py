"""Persistent KV store with `notify_read` — the dependency-resolution primitive.

Reference store/src/lib.rs (94 LoC): a rocksdb behind an mpsc actor with three
commands — Write, Read, and NotifyRead, a read that parks the caller until the
key is written.  The obligations map is what the whole sync/recovery machinery
is built on (SURVEY.md §2.1 row 4, §3.5).

Here: an in-process map with an append-only log for crash recovery (replayed
on open), and parked asyncio futures per missing key.  Since the protocol
state machine runs on one event loop, plain-dict reads/writes are already
serialized — the actor boundary of the reference collapses to method calls,
which removes a channel hop from every hot-path store access.

Log persistence is a synchronous ``writev(2)`` straight from the caller:
one gather-list syscall per record, no serialization copy, page-cache
durability (power-loss durability would need fsync, which the reference's
rocksdb default also skips).  A writer thread was measured to be strictly
worse on shared-core hosts: every queue handoff forces a producer↔consumer
thread ping-pong through the GIL and scheduler (~1.4 ms per record), which
starves the event loop.
"""

from __future__ import annotations

import asyncio
import os
import struct
import weakref
from typing import Dict, List, Optional

from . import metrics

_REC = struct.Struct("<II")  # key length, value length

_m_puts = metrics.counter("store.puts")
_m_put_bytes = metrics.counter("store.put_bytes")
_m_gets = metrics.counter("store.gets")

# Parked notify_read obligations across every live store in the process —
# the depth of the dependency-resolution machinery (sync/recovery stalls
# show up here first).
_STORES: "weakref.WeakSet[Store]" = weakref.WeakSet()
metrics.gauge_fn(
    "store.parked_obligations",
    lambda: sum(len(s._obligations) for s in _STORES),
)


class Store:
    # writev(2) gather-list ceiling (IOV_MAX is 1024 on Linux); deferred
    # flushes chunk their buffer lists at this bound.
    _IOV_MAX = 1024

    def __init__(self, path: Optional[str] = None) -> None:
        self._map: Dict[bytes, bytes] = {}
        self._obligations: Dict[bytes, List[asyncio.Future]] = {}
        self._fd: Optional[int] = None
        self._size = 0  # valid log length (single writer: we own the file)
        self._failed = False  # log lost its record boundary; writes refuse
        self._pending: List[bytes] = []  # deferred log buffers (see below)
        _STORES.add(self)
        if path is not None:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            if os.path.exists(path):
                self._replay(path)
            self._fd = os.open(
                path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644
            )

    def _replay(self, path: str) -> None:
        with open(path, "rb") as f:
            data = f.read()
        pos, n = 0, len(data)
        while pos + _REC.size <= n:
            klen, vlen = _REC.unpack_from(data, pos)
            end = pos + _REC.size + klen + vlen
            if end > n:
                break  # torn tail record from a crash; discard
            k = data[pos + _REC.size : pos + _REC.size + klen]
            self._map[k] = data[pos + _REC.size + klen : end]
            pos = end
        if pos < n:
            # Truncate the torn tail NOW: appending after the garbage would
            # make every post-recovery record unreachable to the next replay
            # (it stops at the first torn record).
            with open(path, "r+b") as f:
                f.truncate(pos)
        self._size = pos

    def _append(self, bufs: List[bytes]) -> None:
        """Append a gather list of record buffers to the log.
        writev may write short (signal, ENOSPC cleared later): retry
        the remainder, else the torn record would make every later
        append unrecoverable on replay (truncation stops at it)."""
        total = sum(len(b) for b in bufs)
        try:
            # Short writes are retried PER CHUNK, before the next chunk is
            # written: retrying at the end against the flattened whole
            # would re-append the tail while leaving a hole at the short
            # chunk — a silent mid-log tear that replay only discovers by
            # truncating everything after it.
            for off in range(0, len(bufs), self._IOV_MAX):
                chunk = bufs[off : off + self._IOV_MAX]
                chunk_total = sum(len(b) for b in chunk)
                written = os.writev(self._fd, chunk)
                if written < chunk_total:
                    flat = b"".join(chunk)
                    while written < chunk_total:
                        written += os.write(self._fd, flat[written:])
        except OSError:
            # A torn record would strand every later append behind it on
            # replay (truncation stops at the first torn record): roll
            # the file back to the record boundary before propagating.
            try:
                os.ftruncate(self._fd, self._size)
            except OSError:
                # Boundary unrecoverable — poison the store so later
                # writes fail instead of appending unreachable records.
                # The fd must end up cleared even if close() itself
                # fails on the dying device (else Store.close() would
                # double-close a reused fd number).
                self._failed = True
                try:
                    os.close(self._fd)
                except OSError:
                    pass
                finally:
                    self._fd = None
            raise
        self._size += total

    def _deliver(self, key: bytes, value: bytes) -> None:
        """Memory map update + parked notify_read wakeups for one record."""
        _m_puts.inc()
        _m_put_bytes.inc(len(key) + len(value))
        self._map[key] = value
        waiters = self._obligations.pop(key, None)
        if waiters:
            for fut in waiters:
                if not fut.done():
                    fut.set_result(value)

    def write(self, key: bytes, value: bytes) -> None:
        if self._failed:
            # The log lost its record boundary (see below): refusing loudly
            # beats silently keeping memory-only state the next replay will
            # never see.  The reference aborts on storage failure too
            # (core.rs:392-395).
            raise OSError("store log is failed; refusing further writes")
        if self._fd is not None:
            # Drain any deferred buffer FIRST: an immediate append jumping
            # ahead of buffered records would invert the callers' persist
            # order in the log (e.g. a certificate logged before the
            # header it certifies — a crash pre-flush would then replay
            # the cert without its header, which the reference's
            # header-then-cert write order can never produce).
            if self._pending:
                self.flush_deferred()
            # Log FIRST, memory after: a failed append must leave memory and
            # log agreeing (both without the record), not diverged.
            # One writev() per record: no serialization copy, atomic w.r.t.
            # our own replay logic (torn tails are discarded on open).
            self._append([_REC.pack(len(key), len(value)), key, value])
        self._deliver(key, value)

    def write_deferred(self, key: bytes, value: bytes) -> None:
        """Write with the log append DEFERRED to the next flush_deferred().

        Memory (and parked notify_read waiters) see the record immediately
        — every in-process invariant is identical to write() — but the log
        record is only buffered, so a burst of N records costs ONE writev
        at flush time instead of N syscalls on the hot path.  The caller
        owns the durability ordering: anything that must not leave the
        node before the record is logged (a vote for the header, per the
        persist-before-vote rule) must flush first.  Note the inversion vs
        write(): memory is updated BEFORE the log here, so a flush failure
        leaves memory ahead of the log — acceptable because a failed
        append poisons the store and the node aborts (reference
        core.rs:392-395 does the same on storage failure)."""
        if self._failed:
            raise OSError("store log is failed; refusing further writes")
        if self._fd is not None:
            self._pending.extend(
                (_REC.pack(len(key), len(value)), key, value)
            )
        self._deliver(key, value)

    def flush_deferred(self) -> None:
        """Append every record buffered by write_deferred in one writev
        (chunked at IOV_MAX).  No-op when nothing is pending.

        On a failed append the records STAY buffered: _append rolls the
        file back to the record boundary, so a later flush (or close(),
        which flushes) retries the whole batch — dropping them here would
        silently diverge memory (already served to notify_read waiters)
        from the log.  If the rollback itself failed, the store is
        poisoned and this raises like every other write path."""
        if not self._pending:
            return
        if self._failed:
            raise OSError("store log is failed; refusing further writes")
        if self._fd is not None:
            self._append(self._pending)  # raises with records kept pending
        self._pending = []

    def read(self, key: bytes) -> Optional[bytes]:
        _m_gets.inc()
        return self._map.get(key)

    def values(self) -> List[bytes]:
        """Snapshot of every stored value (boot-time recovery scans: the
        post-restore consensus replay parses these for certificates)."""
        return list(self._map.values())

    async def notify_read(self, key: bytes) -> bytes:
        """Return the value for `key`, parking until it is written if absent
        (reference store/src/lib.rs:47-58)."""
        val = self._map.get(key)
        if val is not None:
            return val
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        # lint: allow-interleave(every store-sharing task root can append to _obligations while this waiter is suspended on its future — safely: _deliver pops a key's WHOLE waiter list atomically before resolving any future, and the cancelled-waiter cleanup below removes only the future THIS call appended, re-reading the dict after the suspension)
        self._obligations.setdefault(key, []).append(fut)
        try:
            return await fut
        finally:
            # A cancelled waiter must not leak its obligation entry (keys that
            # never arrive would otherwise accumulate futures forever).
            if fut.cancelled():
                waiters = self._obligations.get(key)
                if waiters is not None:
                    try:
                        waiters.remove(fut)
                    except ValueError:
                        pass
                    if not waiters:
                        del self._obligations[key]

    def flush(self) -> None:
        """write() records hit the OS immediately; this only drains any
        write_deferred buffer (see flush_deferred)."""
        self.flush_deferred()

    def close(self) -> None:
        if self._fd is not None:
            try:
                self.flush_deferred()
            finally:
                if self._fd is not None:  # _append may have poisoned us
                    os.close(self._fd)
                    self._fd = None
