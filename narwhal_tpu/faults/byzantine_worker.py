"""Byzantine worker behaviors: the payload-availability attacks.

The paper's central availability claim is that a certificate is a *proof
of batch availability* — 2f+1 workers ACKed the batch, so consensus never
fetches bodies on the critical path.  These behaviors attack exactly that
claim at the worker plane, each as a thin subclass of the live pipeline
class acting only at the network boundary (the primary-plane pattern of
``faults.byzantine``):

- ``withhold_batches`` — the BatchMaker broadcasts each sealed batch to
  JUST enough peers that, with our own stake, the ACK quorum still
  completes (so the batch certifies and enters headers), and the Helper
  then never answers ``BatchRequest``s for it.  The starved peers must
  recover through the Synchronizer's retry escalation to random holders
  — and their ``worker.unserved_sync_age_seconds`` names the attack
  (the ``batch_withholding`` health rule).
- ``garbage_batches`` — same under-sharing split, but the Helper answers
  sync requests with junk: alternately an OVERSIZED structurally-valid
  batch (rejected by the receiver's ``max_batch_bytes`` gate into
  ``worker.garbage_batches`` — the ``garbage_batches`` rule) and a
  corrupt frame (the existing malformed-drop path).  Honest peers still
  recover via escalation, because f+1 honest ACKers hold the real bytes
  — which is precisely the availability property under test.
- ``sync_flood`` — repeated maximum-size ``BatchRequest``s to every
  peer, exploiting the ~32 B-request → ~500 kB-reply amplification of
  worker/helper.py.  The Helper's per-request digest cap bounds the
  damage and counts the abuse into ``worker.helper_rejected_requests``
  (the ``helper_abuse`` rule).

All randomness (peer splits, junk bytes, flood padding) comes from the
plan's seeded RNG, like the primary-plane behaviors.
"""

from __future__ import annotations

import asyncio
import logging

from .. import metrics
from ..crypto import Digest
from ..crypto.digest import DIGEST_LEN
from ..messages import encode_batch_request
from ..network import SimpleSender
from ..worker.batch_maker import BatchMaker
from ..worker.helper import Helper, max_request_digests
from .byzantine import ByzantinePlan, _require_unit_stake

log = logging.getLogger("narwhal.faults")

# The flood names this many digests per request — far past the Helper's
# cap, so every frame is provably abusive on arrival.
_FLOOD_DIGESTS_MIN = 1_024

_SPLIT_BEHAVIORS = {"withhold_batches", "garbage_batches"}


class ByzantineBatchMaker(BatchMaker):
    """Under-shares every sealed batch: the ACK quorum still completes
    (our own stake + exactly enough peers), but the remaining peers never
    receive the broadcast and must fall back to ``BatchRequest`` — which
    the ByzantineHelper then refuses or poisons."""

    def __init__(self, plan: ByzantinePlan, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.plan = plan
        self._split = bool(_SPLIT_BEHAVIORS & plan.behaviors)
        if self._split:
            # The share is sized by COUNT against the stake-denominated
            # quorum threshold — same restriction (and same loud refusal)
            # as the primary plane's equivocate split.
            _require_unit_stake(
                self.committee,
                behavior=sorted(_SPLIT_BEHAVIORS & plan.behaviors)[0],
            )
        self._m_withheld = metrics.counter(
            "faults.byzantine.batches_withheld"
        )

    def _broadcast_batch(self, digest, message: bytes):
        if not self._split:
            return super()._broadcast_batch(digest, message)
        stake_by_addr = {addr: stake for stake, addr in self._peers}
        keep = self.committee.quorum_threshold() - self.committee.stake(
            self.name
        )
        # The authority-keyed favored split: aligned with the primary
        # plane's real-header share (plan.favored_split docstring), so
        # the under-share can never starve our own header's vote quorum.
        share, starved = self.plan.favored_split(
            {
                peer_name: addrs.worker_to_worker
                for peer_name, addrs in self.committee.others_workers(
                    self.name, self.worker_id
                )
            },
            keep,
        )
        self._m_withheld.inc()
        log.warning(
            "FAULT withholding batch %r from %d peer(s) "
            "(certifying via %d + own ACK)",
            digest, len(starved), len(share),
        )
        return [
            (stake_by_addr[addr], self.sender.send(addr, message, msg_type="batch"))
            for addr in share
        ]


class ByzantineHelper(Helper):
    """Answers (or refuses) sync requests adversarially.  Request intake,
    dedup/cap bounding and the abuse accounting stay the honest path —
    only the serve decision (`_respond`) is overridden."""

    def __init__(self, plan: ByzantinePlan, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.plan = plan
        self._m_ignored = metrics.counter(
            "faults.byzantine.sync_requests_ignored"
        )
        self._m_garbage = metrics.counter("faults.byzantine.garbage_served")
        self._junk_frame = None
        self._served = 0

    def _garbage(self) -> bytes:
        """A structurally VALID batch frame of ``plan.garbage_bytes`` junk
        (one giant transaction) — it passes the length-prefix walk, so
        only the receiver's size gate stands between it and a
        multi-megabyte hash + store append.  Built once, lazily."""
        if self._junk_frame is None:
            body = self.plan.rng.randbytes(self.plan.garbage_bytes)
            self._junk_frame = (
                b"\x00"
                + (1).to_bytes(4, "little")
                + len(body).to_bytes(4, "little")
                + body
            )
        return self._junk_frame

    async def _respond(self, address: str, digests) -> None:
        behaviors = self.plan.behaviors
        if "withhold_batches" in behaviors:
            self._m_ignored.inc()
            log.warning(
                "FAULT ignoring batch request for %d digest(s)", len(digests)
            )
            return
        if "garbage_batches" in behaviors:
            for digest in digests:
                self._served += 1
                if self._served % 2:
                    reply = self._garbage()
                else:
                    # A corrupt normal-size frame: valid batch tag, body
                    # that fails the structural walk (truncated tx).
                    reply = b"\x00" + (3).to_bytes(4, "little") + b"\x77"
                self._m_garbage.inc()
                self.sender.send(address, reply, msg_type="batch")
            if digests:
                log.warning(
                    "FAULT served garbage for %d digest(s)", len(digests)
                )
            return
        await super()._respond(address, digests)


class SyncFlooder:
    """``sync_flood``: a request loop sending max-size ``BatchRequest``s
    to every peer on a fixed cadence.  Digests are drawn from our own
    store (batches the peers genuinely hold — the real amplification
    case) padded with seeded-random junk to the flood width, so the flood
    is at full strength from the first tick."""

    def __init__(
        self, plan: ByzantinePlan, name, worker_id, committee, store
    ) -> None:
        self.plan = plan
        self.name = name
        self.worker_id = worker_id
        self.committee = committee
        self.store = store
        self.sender = SimpleSender()
        self._m_floods = metrics.counter("faults.byzantine.sync_floods")

    def _flood_digests(self):
        width = max(_FLOOD_DIGESTS_MIN, 2 * max_request_digests())
        # The store's key map is an implementation detail we peek at
        # deliberately: a real attacker knows the digests it was sent.
        stored = [
            Digest(k)
            for k in getattr(self.store, "_map", {})
            if len(k) == DIGEST_LEN
        ][: width // 2]
        junk = [
            Digest(self.plan.rng.randbytes(DIGEST_LEN))
            for _ in range(width - len(stored))
        ]
        return stored + junk

    async def run(self) -> None:
        interval = max(0.02, self.plan.flood_interval_ms / 1000.0)
        addresses = [
            addrs.worker_to_worker
            for _, addrs in self.committee.others_workers(
                self.name, self.worker_id
            )
        ]
        while True:
            await asyncio.sleep(interval)
            message = encode_batch_request(self._flood_digests(), self.name)
            for address in addresses:
                self.sender.send(address, message, msg_type="batch_request")
            self._m_floods.inc()
