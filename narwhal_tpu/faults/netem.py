"""WAN emulation at the ``network/`` seam: latency, jitter, loss, partitions.

One ``NetEmulator`` per process, configured from a JSON file named by the
``NARWHAL_FAULT_NETEM`` env var (the per-node compilation of a scenario's
``wan`` plane, written by benchmark/fault_bench.py) and selected into by
``NARWHAL_FAULT_NODE``.  The network layer calls two hooks:

- :func:`blocked` — before every outbound connect; a partitioned peer's
  connect attempt fails like a dead host (OSError), so the sender runs its
  REAL reconnect-backoff path and the ``peer_unreachable`` health rule has
  the same signal a real partition leaves;
- :func:`wrap` — after every successful outbound connect; when a shaping
  rule matches the destination, the writer is replaced by a
  :class:`_ShapedWriter` that delays each frame by latency+jitter and
  surfaces emulated loss as a connection reset (TCP semantics: a lost
  segment stalls then kills the stream — it never silently drops one
  message), so ReliableSender retransmits and SimpleSender visibly drops.

Per-peer-pair shaping lives entirely on the initiating side, where the
destination identity is known.  ACK return legs ride the unwrapped
socket: one-way latency is emulated exactly, measured RTTs see the
outbound leg.

Every stochastic draw comes from one ``random.Random`` seeded from the
scenario seed and the node name, so a scenario replays identically under
the same ``NARWHAL_FAULT_SEED``.  With no env config the hooks are a
single ``is None`` check — zero cost for normal runs.
"""

from __future__ import annotations

import asyncio
import collections
import json
import random
import time
import zlib
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional, Tuple

from ..utils.env import env_str
from ..utils.tasks import spawn


@dataclass(frozen=True)
class Shape:
    latency_ms: float = 0.0
    jitter_ms: float = 0.0
    loss: float = 0.0

    def delay_s(self, rng: random.Random) -> float:
        return (self.latency_ms + self.jitter_ms * rng.random()) / 1000.0

    def shaping(self) -> bool:
        return self.latency_ms > 0 or self.jitter_ms > 0 or self.loss > 0


@dataclass(frozen=True)
class PartitionWindow:
    peers: frozenset  # destination addresses cut off from this node
    from_s: float
    until_s: Optional[float]  # None = never heals


class NetEmulator:
    """Per-process shaping state.  ``start_ts`` anchors the partition
    windows (the runner stamps launch time so every node agrees on when
    a partition begins and heals)."""

    def __init__(
        self,
        rules: Dict[str, Shape],
        default: Optional[Shape],
        partitions: List[PartitionWindow],
        seed: int,
        node: str = "",
        start_ts: Optional[float] = None,
    ) -> None:
        self.rules = dict(rules)
        self.default = default
        self.partitions = list(partitions)
        self.start_ts = time.time() if start_ts is None else start_ts
        # One deterministic stream per (scenario seed, node): replaying a
        # scenario re-draws identical jitter/loss decisions.
        self.rng = random.Random(seed ^ zlib.crc32(node.encode()))

    @classmethod
    def load(cls, path: str, node: str) -> Optional["NetEmulator"]:
        with open(path) as f:
            cfg = json.load(f)
        entry = (cfg.get("nodes") or {}).get(node)
        if entry is None:
            return None  # this process is unshaped in the scenario
        rules: Dict[str, Shape] = {}
        default: Optional[Shape] = None
        for r in entry.get("rules", []):
            shape = Shape(
                latency_ms=float(r.get("latency_ms", 0.0)),
                jitter_ms=float(r.get("jitter_ms", 0.0)),
                loss=float(r.get("loss", 0.0)),
            )
            if r.get("dst", "*") == "*":
                default = shape
            else:
                rules[r["dst"]] = shape
        partitions = [
            PartitionWindow(
                peers=frozenset(p["peers"]),
                from_s=float(p["from_s"]),
                until_s=(
                    None if p.get("until_s") is None else float(p["until_s"])
                ),
            )
            for p in entry.get("partitions", [])
        ]
        return cls(
            rules,
            default,
            partitions,
            seed=int(cfg.get("seed", 0)),
            node=node,
            start_ts=cfg.get("start_ts"),
        )

    # -- hooks ----------------------------------------------------------------

    def shape_for(self, address: str) -> Optional[Shape]:
        shape = self.rules.get(address, self.default)
        return shape if shape is not None and shape.shaping() else None

    def blocked(self, address: str, now: Optional[float] = None) -> bool:
        if not self.partitions:
            return False
        t = (time.time() if now is None else now) - self.start_ts
        for w in self.partitions:
            if address in w.peers and t >= w.from_s and (
                w.until_s is None or t < w.until_s
            ):
                return True
        return False

    def wrap(
        self,
        address: str,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
    ) -> Tuple[asyncio.StreamReader, "asyncio.StreamWriter"]:
        shape = self.shape_for(address)
        # A peer named in a pending or open partition window gets wrapped
        # even when unshaped: a partition must cut ESTABLISHED connections
        # too (the wrapper re-checks `blocked` on every drain), not just
        # refuse new ones.  Windows that have already healed for good are
        # ignored — post-heal reconnects must not pay the per-frame
        # queue-and-pump hop on the catch-up path.
        elapsed = time.time() - self.start_ts
        partitioned = any(
            address in w.peers
            and (w.until_s is None or elapsed < w.until_s)
            for w in self.partitions
        )
        if shape is None and not partitioned:
            return reader, writer
        return reader, _ShapedWriter(  # type: ignore[return-value]
            writer, shape or Shape(), self.rng, emu=self, address=address
        )


class _ShapedWriter:
    """StreamWriter stand-in that releases each drained frame after the
    shape's latency+jitter, in order, and surfaces emulated loss as a
    connection reset at drain time.

    ``write()`` only buffers; ``drain()`` seals the buffered bytes into one
    delivery unit (write_frame's prefix+payload pair stays atomic) and
    hands it to the pump task.  drain never exerts backpressure — the
    emulated pipe absorbs the bytes, like a WAN's bandwidth-delay product.
    """

    def __init__(
        self,
        writer: asyncio.StreamWriter,
        shape: Shape,
        rng: random.Random,
        emu: Optional["NetEmulator"] = None,
        address: str = "",
    ) -> None:
        self._w = writer
        self._shape = shape
        self._rng = rng
        self._emu = emu
        self._addr = address
        self._buf = bytearray()
        self._q: Deque[Tuple[float, bytes]] = collections.deque()
        self._wake = asyncio.Event()
        self._exc: Optional[BaseException] = None
        self._loop = asyncio.get_running_loop()
        self._task = spawn(self._pump(), name="netem-pump")

    def write(self, data: bytes) -> None:
        self._buf += data

    async def drain(self) -> None:
        if self._exc is not None:
            raise self._exc
        if self._emu is not None and self._emu.blocked(self._addr):
            # The partition window opened while this connection was up:
            # cut it like a real link failure.
            raise ConnectionResetError("netem: partitioned from peer")
        chunk = bytes(self._buf)
        self._buf.clear()
        if not chunk:
            return
        if self._shape.loss and self._rng.random() < self._shape.loss:
            # TCP loses segments, not messages: surface the loss as a dead
            # stream so the caller's real recovery path (reconnect +
            # retransmit, or visible drop) runs instead of a silent skip.
            raise ConnectionResetError("netem: emulated segment loss")
        self._q.append((self._loop.time() + self._shape.delay_s(self._rng), chunk))
        self._wake.set()

    async def _pump(self) -> None:
        try:
            while True:
                while not self._q:
                    self._wake.clear()
                    await self._wake.wait()
                due, chunk = self._q.popleft()
                now = self._loop.time()
                if due > now:
                    await asyncio.sleep(due - now)
                self._w.write(chunk)
                await self._w.drain()
        except asyncio.CancelledError:
            raise
        except BaseException as e:  # surfaced on the caller's next drain
            self._exc = e

    def close(self) -> None:
        self._task.cancel()
        self._w.close()

    def is_closing(self) -> bool:
        return self._w.is_closing()

    async def wait_closed(self) -> None:
        await self._w.wait_closed()

    def get_extra_info(self, *args, **kwargs):
        return self._w.get_extra_info(*args, **kwargs)

    @property
    def transport(self):
        return self._w.transport


# -- scenario wan-plane resolution --------------------------------------------


def resolve_wan_plane(scenario, committee, names) -> Dict[str, dict]:
    """Resolve a scenario's ``wan`` plane (committee-wide defaults,
    per-directed-pair overrides, partition windows — see
    ``faults/spec.py::WanSpec``) into per-node-label shaping config:
    ``{label: {"rules": [{dst, latency_ms, jitter_ms, loss}],
    "partitions": [{"peers": [...], "from_s", "until_s"}]}}`` with
    destination ADDRESSES.  Intra-authority LAN traffic stays unshaped.
    The ONE compilation both fault harnesses consume:
    ``benchmark/fault_bench.py`` wraps it into the per-process config
    file this module loads, and ``narwhal_tpu/sim/transport.py`` feeds
    it to the in-memory transport — so the socketed and simulated WAN
    semantics can never drift apart."""
    wan = scenario.wan
    if wan is None:
        return {}
    nodes: Dict[str, dict] = {}

    def entry(label: str) -> dict:
        return nodes.setdefault(label, {"rules": [], "partitions": []})

    def wan_addresses(j: int) -> List[str]:
        auth = committee.authorities[names[j]]
        return [auth.primary.primary_to_primary] + [
            w.worker_to_worker for w in auth.workers.values()
        ]

    pair_shapes = {(p.src, p.dst): p for p in wan.pairs}
    for i in range(scenario.nodes):
        labels = [f"primary-{i}"] + [
            f"worker-{i}-{wid}" for wid in range(scenario.workers)
        ]
        for j in range(scenario.nodes):
            if j == i:
                continue  # intra-authority traffic stays LAN-fast
            p = pair_shapes.get((i, j))
            shape = {
                "latency_ms": p.latency_ms if p else wan.latency_ms,
                "jitter_ms": p.jitter_ms if p else wan.jitter_ms,
                "loss": p.loss if p else wan.loss,
            }
            if not any(shape.values()):
                continue
            for dst in wan_addresses(j):
                for label in labels:
                    entry(label)["rules"].append(dict(shape, dst=dst))
        for part in wan.partitions:
            group = set(part.group)
            cut = (
                [j for j in range(scenario.nodes) if j not in group]
                if i in group
                else list(group)
            )
            peers = [a for j in cut for a in wan_addresses(j)]
            if not peers:
                continue
            for label in labels:
                entry(label)["partitions"].append(
                    {
                        "peers": peers,
                        "from_s": part.from_s,
                        "until_s": part.until_s,
                    }
                )
    return nodes


# -- process-wide accessor -----------------------------------------------------

_EMULATOR: Optional[NetEmulator] = None
_LOADED = False


def emulator() -> Optional[NetEmulator]:
    """The process's emulator, lazily loaded from NARWHAL_FAULT_NETEM /
    NARWHAL_FAULT_NODE; None (the overwhelmingly common case) means every
    hook below is a no-op."""
    global _EMULATOR, _LOADED
    if not _LOADED:
        _LOADED = True
        path = env_str("NARWHAL_FAULT_NETEM")
        if path:
            _EMULATOR = NetEmulator.load(
                path, env_str("NARWHAL_FAULT_NODE")
            )
    return _EMULATOR


def install(emu: Optional[NetEmulator]) -> None:
    """Programmatic install (tests, in-process harnesses)."""
    global _EMULATOR, _LOADED
    _EMULATOR = emu
    _LOADED = True


def reset() -> None:
    """Forget any installed/loaded emulator; the next :func:`emulator`
    call re-reads the environment."""
    global _EMULATOR, _LOADED
    _EMULATOR = None
    _LOADED = False


def blocked(address: str) -> bool:
    emu = emulator()
    return emu is not None and emu.blocked(address)


def wrap(
    address: str,
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
) -> Tuple[asyncio.StreamReader, asyncio.StreamWriter]:
    emu = emulator()
    if emu is None:
        return reader, writer
    return emu.wrap(address, reader, writer)

