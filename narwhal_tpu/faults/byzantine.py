"""Byzantine primary behaviors: ``ByzantineCore`` / ``ByzantineProposer``.

Both are thin subclasses of the live protocol classes — the node runs the
REAL header/vote/certificate machinery and the fault is injected exactly
where a real adversary would act, at the network boundary:

- ``equivocate`` — the Proposer mints a signed twin header per round
  (same round, slightly different parent set or payload, so every honest
  peer can fully process it) and the Core broadcasts the real header to
  just enough peers to still certify (quorum − 1, plus our own vote) and
  the twin to everyone else.  Honest peers vote for whichever they saw
  first; when the real header's certificate reaches a twin-voter, its
  Core holds two validly signed headers for one (round, author) slot —
  a proven equivocation, counted into
  ``primary.equivocations_detected`` (the `equivocation` rule's input).
- ``wrong_key`` — headers go out carrying a rogue keypair's signature
  over the correct header id; peers' signature checks reject them
  (``primary.invalid_signatures`` → the `invalid_signature` rule).
- ``withhold_votes`` — never send votes for targeted authors' headers
  (the once-per-slot vote record is still kept, so the node is a silent
  abstainer, not a double voter); the victims' ``peer_vote_silence``
  rule names this node.
- ``replay_stale`` — re-broadcast the node's earliest own certificates
  forever; once the committee's GC horizon passes them, every replay is
  a ``primary.stale_messages`` hit on every peer (the `stale_replay`
  rule's input).

All randomness (peer-set splits, twin perturbation, the rogue key) comes
from the plan's seeded ``random.Random`` so a scenario replays
identically under the same ``NARWHAL_FAULT_SEED``.
"""

from __future__ import annotations

import asyncio
import json
import logging
import random
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .. import metrics
from ..crypto import KeyPair, PublicKey
from ..messages import Round
from ..primary.core import Core
from ..primary.messages import Header, Vote, encode_primary_message
from ..primary.proposer import Proposer
from .spec import BYZANTINE_BEHAVIORS, SpecError
from ..utils.tasks import spawn

log = logging.getLogger("narwhal.faults")

# How many of our earliest certificates the replay loop cycles through.
_STALE_CAP = 4
# Twin headers kept for the Core to pick up (rounds move on; a twin the
# Core never consumed is garbage after a few rounds).
_TWIN_CAP = 16


class ByzantinePlan:
    """Shared state between the Byzantine Proposer and Core of one node:
    which behaviors are active, the seeded RNG, the rogue keypair, and
    the twin headers minted by the Proposer for the Core to split-cast."""

    def __init__(
        self,
        behaviors: Sequence[str],
        seed: int = 0,
        withhold_targets: Optional[Set[PublicKey]] = None,
        replay_interval_ms: int = 250,
        flood_interval_ms: int = 200,
        garbage_bytes: int = 2_200_000,
    ) -> None:
        unknown = set(behaviors) - set(BYZANTINE_BEHAVIORS)
        if unknown:
            raise SpecError(f"unknown byzantine behavior(s): {sorted(unknown)}")
        if {"withhold_batches", "garbage_batches"} <= set(behaviors):
            raise SpecError(
                "withhold_batches and garbage_batches conflict "
                "(both decide what the worker Helper serves)"
            )
        self.behaviors = set(behaviors)
        self.seed = seed
        self.rng = random.Random(seed)
        # None = withhold from every other author.
        self.withhold_targets = withhold_targets
        self.replay_interval_ms = replay_interval_ms
        self.flood_interval_ms = flood_interval_ms
        self.garbage_bytes = garbage_bytes
        self.twins: Dict[Round, Header] = {}
        # Deterministic rogue identity for wrong_key: valid ed25519
        # signatures from a key that is simply not the author's.
        self.rogue = KeyPair.generate(self.rng.randbytes(32))

    def primary_behaviors(self) -> Set[str]:
        from .spec import PRIMARY_BEHAVIORS

        return self.behaviors & set(PRIMARY_BEHAVIORS)

    def worker_behaviors(self) -> Set[str]:
        from .spec import WORKER_BEHAVIORS

        return self.behaviors & set(WORKER_BEHAVIORS)

    @classmethod
    def from_json(cls, obj: dict) -> "ByzantinePlan":
        targets = obj.get("withhold_targets")
        resolved: Optional[Set[PublicKey]] = None
        if targets:
            resolved = {PublicKey.decode_base64(t) for t in targets}
        return cls(
            behaviors=list(obj.get("behaviors", [])),
            seed=int(obj.get("seed", 0)),
            withhold_targets=resolved,
            replay_interval_ms=int(obj.get("replay_interval_ms", 250)),
            flood_interval_ms=int(obj.get("flood_interval_ms", 200)),
            garbage_bytes=int(obj.get("garbage_bytes", 2_200_000)),
        )

    @classmethod
    def load(cls, path: str) -> "ByzantinePlan":
        with open(path) as f:
            return cls.from_json(json.load(f))

    def favored_split(
        self, addr_by_name: Dict, keep: int
    ) -> Tuple[List[str], List[str]]:
        """The adversary's ONE coordinated peer split, keyed by authority
        so every plane of this node favors the SAME validators: the
        Core's real-header share and the worker's batch under-share both
        take the first ``keep`` names of one seed-derived permutation.

        Coordination is the point (and what a real adversary would do):
        when the splits were drawn independently per plane and per round,
        an equivocate+withhold composition starved the adversary's OWN
        vote quorum — the real-header share needed every member to hold
        the under-shared batch, which at N≥10 almost never happened, so
        the certificate never formed, never crossed the split to the
        twin-voters, and the committee could not prove the equivocation
        it was expected to detect (sim sweep points 7023/7024/7034/7035).
        Aligned splits keep the attack COHERENT: the favored quorum can
        vote, the certificate forms, and the starved side both misses
        batches (the withholding evidence) and holds the twin (the
        equivocation evidence).

        Deterministic from the plan seed and the roster alone — two
        independently-loaded plan instances (one per role process) with
        the same seed produce the same split, and nothing here consumes
        the shared sequential ``self.rng`` stream."""
        names = sorted(addr_by_name)
        random.Random(f"narwhal-favored-peers:{self.seed}").shuffle(names)
        keep = max(0, min(keep, len(names)))
        return (
            [addr_by_name[n] for n in names[:keep]],
            [addr_by_name[n] for n in names[keep:]],
        )


def _require_unit_stake(committee, behavior: str = "equivocate") -> None:
    """Behaviors that split a peer set by COUNT against the
    stake-denominated ``quorum_threshold()`` (equivocate's twin/real
    share, the worker plane's withhold/garbage under-share) are only
    valid when every stake is 1 (count == stake).  On a weighted
    committee the split could fall below quorum (never certified /
    never proven at any peer), silently voiding the scenario — refuse
    loudly instead, naming the behavior that needs the property."""
    stakes = {
        str(n): a.stake
        for n, a in committee.authorities.items()
        if a.stake != 1
    }
    if stakes:
        raise SpecError(
            f"the {behavior!r} behavior requires a unit-stake committee "
            f"(count == stake); found weighted authorities: {stakes}"
        )


class ByzantineProposer(Proposer):
    """Mints the equivocation twin alongside every real header."""

    def __init__(self, plan: ByzantinePlan, name, committee, *args, **kwargs):
        super().__init__(name, committee, *args, **kwargs)
        self.plan = plan
        self.committee = committee
        if "equivocate" in plan.behaviors:
            _require_unit_stake(committee)
        self._m_twins = metrics.counter("faults.byzantine.twins_minted")

    async def _make_header(self) -> None:
        # Mint and register the twin BEFORE super() queues the real
        # header: the Core can consume the header the moment it is
        # queued, and a twin registered after that pop is silently never
        # split-cast (which rounds equivocate would then depend on
        # scheduling, not on the seed).
        if "equivocate" in self.plan.behaviors:
            await self._mint_twin(
                self.round, list(self.last_parents), dict(self.digests)
            )
        await super()._make_header()

    async def _mint_twin(self, round_, parents, payload) -> None:
        # The twin must be fully processable by honest peers (otherwise it
        # parks in their waiters and the equivocation is never proven), so
        # it only ever SHRINKS the real header: drop one parent when the
        # set stays above quorum (stake-1 committees: count == stake), else
        # drop one payload digest (a subset of batches the peers already
        # hold).  An empty-parent-margin, empty-payload round mints none.
        twin_parents, twin_payload = parents, payload
        if len(parents) > self.committee.quorum_threshold():
            drop = self.plan.rng.randrange(len(parents))
            twin_parents = [p for i, p in enumerate(parents) if i != drop]
        elif payload:
            gone = self.plan.rng.choice(sorted(payload))
            twin_payload = {d: w for d, w in payload.items() if d != gone}
        else:
            return
        twin = await Header.new(
            self.name, round_, twin_payload, twin_parents,
            self.signature_service,
        )
        self._m_twins.inc()
        self.plan.twins[round_] = twin
        while len(self.plan.twins) > _TWIN_CAP:
            self.plan.twins.pop(min(self.plan.twins))


class ByzantineCore(Core):
    """Executes the plan's behaviors at the broadcast/vote boundary; all
    inbound processing stays byte-for-byte the honest Core."""

    def __init__(self, plan: ByzantinePlan, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.plan = plan
        if "equivocate" in plan.behaviors:
            _require_unit_stake(self.committee)
        self._stale_certs: List[bytes] = []
        self._replay_futs: List[asyncio.Future] = []
        self._m_equivocated = metrics.counter(
            "faults.byzantine.equivocated_headers"
        )
        self._m_wrong_key = metrics.counter(
            "faults.byzantine.wrong_key_headers"
        )
        self._m_withheld = metrics.counter("faults.byzantine.votes_withheld")
        self._m_replays = metrics.counter("faults.byzantine.stale_replays")

    def _broadcast_own_header(self, header: Header) -> List:
        # Only the WIRE copy is tampered with — local processing (our
        # vote, our certificate aggregation) still sees the real header,
        # exactly like the honest path, because the base class calls this
        # seam for the broadcast alone.
        plan = self.plan
        wire_header = header
        if "wrong_key" in plan.behaviors:
            # Correct header id, valid signature, WRONG key: peers must
            # reject it at the signature gate, not the structure gate.
            wire_header = Header(
                author=header.author,
                round=header.round,
                payload=dict(header.payload),
                parents=set(header.parents),
                id=header.id,
                signature=plan.rogue.sign(header.id),
            )
            self._m_wrong_key.inc()
        message = encode_primary_message(wire_header)
        twin = (
            plan.twins.pop(header.round, None)
            if "equivocate" in plan.behaviors
            else None
        )
        if twin is None:
            return self.network.broadcast(
                self.others_addresses, message, msg_type="header"
            )
        real_share, twin_share = plan.favored_split(
            {
                n: self.committee.primary(n).primary_to_primary
                for n in self.committee.authorities
                if n != self.name
            },
            self.committee.quorum_threshold() - 1,
        )
        handlers = self.network.broadcast(
            real_share, message, msg_type="header"
        )
        handlers.extend(
            self.network.broadcast(
                twin_share, encode_primary_message(twin), msg_type="header"
            )
        )
        self._m_equivocated.inc()
        log.warning(
            "FAULT equivocating at round %d: %r to %d peer(s), "
            "twin %r to %d peer(s)",
            header.round, header.id, len(real_share),
            twin.id, len(twin_share),
        )
        return handlers

    async def _dispatch_vote(self, vote: Vote, header: Header) -> None:
        plan = self.plan
        if "withhold_votes" in plan.behaviors and vote.origin != self.name:
            targets = plan.withhold_targets
            if targets is None or header.author in targets:
                self._m_withheld.inc()
                log.warning(
                    "FAULT withholding vote for %r round %d",
                    header.author, header.round,
                )
                return
        await super()._dispatch_vote(vote, header)

    async def process_certificate(self, certificate) -> None:
        if (
            "replay_stale" in self.plan.behaviors
            and certificate.origin == self.name
            and len(self._stale_certs) < _STALE_CAP
        ):
            self._stale_certs.append(encode_primary_message(certificate))
        await super().process_certificate(certificate)

    async def run(self) -> None:
        replay_task = None
        if "replay_stale" in self.plan.behaviors:
            replay_task = spawn(self._replay_loop(), name="byz-replay")
        try:
            await super().run()
        finally:
            if replay_task is not None:
                replay_task.cancel()

    def _seed_stale_from_store(self) -> None:
        """A restarted replay attacker replays its OLD certificates, not
        its post-restart ones: without this, a crash/restart composition
        re-anchored ``_stale_certs`` at the restart round and the GC
        horizon could not pass them within any affordable scenario
        window (sim sweep point 7017 at N=20) — a replay adversary that
        forgets what it persisted is not a believable adversary.  Scans
        the retained store once at replay start for our earliest own
        vote-carrying certificates."""
        from ..primary.messages import Certificate

        mine = []
        for value in self.store.values():
            if len(value) < 140:
                continue
            try:
                cert = Certificate.deserialize(value)
            except Exception:
                continue
            if cert.votes and cert.origin == self.name:
                mine.append(cert)
        mine.sort(key=lambda c: c.round)
        for cert in mine[:_STALE_CAP]:
            self._stale_certs.append(encode_primary_message(cert))

    async def _replay_loop(self) -> None:
        """Re-broadcast our earliest certificates forever.  Early on the
        replays are idempotent re-inserts at the peers; once the
        committee's GC horizon passes the certificates' rounds, every
        replay is a TooOld rejection — the stale-flood signal."""
        if not self._stale_certs:
            self._seed_stale_from_store()
        interval = max(0.01, self.plan.replay_interval_ms / 1000.0)
        i = 0
        while True:
            await asyncio.sleep(interval)
            if not self._stale_certs:
                continue
            message = self._stale_certs[i % len(self._stale_certs)]
            i += 1
            self._replay_futs = [
                f for f in self._replay_futs if not f.done()
            ]
            if len(self._replay_futs) > 1_000:
                # Peers gone/unreachable: stop accumulating un-ACKed
                # deliveries (the flood must not OOM the attacker).
                for f in self._replay_futs:
                    f.cancel()
                self._replay_futs = []
            self._replay_futs.extend(
                self.network.broadcast(
                    self.others_addresses, message, msg_type="certificate"
                )
            )
            self._m_replays.inc()
