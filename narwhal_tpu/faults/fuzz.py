"""Fuzzed fault-scenario generation: one seed → one replayable scenario.

``generate(seed)`` derives a random-but-fully-seeded scenario instance —
which fault planes run, on which node, with what timing windows and
netem shape — as a PLAIN scenario-spec dict (the benchmark/scenarios
JSON schema).  benchmark/fault_bench.py replays generated scenarios
through the same three-verdict engine as the hand-written ones, and
dumps each as a normal JSON spec first, so any fuzz catch is replayable
byte-for-byte with ``--scenario`` and no fuzzer in the loop.

Design constraints that keep every draw judgeable:

- **Detection stays derivable.**  Each behavior carries its detection
  contract — the health rule it must light up plus the env/parameter
  knobs that make the rule's timing deterministic on a shared-core host
  (the same values the hand-written scenarios pinned).  ``expect.rules``
  is the union over the drawn behaviors, so the detection verdict is
  never vacuous.
- **BFT bound by construction.**  All byzantine behaviors land on ONE
  node, and a drawn crash hits that same node — the faulted-node union
  is always 1 ≤ f, whatever the seed (parse_scenario re-checks anyway).
- **WAN noise is noise.**  The optional netem shape is mild (it has no
  expected rule of its own); fault arms tolerate extra firings, and the
  control arm strips it, so the shape can randomize freely.

The generator never touches the process RNG: everything flows from one
``random.Random(seed)``, so ``generate(s) == generate(s)`` exactly.
"""

from __future__ import annotations

import random
from typing import Dict, List, Sequence, Tuple

# Committee sizes a draw may pick.  4/7/10 are what the socketed
# one-host runner can carry (it passes a pruned pool); 20 is the
# committee-at-scale point the deterministic simulation harness
# (narwhal_tpu/sim, benchmark/sim_bench.py) exists to explore.
SIZES: Tuple[int, ...] = (4, 7, 10, 20)

# (behavior, expected rules, env knobs, parameter overrides) — the
# detection contract of each plane, mirrored from the hand-written
# scenarios that validated these values end-to-end.
_PRIMARY_POOL: List[Tuple[str, List[str], Dict[str, str], Dict[str, int]]] = [
    ("equivocate", ["equivocation"], {}, {}),
    ("wrong_key", ["invalid_signature"], {}, {}),
    (
        "withhold_votes",
        ["peer_vote_silence"],
        {"NARWHAL_HEALTH_VOTE_SILENCE_WINDOW_S": "6"},
        {},
    ),
    # gc_depth 8 so the replayed certificates fall behind the horizon
    # within the window (byz_replay_stale.json's values).
    ("replay_stale", ["stale_replay"], {}, {"gc_depth": 8}),
]

_WORKER_POOL: List[Tuple[str, List[str], Dict[str, str], Dict[str, int]]] = [
    # A raised retry delay + lowered age threshold makes the starvation
    # window unambiguous before escalation recovers the bytes.
    (
        "withhold_batches",
        ["batch_withholding"],
        {"NARWHAL_HEALTH_SYNC_AGE_S": "3"},
        {"sync_retry_delay": 6_000},
    ),
    (
        "garbage_batches",
        ["garbage_batches"],
        {"NARWHAL_HEALTH_SYNC_AGE_S": "3"},
        {"sync_retry_delay": 4_000},
    ),
    ("sync_flood", ["helper_abuse"], {}, {}),
]


def generate(seed: int, sizes: Sequence[int] = SIZES) -> dict:
    """One seeded scenario-spec dict (see module docstring).  Pass the
    result to ``narwhal_tpu.faults.spec.parse_scenario`` (fault_bench
    and sim_bench do) — the generator stays within the schema's bounds,
    and parsing re-validates every invariant regardless.

    ``sizes`` is the committee-size pool the draw picks from (the
    socketed runner prunes it to what one host can carry; the sim
    harness uses the full pool).  All faults still land on ONE node, so
    the faulted-node union is 1 ≤ f at every size in the pool."""
    rng = random.Random(seed)
    nodes = rng.choice(list(sizes))

    env: Dict[str, str] = {}
    parameters: Dict[str, int] = {}
    rules: set = set()
    behaviors: List[str] = []

    primary = rng.random() < 0.7 and rng.choice(_PRIMARY_POOL)
    worker = rng.random() < 0.7 and rng.choice(_WORKER_POOL)
    if not primary and not worker:
        # Every scenario needs at least one behavior; re-draw the plane
        # the dice liked least (still pure-seed-derived).
        worker = rng.choice(_WORKER_POOL)
    for pick in (primary, worker):
        if not pick:
            continue
        behavior, expect, env_knobs, param_knobs = pick
        behaviors.append(behavior)
        rules.update(expect)
        env.update(env_knobs)
        parameters.update(param_knobs)
    # Behavior masking: wrong_key makes every header of the adversary
    # invalid, so honest peers never accept the headers that would
    # reference its batches — and without accepted references nobody
    # requests the bytes, which is the ONLY evidence path the
    # batch-availability rules observe.  The worker behavior still runs
    # (stress), but its rule leaves the detection contract: expecting it
    # would make the verdict fail for a reason that is protocol
    # semantics, not a detection gap (found by the sim sweep at N=10).
    if "wrong_key" in behaviors:
        rules.discard("batch_withholding")
        rules.discard("garbage_batches")

    # Duration draw: scenario length varies per seed; replay_stale needs
    # the extra tail for the GC horizon to pass the replayed rounds.
    duration = rng.choice([25, 30, 35])
    if "replay_stale" in behaviors:
        duration = max(duration, 35)
        if nodes > 10:
            # Staleness evidence needs the committee's COMMITTED round to
            # clear gc_depth (8) past the replayed early rounds, and the
            # sim stretches large-committee cadence to ~5 s rounds — at
            # 35 s the horizon never moves and the rule provably cannot
            # fire (sweep points 7017/7036 at N=20 sat at committed
            # round 2 all run).  ~16 rounds is enough with margin.
            duration = max(duration, 80)
    byz_node = rng.randrange(nodes)
    byz_entry: dict = {"node": byz_node, "behaviors": behaviors}
    if "replay_stale" in behaviors:
        byz_entry["replay_interval_ms"] = 100
    if "sync_flood" in behaviors:
        byz_entry["flood_interval_ms"] = rng.choice([100, 200, 400])

    obj: dict = {
        "name": f"fuzz_{seed}",
        "nodes": nodes,
        "workers": 1,
        "rate": rng.choice([1_500, 2_000, 2_500]),
        "tx_size": 512,
        "duration": duration,
        "seed": seed,
        "byzantine": [byz_entry],
    }
    if parameters:
        obj["parameters"] = parameters

    # Optional crash/restart of the SAME node (union stays 1 ≤ f): the
    # adversary has been active since boot, so its detections fire well
    # before the kill; the restart respawns it with the same plan.
    if rng.random() < 0.35:
        at_s = rng.randrange(14, 19)
        restart_at_s = at_s + rng.randrange(5, 9)
        obj["duration"] = max(obj["duration"], restart_at_s + 23)
        obj["crash"] = [
            {"node": byz_node, "at_s": at_s, "restart_at_s": restart_at_s}
        ]
        env["NARWHAL_NET_BACKOFF_MAX_S"] = "2"
        rules.add("peer_unreachable")

    # Optional mild WAN shape — pure noise, no expected rule.
    if rng.random() < 0.5:
        obj["wan"] = {
            "latency_ms": rng.randrange(10, 41),
            "jitter_ms": rng.randrange(0, 11),
            "loss": rng.choice([0.0, 0.02, 0.05]),
        }

    if env:
        obj["env"] = env
    obj["expect"] = {"rules": sorted(rules)}
    obj["progress_wait"] = 45
    return obj
