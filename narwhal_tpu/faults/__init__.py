"""Fault injection: Byzantine primaries, crash/restart, WAN emulation.

Three planes, all declarative and seeded (``NARWHAL_FAULT_SEED``):

- :mod:`narwhal_tpu.faults.spec` — the scenario schema
  (benchmark/scenarios/*.json → :class:`FaultScenario`);
- :mod:`narwhal_tpu.faults.netem` — per-peer-pair latency/jitter/loss and
  time-windowed partitions injected at the ``network/`` seam;
- :mod:`narwhal_tpu.faults.byzantine` — ``ByzantineCore`` /
  ``ByzantineProposer`` (equivocation, rogue-key signatures, vote
  withholding, stale-certificate replay), wired by ``node --fault-plan``;
- :mod:`narwhal_tpu.faults.byzantine_worker` — the worker-plane
  availability attacks (batch withholding, garbage serving, sync
  flooding), wired by the same ``--fault-plan`` on the worker role;
- :mod:`narwhal_tpu.faults.fuzz` — seeded scenario generation: one seed
  → one replayable scenario-spec dict, replayed by fault_bench's
  ``--fuzz-seed``.

This ``__init__`` deliberately imports only the leaf modules with no
in-package dependencies: ``network/`` imports :mod:`netem` for its hooks,
and :mod:`byzantine` imports ``primary/`` — eagerly importing it here
would close an import cycle.  Import ``narwhal_tpu.faults.byzantine``
directly where needed.
"""

from . import netem  # noqa: F401
from .spec import (  # noqa: F401
    BYZANTINE_BEHAVIORS,
    FaultScenario,
    SpecError,
    load_scenario,
    parse_scenario,
)
