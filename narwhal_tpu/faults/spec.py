"""Declarative fault-scenario specs (the input to benchmark/fault_bench.py).

A scenario is one JSON file naming a committee shape, a load profile, and
up to three fault planes:

- ``byzantine``: per-node behavior lists drawn from
  :data:`BYZANTINE_BEHAVIORS` — primary-plane behaviors executed
  in-process by ``narwhal_tpu.faults.byzantine.ByzantineCore``/
  ``ByzantineProposer``, worker-plane behaviors (batch withholding,
  garbage serving, sync flooding) by
  ``narwhal_tpu.faults.byzantine_worker``;
- ``crash``: kill an authority's processes mid-run (SIGKILL — the point is
  to exercise the torn-file/far-frontier restore paths) and restart them
  from their on-disk store + consensus checkpoint while the committee is
  under load;
- ``wan``: latency/jitter/loss defaults, per-directed-pair overrides, and
  time-windowed partitions, compiled by the runner into the per-node
  config ``narwhal_tpu.faults.netem`` loads inside each process.

Fault planes COMPOSE: one scenario may put different planes on distinct
nodes (a Byzantine worker on one authority while another crashes, an
equivocating primary under committee-wide WAN loss, ...) — the parser
enforces the BFT bound over the UNION of byzantine + crashed +
partitioned nodes so a composition can never silently cost quorum.

``expect.rules`` names the HealthMonitor rules the scenario must light up
(the detection verdict); the safety and liveness verdicts are computed
mechanically from the consensus audit logs and the scraped timeline and
need no per-scenario configuration.

Everything randomized (netem jitter/loss draws, Byzantine peer-set
splits) derives from ``seed``; the ``NARWHAL_FAULT_SEED`` env var
overrides the file so CI can re-roll a flaky draw without editing the
scenario.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..utils.env import env_raw

PRIMARY_BEHAVIORS = (
    "equivocate",       # two conflicting headers per round, disjoint peer sets
    "wrong_key",        # headers broadcast with a rogue-key signature
    "withhold_votes",   # never vote for targeted authors' headers
    "replay_stale",     # re-broadcast own old certificates forever
)

# Worker-plane behaviors (narwhal_tpu.faults.byzantine_worker): the
# payload-availability attacks.  A behavior list may mix primary and
# worker behaviors — the runner hands the same plan to the authority's
# primary AND its workers and each plane acts only on its own set.
WORKER_BEHAVIORS = (
    "withhold_batches",  # certify via the ACK quorum, never serve the bytes
    "garbage_batches",   # serve corrupted/oversized junk to sync requests
    "sync_flood",        # repeated max-size BatchRequests (amplification)
)

BYZANTINE_BEHAVIORS = PRIMARY_BEHAVIORS + WORKER_BEHAVIORS


class SpecError(ValueError):
    pass


@dataclass
class ByzantineSpec:
    node: int                       # authority index (keypair order)
    behaviors: List[str]
    # withhold_votes: authority indices to starve; empty = every other
    # authority (resolved to base64 public keys by the runner).
    targets: List[int] = field(default_factory=list)
    replay_interval_ms: int = 250
    # sync_flood: cadence of the flood requests.
    flood_interval_ms: int = 200
    # garbage_batches: size of the junk batch served to sync requests.
    # The default sits well above the worker's default accepted-batch
    # ceiling (2 x batch_size + 64 KiB; see worker.max_batch_bytes) so
    # the junk is REJECTED and counted, not hashed and persisted.
    garbage_bytes: int = 2_200_000


@dataclass
class CrashSpec:
    node: int
    at_s: float                     # SIGKILL (primary + workers) at this offset
    restart_at_s: Optional[float]   # respawn offset; None = stays dead


@dataclass
class WanPairSpec:
    src: int                        # authority index whose OUTBOUND traffic
    dst: int                        # toward this authority is shaped
    latency_ms: float = 0.0
    jitter_ms: float = 0.0
    loss: float = 0.0


@dataclass
class PartitionSpec:
    group: List[int]                # isolated authority indices
    from_s: float
    until_s: Optional[float]        # None = never heals


@dataclass
class WanSpec:
    # Committee-wide defaults applied to every directed pair.
    latency_ms: float = 0.0
    jitter_ms: float = 0.0
    loss: float = 0.0
    pairs: List[WanPairSpec] = field(default_factory=list)
    partitions: List[PartitionSpec] = field(default_factory=list)


@dataclass
class FaultScenario:
    name: str
    nodes: int = 4
    workers: int = 1
    rate: int = 2_000
    tx_size: int = 512
    duration: int = 20
    seed: int = 0
    # Parameter overrides forwarded to narwhal_tpu.config.Parameters.
    parameters: Dict[str, int] = field(default_factory=dict)
    byzantine: List[ByzantineSpec] = field(default_factory=list)
    crash: List[CrashSpec] = field(default_factory=list)
    wan: Optional[WanSpec] = None
    # Extra environment for every node process — per-scenario health
    # thresholds (NARWHAL_HEALTH_*) and network knobs
    # (NARWHAL_NET_BACKOFF_MAX_S).  Carried into the control arm too, so
    # lowering a detection threshold keeps the control honest.
    env: Dict[str, str] = field(default_factory=dict)
    # Detection verdict: every named rule must FIRE (on >=1 node) in the
    # fault arm; the control arm must fire no rule at all.
    expect_rules: List[str] = field(default_factory=list)
    # Extra seconds the liveness gate may stretch waiting for payload
    # commits (matches local_bench's progress_wait semantics).
    progress_wait: float = 30.0

    # -- derived -------------------------------------------------------------

    def byzantine_nodes(self) -> List[int]:
        return sorted({b.node for b in self.byzantine})

    def honest_nodes(self) -> List[int]:
        byz = set(self.byzantine_nodes())
        return [i for i in range(self.nodes) if i not in byz]

    def is_clean(self) -> bool:
        return not (self.byzantine or self.crash or self.wan)

    def control_arm(self) -> "FaultScenario":
        """The same committee/load with every fault plane stripped — the
        arm whose timeline must show ZERO firing rules."""
        return FaultScenario(
            name=f"{self.name}.control",
            nodes=self.nodes,
            workers=self.workers,
            rate=self.rate,
            tx_size=self.tx_size,
            duration=self.duration,
            seed=self.seed,
            parameters=dict(self.parameters),
            env=dict(self.env),
            progress_wait=self.progress_wait,
        )


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise SpecError(msg)


def load_scenario(path: str, env: Optional[Dict[str, str]] = None) -> FaultScenario:
    with open(path) as f:
        return parse_scenario(json.load(f), env=env)


def parse_scenario(
    obj: dict, env: Optional[Dict[str, str]] = None
) -> FaultScenario:
    env = os.environ if env is None else env
    _require(isinstance(obj, dict), "scenario must be a JSON object")
    _require("name" in obj, "scenario needs a name")
    known = {
        "name", "nodes", "workers", "rate", "tx_size", "duration", "seed",
        "parameters", "byzantine", "crash", "wan", "expect", "env",
        "progress_wait",
    }
    unknown = set(obj) - known
    _require(not unknown, f"unknown scenario field(s): {sorted(unknown)}")

    nodes = int(obj.get("nodes", 4))
    # Up to 10 is what the socketed one-host runner can carry; the
    # deterministic simulation harness (narwhal_tpu/sim) runs the same
    # specs at N=20/50 on one event loop.
    _require(4 <= nodes <= 50, "nodes must be in [4, 50]")

    # The override must fail LOUD on garbage (unlike the warn-and-default
    # registry accessors): the fault suite's premise is byte-for-byte
    # replayability from a seed, and a silently-ignored override would
    # run a different stochastic draw than the one the operator asked
    # to reproduce while the artifact claims otherwise.
    raw_seed = env_raw("NARWHAL_FAULT_SEED", env=env)
    if raw_seed is not None:
        try:
            seed = int(raw_seed)
        except ValueError:
            raise SpecError(
                f"NARWHAL_FAULT_SEED={raw_seed!r} is not an integer"
            ) from None
    else:
        seed = int(obj.get("seed", 0))

    byz = []
    for b in obj.get("byzantine", []):
        behaviors = list(b.get("behaviors", []))
        _require(behaviors, "byzantine entry needs behaviors")
        node_dup = int(b["node"])
        _require(
            node_dup not in {x.node for x in byz},
            f"duplicate byzantine entry for node {node_dup} (one entry "
            "per node — the runner writes ONE plan file per authority, "
            "so a second entry would silently replace the first; list "
            "all of a node's behaviors in one entry)",
        )
        for beh in behaviors:
            _require(
                beh in BYZANTINE_BEHAVIORS,
                f"unknown byzantine behavior {beh!r} "
                f"(known: {list(BYZANTINE_BEHAVIORS)})",
            )
        node = int(b["node"])
        _require(0 <= node < nodes, f"byzantine node {node} out of range")
        targets = [int(t) for t in b.get("targets", [])]
        for t in targets:
            _require(0 <= t < nodes and t != node, f"bad withhold target {t}")
        byz.append(
            ByzantineSpec(
                node=node,
                behaviors=behaviors,
                targets=targets,
                replay_interval_ms=int(b.get("replay_interval_ms", 250)),
                flood_interval_ms=int(b.get("flood_interval_ms", 200)),
                garbage_bytes=int(b.get("garbage_bytes", 2_200_000)),
            )
        )
    # One node's Helper can refuse sync requests or poison them, not both
    # — the two behaviors own the same serve decision.
    for b in byz:
        _require(
            not {"withhold_batches", "garbage_batches"} <= set(b.behaviors),
            f"node {b.node}: withhold_batches and garbage_batches "
            "conflict (both decide what the Helper serves)",
        )
    # Faults must stay within BFT tolerance or the verdicts are vacuous.
    f_tol = (nodes - 1) // 3
    _require(
        len({b.node for b in byz}) <= f_tol,
        f"{len(byz)} byzantine node(s) exceeds f={f_tol} for n={nodes}",
    )

    crash = []
    for c in obj.get("crash", []):
        node = int(c["node"])
        _require(0 <= node < nodes, f"crash node {node} out of range")
        at_s = float(c["at_s"])
        restart = c.get("restart_at_s")
        if restart is not None:
            restart = float(restart)
            _require(restart > at_s, "restart_at_s must come after at_s")
        crash.append(CrashSpec(node=node, at_s=at_s, restart_at_s=restart))
    _require(
        len({c.node for c in crash} | {b.node for b in byz}) <= f_tol,
        f"crashed+byzantine nodes exceed f={f_tol} for n={nodes}",
    )

    wan = None
    if "wan" in obj and obj["wan"]:
        w = obj["wan"]
        pairs = []
        for p in w.get("pairs", []):
            src, dst = int(p["src"]), int(p["dst"])
            _require(
                0 <= src < nodes and 0 <= dst < nodes and src != dst,
                f"bad wan pair {src}->{dst}",
            )
            pairs.append(
                WanPairSpec(
                    src=src,
                    dst=dst,
                    latency_ms=float(p.get("latency_ms", 0.0)),
                    jitter_ms=float(p.get("jitter_ms", 0.0)),
                    loss=float(p.get("loss", 0.0)),
                )
            )
        partitions = []
        for p in w.get("partitions", []):
            group = sorted({int(g) for g in p["group"]})
            _require(group, "partition needs a non-empty group")
            for g in group:
                _require(0 <= g < nodes, f"partition node {g} out of range")
            _require(
                len(group) <= f_tol,
                f"partitioned group of {len(group)} exceeds f={f_tol}",
            )
            # Fault planes compose: a node that is byzantine or crashed
            # WHILE another is partitioned away counts against the same
            # f — otherwise the committee silently loses quorum and the
            # verdicts are vacuous.
            _require(
                len(
                    set(group)
                    | {c.node for c in crash}
                    | {b.node for b in byz}
                )
                <= f_tol,
                f"partitioned+crashed+byzantine nodes exceed f={f_tol} "
                f"for n={nodes}",
            )
            until = p.get("until_s")
            partitions.append(
                PartitionSpec(
                    group=group,
                    from_s=float(p["from_s"]),
                    until_s=None if until is None else float(until),
                )
            )
        loss = float(w.get("loss", 0.0))
        _require(0.0 <= loss < 1.0, "wan.loss must be in [0, 1)")
        wan = WanSpec(
            latency_ms=float(w.get("latency_ms", 0.0)),
            jitter_ms=float(w.get("jitter_ms", 0.0)),
            loss=loss,
            pairs=pairs,
            partitions=partitions,
        )

    # Every timed fault must land INSIDE the declared measurement window:
    # an offset past `duration` would silently stretch the run (the event
    # loop sleeps until the offset before acting) and push the liveness
    # settle point outside the scraped window, hollowing out the verdict.
    duration = int(obj.get("duration", 20))
    for c in crash:
        _require(
            c.at_s < duration,
            f"crash at_s={c.at_s} is at/after duration={duration}",
        )
        if c.restart_at_s is not None:
            _require(
                c.restart_at_s < duration,
                f"restart_at_s={c.restart_at_s} is at/after "
                f"duration={duration}",
            )
    if wan is not None:
        for p in wan.partitions:
            _require(
                p.from_s < duration,
                f"partition from_s={p.from_s} is at/after "
                f"duration={duration}",
            )
            if p.until_s is not None:
                _require(
                    p.until_s <= duration,
                    f"partition until_s={p.until_s} is after "
                    f"duration={duration}",
                )

    expect = obj.get("expect", {}) or {}
    expect_rules = list(expect.get("rules", []))

    env_extra = {}
    for k, v in (obj.get("env", {}) or {}).items():
        _require(
            isinstance(k, str) and isinstance(v, (str, int, float)),
            f"env entries must be string-keyed scalars: {k!r}",
        )
        env_extra[k] = str(v)

    return FaultScenario(
        name=str(obj["name"]),
        nodes=nodes,
        workers=int(obj.get("workers", 1)),
        rate=int(obj.get("rate", 2_000)),
        tx_size=int(obj.get("tx_size", 512)),
        duration=duration,
        seed=seed,
        parameters=dict(obj.get("parameters", {})),
        byzantine=byz,
        crash=crash,
        wan=wan,
        env=env_extra,
        expect_rules=expect_rules,
        progress_wait=float(obj.get("progress_wait", 30.0)),
    )
