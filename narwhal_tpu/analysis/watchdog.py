"""Event-loop stall watchdog — the runtime half of the invariant suite.

The static ``no-blocking-in-async`` rule bans the blocking-call shapes we
know about; this watchdog measures the ones we don't.  The paper's whole
latency story rides on the primary's single asyncio loop never stalling
(the round period is pure critical path — r10 attribution), so "the loop
never blocks" must be a MEASURED property, not an inferred one.

Mechanism (opt-in via ``NARWHAL_LOOP_WATCHDOG_MS``):

- a heartbeat task on the watched loop stamps a monotonic timestamp
  every ``interval`` seconds.  When a beat arrives LATE, the loop was
  held by something — the overshoot beyond the scheduled interval is the
  stall length, observed into the ``runtime.loop_stall_seconds``
  histogram (plus the ``runtime.loop_stalls`` counter);
- a daemon thread watches the same timestamp from outside.  The moment
  the gap crosses the threshold it captures the LOOP thread's current
  stack via ``sys._current_frames()`` — i.e. a stack excerpt from
  *inside* the stall, naming the blocking callee — logs it, and parks it
  in the ``runtime.loop_stall_last`` snapshot detail.  The loop itself
  cannot log while wedged; the thread can (same stance as the
  ``NARWHAL_FAULTHANDLER_S`` C-level dumper, but scoped, rate-limited
  and joined to the metrics plane);
- ``loop.slow_callback_duration`` is aligned to the threshold so asyncio
  debug mode (when enabled) agrees with the watchdog about what "slow"
  means.

Cost when enabled: one trivial task wakeup per interval on the loop plus
one daemon thread — cheap enough for a bench smoke arm, still opt-in for
production defaults.
"""

from __future__ import annotations

import asyncio
import logging
import sys
import threading
import time
import traceback
from typing import Optional

from .. import metrics
from ..utils.env import env_int
from ..utils.tasks import spawn

log = logging.getLogger("narwhal.watchdog")

_STACK_LIMIT = 12  # frames kept in the excerpt


class LoopWatchdog:
    """Watch one event loop for callbacks that hold it past ``threshold_s``."""

    def __init__(self, threshold_s: float, interval_s: Optional[float] = None):
        self.threshold_s = threshold_s
        # Beat fast enough that the measured overshoot approximates the
        # true stall length, slow enough to stay off the hot path.
        self.interval_s = (
            interval_s if interval_s is not None else max(threshold_s / 4, 0.005)
        )
        self._last_beat = time.monotonic()
        self._stop = threading.Event()
        self._loop_thread_id: Optional[int] = None
        self._task: Optional[asyncio.Task] = None
        self._thread: Optional[threading.Thread] = None
        self._last_stall: dict = {}
        self._stack_captured = False
        self._m_stalls = metrics.counter("runtime.loop_stalls")
        self._m_stall_s = metrics.histogram("runtime.loop_stall_seconds")
        metrics.detail_fn("runtime.loop_stall_last", lambda: self._last_stall)

    def start(self) -> "LoopWatchdog":
        loop = asyncio.get_running_loop()
        # Align asyncio's own slow-callback notion (used when loop debug
        # mode is on) with the watchdog threshold.
        loop.slow_callback_duration = self.threshold_s
        self._loop_thread_id = threading.get_ident()
        self._last_beat = time.monotonic()
        self._task = spawn(self._beat(), name="loop-watchdog-beat")
        self._thread = threading.Thread(
            target=self._watch, name="loop-watchdog", daemon=True
        )
        self._thread.start()
        log.info(
            "Loop-stall watchdog armed: threshold %.0f ms, beat %.0f ms",
            self.threshold_s * 1000, self.interval_s * 1000,
        )
        return self

    async def shutdown(self) -> None:
        self._stop.set()
        if self._task is not None:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval_s + 1)

    # -- loop side: measure ---------------------------------------------------

    async def _beat(self) -> None:
        while True:
            self._last_beat = time.monotonic()
            self._stack_captured = False
            await asyncio.sleep(self.interval_s)
            # The sleep was scheduled for interval_s; anything beyond it
            # is time some callback (or a CPU-bound stretch of one) held
            # the loop.
            overshoot = time.monotonic() - self._last_beat - self.interval_s
            if overshoot >= self.threshold_s:
                self._m_stalls.inc()
                self._m_stall_s.observe(overshoot)
                self._last_stall["stall_s"] = round(overshoot, 4)
                self._last_stall["ts"] = time.time()
                # Stalls are flight-recorder landmarks: the ring shows
                # what the committee was doing around the freeze.
                metrics.flight_event(
                    "loop_stall", stall_s=round(overshoot, 4)
                )

    # -- thread side: name the culprit ----------------------------------------

    def _watch(self) -> None:
        while not self._stop.wait(self.interval_s):
            gap = time.monotonic() - self._last_beat
            if gap - self.interval_s < self.threshold_s or self._stack_captured:
                continue
            # The loop is stalled RIGHT NOW: its thread's stack names the
            # blocking callee. One capture per stall (flag reset by the
            # next beat), so a long wedge logs once, not per tick.
            self._stack_captured = True
            frame = sys._current_frames().get(self._loop_thread_id)
            if frame is None:
                continue
            excerpt = "".join(
                traceback.format_stack(frame, limit=_STACK_LIMIT)
            )
            self._last_stall["stack"] = excerpt
            log.warning(
                "Event loop stalled > %.0f ms; loop thread stack:\n%s",
                self.threshold_s * 1000, excerpt,
            )


def install_from_env() -> Optional[LoopWatchdog]:
    """Arm the watchdog on the running loop when
    ``NARWHAL_LOOP_WATCHDOG_MS`` > 0 (node/main.py calls this once per
    process); returns the armed instance or None."""
    ms = env_int("NARWHAL_LOOP_WATCHDOG_MS")
    if not ms or ms <= 0:
        return None
    return LoopWatchdog(ms / 1000.0).start()
