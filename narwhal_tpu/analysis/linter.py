"""Framework of the invariant linter: file loading, pragmas, findings.

The linter is codebase-specific by design — each rule in ``rules.py``
encodes an invariant that a past PR rediscovered the hard way (blocking
work on the primary's event loop, silently-GC'd tasks, drifting string
registries).  This module owns everything rule-agnostic:

- **Project loading.**  Python files under ``narwhal_tpu/`` and
  ``benchmark/`` are parsed to ASTs; ``README.md``, ``Makefile``,
  ``tests/*.py`` and the root bench scripts ride along as raw text for
  the cross-registry rules (env-table drift, declared-but-unread
  detection).  An ``overlay`` maps relative paths to replacement
  sources, which is how the test suite proves each rule fires: mutate
  one file in memory, re-run, assert the finding — no tree copying.

- **Pragmas.**  ``# lint: allow-<rule>(reason)`` on any line a flagged
  node spans suppresses that rule's finding there.  The reason is
  mandatory: an empty one is itself a finding, and so is a pragma name
  no rule owns (a typo'd pragma that silently suppressed nothing would
  be worse than no pragma at all).

- **Findings.**  Plain (rule, path, line, message) records, sorted for
  stable output; the CLI renders them human-readable and as a JSON
  report for the CI artifact.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import asdict, dataclass
from typing import Dict, Iterable, List, Optional

PRAGMA_RE = re.compile(r"#\s*lint:\s*allow-([a-z][a-z0-9-]*)\(([^)]*)\)")

# Parsed-Python scope (AST rules) and raw-text scope (registry rules).
PY_DIRS = ("narwhal_tpu", "benchmark")
TEXT_GLOBS = (
    "README.md",
    "Makefile",
    "tests",
    ".github/workflows",
    "bench.py",
    "bench_consensus.py",
    "bench_cadence.py",
    "bench_crypto.py",
    "__graft_entry__.py",
)


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def as_dict(self) -> dict:
        return asdict(self)


class SourceFile:
    """One parsed Python source: AST plus the per-line pragma map."""

    def __init__(self, rel: str, text: str):
        self.rel = rel
        self.text = text
        self.syntax_error: Optional[SyntaxError] = None
        try:
            self.tree: Optional[ast.AST] = ast.parse(text)
        except SyntaxError as e:
            self.tree = None
            self.syntax_error = e
        # line -> {pragma-name: reason}
        self.pragmas: Dict[int, Dict[str, str]] = {}
        for lineno, line in enumerate(text.splitlines(), 1):
            for m in PRAGMA_RE.finditer(line):
                self.pragmas.setdefault(lineno, {})[m.group(1)] = (
                    m.group(2).strip()
                )

    def pragma_reason(self, name: str, node: ast.AST) -> Optional[str]:
        """The reason of an ``allow-<name>`` pragma on any line the node
        spans, or on the line directly above it (own-line pragmas for
        reasons too long to share the statement's line).  None = no
        pragma; "" = pragma without a reason, which does NOT suppress."""
        first = getattr(node, "lineno", None)
        if first is None:
            return None
        last = getattr(node, "end_lineno", None) or first
        for ln in range(first - 1, last + 1):
            d = self.pragmas.get(ln)
            if d is not None and name in d:
                return d[name]
        return None

    def suppressed(self, pragma_name: str, node: ast.AST) -> bool:
        reason = self.pragma_reason(pragma_name, node)
        return reason is not None and reason != ""


class Project:
    def __init__(self, root: str):
        self.root = root
        self.files: Dict[str, SourceFile] = {}  # rel path -> parsed source
        self.texts: Dict[str, str] = {}  # rel path -> raw text (non-AST scope)

    def file(self, rel: str) -> Optional[SourceFile]:
        return self.files.get(rel)


def _iter_py(root: str, sub: str) -> Iterable[str]:
    base = os.path.join(root, sub)
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.relpath(os.path.join(dirpath, fn), root)


def load_project(
    root: str, overlay: Optional[Dict[str, str]] = None
) -> Project:
    """Parse the tree (``overlay`` entries replace on-disk content, or
    add files that don't exist on disk — keys are root-relative)."""
    overlay = dict(overlay or {})
    project = Project(root)

    def read(rel: str) -> str:
        if rel in overlay:
            return overlay.pop(rel)
        with open(os.path.join(root, rel), "r", encoding="utf-8") as f:
            return f.read()

    for sub in PY_DIRS:
        if not os.path.isdir(os.path.join(root, sub)):
            continue
        for rel in _iter_py(root, sub):
            project.files[rel] = SourceFile(rel, read(rel))

    for entry in TEXT_GLOBS:
        full = os.path.join(root, entry)
        if os.path.isdir(full):
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    rel = os.path.relpath(os.path.join(dirpath, fn), root)
                    if fn.endswith((".py", ".yml", ".yaml", ".md")):
                        project.texts[rel] = read(rel)
        elif os.path.isfile(full):
            project.texts[entry] = read(entry)

    # Overlay leftovers are new files (mutation tests injecting a module).
    for rel, text in overlay.items():
        if rel.endswith(".py") and rel.startswith(PY_DIRS):
            project.files[rel] = SourceFile(rel, text)
        else:
            project.texts[rel] = text
    return project


def pragma_findings(project: Project, known_pragmas: Iterable[str]) -> List[Finding]:
    """Framework-level checks on the pragmas themselves."""
    known = set(known_pragmas)
    out: List[Finding] = []
    for sf in project.files.values():
        for lineno, entries in sorted(sf.pragmas.items()):
            for name, reason in entries.items():
                if name not in known:
                    out.append(Finding(
                        "pragma", sf.rel, lineno,
                        f"unknown pragma allow-{name} (known: "
                        f"{', '.join(sorted(known))})",
                    ))
                elif not reason:
                    out.append(Finding(
                        "pragma", sf.rel, lineno,
                        f"pragma allow-{name} must carry a reason: "
                        f"# lint: allow-{name}(why this is safe)",
                    ))
    return out


def run_lint(
    root: str, overlay: Optional[Dict[str, str]] = None
) -> List[Finding]:
    """Load the tree and run every rule; the CLI and the test suite both
    enter here."""
    from . import rules  # late import: rules import helpers from here

    project = load_project(root, overlay)
    findings: List[Finding] = []
    for sf in project.files.values():
        if sf.syntax_error is not None:
            findings.append(Finding(
                "syntax", sf.rel, sf.syntax_error.lineno or 0,
                f"syntax error: {sf.syntax_error.msg}",
            ))
    findings.extend(pragma_findings(project, rules.PRAGMA_NAMES))
    for rule_fn in rules.ALL_RULES:
        findings.extend(rule_fn(project))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings
