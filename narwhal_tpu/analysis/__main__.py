"""CLI of the invariant linter.

    python -m narwhal_tpu.analysis [--root DIR] [--report out.json]
    python -m narwhal_tpu.analysis --env-table

Exit status: 0 = clean tree, 1 = findings (CI gates on this), 2 = bad
invocation.  ``--report`` additionally writes the findings as JSON for
the CI artifact upload.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .linter import run_lint


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="narwhal-lint")
    ap.add_argument(
        "--root",
        default=None,
        help="Repository root (default: auto-detected from this package)",
    )
    ap.add_argument(
        "--report",
        default=None,
        help="Also write findings as a JSON report to this path",
    )
    ap.add_argument(
        "--env-table",
        action="store_true",
        help="Print the generated README env-var table and exit",
    )
    args = ap.parse_args(argv)

    if args.env_table:
        from ..utils.env import TABLE_BEGIN, TABLE_END, render_table

        print(TABLE_BEGIN)
        print(render_table())
        print(TABLE_END)
        return 0

    root = args.root or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    if not os.path.isdir(os.path.join(root, "narwhal_tpu")):
        print(f"--root {root!r} does not contain narwhal_tpu/", file=sys.stderr)
        return 2

    findings = run_lint(root)
    if args.report:
        os.makedirs(os.path.dirname(args.report) or ".", exist_ok=True)
        with open(args.report, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "root": root,
                    "findings": [x.as_dict() for x in findings],
                    "count": len(findings),
                },
                f,
                indent=1,
            )
    for finding in findings:
        print(finding.render())
    if findings:
        print(
            f"\nnarwhal-lint: {len(findings)} finding(s). Fix them or "
            "suppress per-site with `# lint: allow-<rule>(reason)` "
            "(see README 'Static analysis').",
            file=sys.stderr,
        )
        return 1
    print("narwhal-lint: clean")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # stdout piped into a pager/head that closed early; not an error.
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
