"""The invariant rules.  Each encodes a bug shape a past PR paid for.

| rule | pragma | invariant |
|---|---|---|
| no-blocking-in-async | allow-blocking | no blocking call shapes inside ``async def`` under narwhal_tpu/ |
| task-retention | allow-unretained-task | no bare ``create_task``/``ensure_future`` statements (use utils.tasks.spawn) |
| wire-type-coverage | allow-wire-type | every sender call labels its frame; labels ⊆ classifier maps ⊆ labels |
| metric-name-drift | allow-metric-name | every metric name a consumer references is actually emitted |
| env-var-registry | allow-env | every NARWHAL_* literal is declared; reads route through utils/env.py; no dead declarations; README table fresh |
| interleave-window | allow-interleave | no self-attr read→yield→write window on state another task root writes (interleave.py) |
| interleave-iteration | allow-interleave | no direct iteration over shared state spanning a yield point (interleave.py) |

Rules are pure functions ``Project -> Iterable[Finding]`` so the test
suite can run them against in-memory mutations.  Suppression is per-node
via ``# lint: allow-<pragma>(reason)`` on any line the node spans.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .linter import Finding, Project, SourceFile

PRAGMA_NAMES = (
    "blocking",
    "unretained-task",
    "wire-type",
    "metric-name",
    "env",
    "interleave",
)


# -- shared AST helpers -------------------------------------------------------

def _dotted(node: ast.AST) -> Optional[str]:
    """'os.environ.get' for an Attribute chain rooted at a Name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _receiver_name(func: ast.AST) -> Optional[str]:
    """For a method call ``a.b.send(...)``: the identifier the method is
    called ON ('b'); for ``send(...)``: None."""
    if not isinstance(func, ast.Attribute):
        return None
    recv = func.value
    if isinstance(recv, ast.Attribute):
        return recv.attr
    if isinstance(recv, ast.Name):
        return recv.id
    return None


def _str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _fstring_prefix(node: ast.JoinedStr) -> str:
    """Leading literal part of an f-string (empty when it starts with a
    formatted field)."""
    prefix = []
    for part in node.values:
        s = _str_const(part)
        if s is None:
            break
        prefix.append(s)
    return "".join(prefix)


# =============================================================================
# Rule 1: no-blocking-in-async
# =============================================================================
#
# The primary runs its whole protocol on ONE event loop; any synchronous
# stall there IS round-cadence latency (the PR 4 checkpoint-fsync stall:
# one os.fsync per commit burst froze proposer+core for the disk's flush
# latency).  Flagged inside `async def` bodies (nested sync `def`s start
# a new, unchecked scope — they may be executor targets):
#   - time.sleep / os.fsync / os.fdatasync / os.system
#   - builtin open() (sync file I/O)
#   - any subprocess.* call
#   - .sign(...) / .verify(...) method calls — the pure-Python crypto
#     entry points; the deliberate on-loop sites carry pragmas with the
#     measurement that justifies them.

_BLOCKING_DOTTED = {
    "time.sleep": "time.sleep blocks the event loop; use asyncio.sleep",
    "os.fsync": "os.fsync stalls the loop for the disk flush; run it in "
    "an executor (see consensus/tusk.py checkpoint path)",
    "os.fdatasync": "os.fdatasync stalls the loop for the disk flush; "
    "run it in an executor",
    "os.system": "os.system blocks the loop for the child's lifetime",
}
_CRYPTO_ATTRS = {"sign", "verify"}


def rule_no_blocking_in_async(project: Project) -> Iterator[Finding]:
    for sf in project.files.values():
        if not sf.rel.startswith("narwhal_tpu/") or sf.tree is None:
            continue
        yield from _scan_async_blocking(sf)


def _scan_async_blocking(sf: SourceFile) -> Iterator[Finding]:
    findings: List[Finding] = []

    def check_call(call: ast.Call) -> None:
        if sf.suppressed("blocking", call):
            return
        msg = None
        dotted = _dotted(call.func)
        if dotted in _BLOCKING_DOTTED:
            msg = f"{dotted}() in async def: {_BLOCKING_DOTTED[dotted]}"
        elif dotted is not None and dotted.startswith("subprocess."):
            msg = (
                f"{dotted}() in async def blocks the loop for the "
                "child's lifetime; use asyncio.create_subprocess_* or an "
                "executor"
            )
        elif isinstance(call.func, ast.Name) and call.func.id == "open":
            msg = (
                "sync file I/O (open) in async def blocks the loop on "
                "disk latency; move it to a sync helper run in an "
                "executor"
            )
        elif (
            isinstance(call.func, ast.Attribute)
            and call.func.attr in _CRYPTO_ATTRS
        ):
            msg = (
                f".{call.func.attr}() in async def: pure-Python crypto "
                "entry point on the event loop (~ms per op on the "
                "fallback backend); batch it, cache it, or pragma it "
                "with the measurement that makes it acceptable"
            )
        if msg is not None:
            findings.append(
                Finding("no-blocking-in-async", sf.rel, call.lineno, msg)
            )

    class Walker(ast.NodeVisitor):
        def __init__(self) -> None:
            self.stack: List[bool] = []

        def _scoped(self, node: ast.AST, is_async: bool) -> None:
            self.stack.append(is_async)
            self.generic_visit(node)
            self.stack.pop()

        def visit_AsyncFunctionDef(self, node):  # noqa: N802
            self._scoped(node, True)

        def visit_FunctionDef(self, node):  # noqa: N802
            self._scoped(node, False)

        def visit_Lambda(self, node):  # noqa: N802
            self._scoped(node, False)

        def visit_Call(self, node):  # noqa: N802
            if self.stack and self.stack[-1]:
                check_call(node)
            self.generic_visit(node)

    Walker().visit(sf.tree)
    yield from findings


# =============================================================================
# Rule 2: task-retention
# =============================================================================
#
# asyncio keeps only a WEAK reference to tasks: a create_task whose
# result is dropped can be garbage-collected mid-flight, and its
# unhandled exception (if it gets that far) is invisible until loop
# teardown.  A bare `create_task(...)` expression statement is exactly
# that shape.  utils/tasks.py::spawn() is the sanctioned fire-into-
# background call (strong ref + teardown logging); retained names that
# are awaited/cancelled later (queue-get races) stay legal.

_TASK_FNS = {"create_task", "ensure_future"}


def rule_task_retention(project: Project) -> Iterator[Finding]:
    for sf in project.files.values():
        if not sf.rel.startswith("narwhal_tpu/") or sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not (
                isinstance(node, ast.Expr) and isinstance(node.value, ast.Call)
            ):
                continue
            func = node.value.func
            name = (
                func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name)
                else None
            )
            if name not in _TASK_FNS:
                continue
            if sf.suppressed("unretained-task", node):
                continue
            yield Finding(
                "task-retention", sf.rel, node.lineno,
                f"fire-and-forget {name}(): the loop holds only a weak "
                "reference, so the task can be GC'd mid-flight and its "
                "exception is never surfaced — use "
                "narwhal_tpu.utils.tasks.spawn() or retain the handle",
            )


# =============================================================================
# Rule 3: wire-type-coverage
# =============================================================================
#
# The wire-goodput ledger (PR 7) is only as good as its labels: a sender
# call site that forgets msg_type= books frames under "other", and a tag
# absent from the frame-classifier maps books the receiver side under
# "unknown" — either silently degrades the ledger's sender_coverage ≈
# 1.0 gate.  Both directions are enforced: every `<sender|network>.send/
# broadcast/lucky_broadcast(...)` call passes a literal msg_type= that
# exists in a *_FRAME_TYPES map, and every declared frame type has at
# least one sender call site (or the map entry is dead).

_SEND_METHODS = {"send", "broadcast", "lucky_broadcast"}
_SENDER_RECEIVERS = {"sender", "network"}
_CLASSIFIER_FILES = (
    "narwhal_tpu/messages.py",
    "narwhal_tpu/primary/messages.py",
)


def _declared_frame_types(project: Project) -> Dict[str, Tuple[str, int]]:
    """type-name -> (file, line) from the *_FRAME_TYPES dict literals."""
    declared: Dict[str, Tuple[str, int]] = {}
    for rel in _CLASSIFIER_FILES:
        sf = project.file(rel)
        if sf is None or sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Assign):
                continue
            targets = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            if not any(t.endswith("_FRAME_TYPES") for t in targets):
                continue
            if isinstance(node.value, ast.Dict):
                for v in node.value.values:
                    s = _str_const(v)
                    if s is not None and s not in declared:
                        declared[s] = (rel, v.lineno)
    return declared


def rule_wire_type_coverage(project: Project) -> Iterator[Finding]:
    declared = _declared_frame_types(project)
    used: Set[str] = set()
    findings: List[Finding] = []
    for sf in project.files.values():
        if not sf.rel.startswith("narwhal_tpu/") or sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if not (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _SEND_METHODS
                and _receiver_name(node.func) in _SENDER_RECEIVERS
            ):
                continue
            kw = next(
                (k for k in node.keywords if k.arg == "msg_type"), None
            )
            if kw is None:
                if not sf.suppressed("wire-type", node):
                    findings.append(Finding(
                        "wire-type-coverage", sf.rel, node.lineno,
                        f"sender .{node.func.attr}() without msg_type=: "
                        "the frame books into the wire ledger as "
                        "'other', regressing sender coverage",
                    ))
                continue
            tag = _str_const(kw.value)
            if tag is None:
                if not sf.suppressed("wire-type", node):
                    findings.append(Finding(
                        "wire-type-coverage", sf.rel, node.lineno,
                        "msg_type= is not a string literal — the linter "
                        "cannot reconcile it against the frame-"
                        "classifier maps",
                    ))
                continue
            used.add(tag)
            if tag not in declared and not sf.suppressed("wire-type", node):
                findings.append(Finding(
                    "wire-type-coverage", sf.rel, node.lineno,
                    f"msg_type='{tag}' has no entry in any *_FRAME_TYPES "
                    "classifier map — the receiver side will book these "
                    "frames as 'unknown'",
                ))
    for tag, (rel, lineno) in sorted(declared.items()):
        if tag not in used:
            findings.append(Finding(
                "wire-type-coverage", rel, lineno,
                f"frame type '{tag}' is declared in a classifier map but "
                "no sender call site labels frames with it",
            ))
    yield from findings


# =============================================================================
# Rule 4: metric-name-drift
# =============================================================================
#
# Metric names are a string registry spread across ~100 emit sites and
# four consumer surfaces (metrics.default_rules, benchmark/
# metrics_check.py, benchmark/trajectory.py, the README tables).  A
# consumed name nothing emits is a health rule that can never fire or a
# bench section that silently reads zero.  Checked direction: consumed ⊆
# emitted (the reverse is meaningless — most metrics are not consumed by
# rules).  Dynamic per-peer/per-site suffixes are covered by the emit
# sites' f-string prefixes; names constructed entirely at runtime are
# allowlisted with a reason.

_INSTRUMENT_FNS = {"counter", "gauge", "histogram", "gauge_fn", "detail_fn"}
_CTX_EXACT_FNS = {"counter", "gauge", "rate", "last_change_age"}
_CTX_PREFIX_FNS = {"gauges_prefixed", "rates_prefixed"}
_METRIC_ROOTS = (
    "primary", "worker", "consensus", "net", "store", "crypto", "wire",
    "metrics", "faults", "runtime", "profile", "flight", "queue",
)
_METRIC_NAME_RE = re.compile(
    r"(?:%s)(?:\.[a-z0-9_]+)+\.?" % "|".join(_METRIC_ROOTS)
)
_README_TICK_RE = re.compile(r"`([^`]+)`")

# Metric names (exact or 'prefix.') legitimately constructed at runtime,
# with the reason the static scan cannot see them.
METRIC_ALLOWLIST: Dict[str, str] = {
    "wire.": "WireLedger builds wire.<dir>.{frames,bytes}.<type> (and the "
    "retransmit_ variants) at account time from the msg_type labels that "
    "rule wire-type-coverage pins",
    "queue.": "InstrumentedQueue builds queue.<channel>.{depth,capacity,"
    "high_water,enqueued,dequeued,full,put_wait_seconds,residence_seconds} "
    "from the channel name passed at construction (channel table in "
    "README 'Queue & backpressure accounting')",
}

_CONSUMER_FILES = (
    "benchmark/metrics_check.py",
    "benchmark/trajectory.py",
    "benchmark/scraper.py",
)


def _collect_metric_names(
    project: Project,
) -> Tuple[Set[str], Set[str], List[Tuple[str, str, int, bool]], List[Finding]]:
    """-> (emitted_exact, emitted_prefixes,
          consumers [(name, file, line, is_prefix)], findings)"""
    emitted: Set[str] = set()
    prefixes: Set[str] = set()
    consumers: List[Tuple[str, str, int, bool]] = []
    findings: List[Finding] = []
    for sf in project.files.values():
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            func = node.func
            name = (
                func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name)
                else None
            )
            recv = _receiver_name(func)
            arg0 = node.args[0]
            if recv == "ctx" and name in _CTX_EXACT_FNS | _CTX_PREFIX_FNS:
                s = _str_const(arg0)
                if s is not None:
                    consumers.append(
                        (s, sf.rel, arg0.lineno, name in _CTX_PREFIX_FNS)
                    )
                continue
            if name in _INSTRUMENT_FNS:
                s = _str_const(arg0)
                if s is not None:
                    emitted.add(s)
                elif isinstance(arg0, ast.JoinedStr):
                    prefix = _fstring_prefix(arg0)
                    if prefix:
                        prefixes.add(prefix)
                elif (
                    sf.rel != "narwhal_tpu/metrics.py"
                    and not sf.suppressed("metric-name", node)
                ):
                    # metrics.py itself forwards names through the
                    # registry plumbing; everywhere else a non-literal
                    # name is invisible to drift checking.
                    findings.append(Finding(
                        "metric-name-drift", sf.rel, node.lineno,
                        f"{name}() with a non-literal metric name — "
                        "unresolvable for drift checking; use a string "
                        "literal (or an f-string with a literal prefix)",
                    ))
    # Literal references in the bench consumer files.
    for rel in _CONSUMER_FILES:
        sf = project.file(rel)
        if sf is None or sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            s = _str_const(node)
            if s is None or not _METRIC_NAME_RE.fullmatch(s):
                continue
            consumers.append((s, rel, node.lineno, s.endswith(".")))
    # README tables: backtick-quoted names (a trailing `.<placeholder>`
    # marks a dynamic-suffix family -> prefix consumer).
    readme = project.texts.get("README.md")
    if readme is not None:
        for lineno, line in enumerate(readme.splitlines(), 1):
            for tick in _README_TICK_RE.findall(line):
                is_prefix = False
                if tick.endswith(">") and "<" in tick:
                    tick = tick[: tick.rindex("<")]
                    is_prefix = True
                if _METRIC_NAME_RE.fullmatch(tick):
                    consumers.append(
                        (tick, "README.md", lineno,
                         is_prefix or tick.endswith(".")),
                    )
    return emitted, prefixes, consumers, findings


def rule_metric_name_drift(project: Project) -> Iterator[Finding]:
    emitted, prefixes, consumers, findings = _collect_metric_names(project)

    def allowlisted(name: str) -> bool:
        return any(
            name == entry or name.startswith(entry)
            for entry in METRIC_ALLOWLIST
        )

    def exact_ok(name: str) -> bool:
        return (
            name in emitted
            or any(name.startswith(p) for p in prefixes)
            or allowlisted(name)
        )

    def prefix_ok(name: str) -> bool:
        return (
            any(e.startswith(name) for e in emitted)
            or any(p.startswith(name) or name.startswith(p) for p in prefixes)
            or allowlisted(name)
        )

    seen: Set[Tuple[str, str, int]] = set()
    for name, rel, lineno, is_prefix in consumers:
        key = (name, rel, lineno)
        if key in seen:
            continue
        seen.add(key)
        ok = prefix_ok(name) if is_prefix else exact_ok(name)
        if ok:
            continue
        sf = project.file(rel)
        if sf is not None:
            probe = ast.Expr(value=ast.Constant(value=name))
            probe.lineno = probe.end_lineno = lineno  # type: ignore[attr-defined]
            if sf.suppressed("metric-name", probe):
                continue
        kind = "prefix" if is_prefix else "name"
        findings.append(Finding(
            "metric-name-drift", rel, lineno,
            f"metric {kind} '{name}' is consumed here but no emit site "
            "registers it — the consumer silently reads nothing",
        ))
    yield from sorted(findings, key=lambda f: (f.path, f.line))


# =============================================================================
# Rule 5: env-var-registry
# =============================================================================
#
# 35+ NARWHAL_* knobs accreted across PRs 4-8, each hand-parsed at its
# read site and hand-documented (or not).  The registry in
# narwhal_tpu/utils/env.py is now the single source of truth: every
# NARWHAL_* string literal in the tree must be declared there, direct
# os.environ reads outside that module must route through its typed
# accessors, a declared knob nothing references is dead weight, and the
# README table is generated from the registry (drift in either direction
# fails here).

_ENV_NAME_RE = re.compile(r"NARWHAL_[A-Z0-9_]+")
_ENV_MODULE = "narwhal_tpu/utils/env.py"
_DIRECT_READ_FNS = {"os.environ.get", "os.getenv"}


def _declared_env(project: Project) -> Dict[str, int]:
    sf = project.file(_ENV_MODULE)
    declared: Dict[str, int] = {}
    if sf is None or sf.tree is None:
        return declared
    for node in ast.walk(sf.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "EnvVar"
            and node.args
        ):
            s = _str_const(node.args[0])
            if s is not None:
                declared[s] = node.lineno
    return declared


def rule_env_var_registry(project: Project) -> Iterator[Finding]:
    declared = _declared_env(project)
    findings: List[Finding] = []
    referenced: Set[str] = set()
    for sf in project.files.values():
        if sf.tree is None or sf.rel == _ENV_MODULE:
            continue
        for node in ast.walk(sf.tree):
            s = _str_const(node)
            if s is not None and _ENV_NAME_RE.fullmatch(s):
                referenced.add(s)
                if s not in declared and not sf.suppressed("env", node):
                    findings.append(Finding(
                        "env-var-registry", sf.rel, node.lineno,
                        f"{s} is not declared in the "
                        "narwhal_tpu/utils/env.py registry (name, type, "
                        "default, doc) — undeclared knobs are invisible "
                        "to the README table and rot unreviewed",
                    ))
            if isinstance(node, ast.Call):
                dotted = _dotted(node.func)
                arg = _str_const(node.args[0]) if node.args else None
                if (
                    dotted in _DIRECT_READ_FNS
                    and arg is not None
                    and _ENV_NAME_RE.fullmatch(arg)
                    and not sf.suppressed("env", node)
                ):
                    findings.append(Finding(
                        "env-var-registry", sf.rel, node.lineno,
                        f"direct {dotted}({arg!r}) — route NARWHAL_* "
                        "reads through the typed accessors in "
                        "narwhal_tpu.utils.env (env_flag/env_int/"
                        "env_float/env_str) so parsing and defaults "
                        "stay declared once",
                    ))
            if isinstance(node, ast.Subscript) and _dotted(
                node.value
            ) == "os.environ":
                arg = _str_const(node.slice)
                if (
                    arg is not None
                    and _ENV_NAME_RE.fullmatch(arg)
                    and not sf.suppressed("env", node)
                ):
                    findings.append(Finding(
                        "env-var-registry", sf.rel, node.lineno,
                        f"direct os.environ[{arg!r}] — route NARWHAL_* "
                        "reads through narwhal_tpu.utils.env accessors",
                    ))
    # Dead declarations: nothing in the parsed scope NOR the raw-text
    # scope (tests, Makefile, CI workflows, bench scripts) mentions them.
    for name, lineno in sorted(declared.items()):
        if name in referenced:
            continue
        if any(name in text for text in project.texts.values()):
            continue
        findings.append(Finding(
            "env-var-registry", _ENV_MODULE, lineno,
            f"{name} is declared in the registry but nothing reads it "
            "(searched narwhal_tpu/, benchmark/, tests/, Makefile, CI "
            "workflows) — delete the declaration or the dead knob",
        ))
    findings.extend(_env_table_drift(project))
    yield from findings


def _env_table_drift(project: Project) -> List[Finding]:
    """README 'Environment variables' table must equal the generated one.

    The registry is evaluated from the LINTED tree's utils/env.py (not
    the running package) so ``--root <other-checkout>`` and overlay
    mutations check the tree they claim to — env.py is stdlib-only by
    contract, which is what makes executing it here safe."""
    readme = project.texts.get("README.md")
    sf = project.file(_ENV_MODULE)
    if readme is None or sf is None:
        return []
    import sys
    import types

    mod_name = "_narwhal_lint_env"
    env_mod = types.ModuleType(mod_name)
    # Registered during exec: the dataclass machinery resolves
    # annotations through sys.modules[cls.__module__] (unguarded
    # .__dict__ access on 3.10).
    sys.modules[mod_name] = env_mod
    try:
        exec(compile(sf.text, _ENV_MODULE, "exec"), env_mod.__dict__)
        begin, end = env_mod.TABLE_BEGIN, env_mod.TABLE_END
    except Exception as e:
        return [Finding(
            "env-var-registry", _ENV_MODULE, 1,
            f"could not evaluate the env registry for the README table "
            f"check: {e!r}",
        )]
    finally:
        sys.modules.pop(mod_name, None)
    if begin not in readme or end not in readme:
        return [Finding(
            "env-var-registry", "README.md", 1,
            "README has no generated env-var table markers "
            f"({begin!r} … {end!r}); insert the output of "
            "`python -m narwhal_tpu.analysis --env-table`",
        )]
    section = readme.split(begin, 1)[1].split(end, 1)[0].strip()
    expected = env_mod.render_table().strip()
    if section != expected:
        line = readme[: readme.index(begin)].count("\n") + 1
        return [Finding(
            "env-var-registry", "README.md", line,
            "README env-var table drifted from the registry — "
            "regenerate with `python -m narwhal_tpu.analysis "
            "--env-table` and paste between the markers",
        )]
    return []


from .interleave import (  # noqa: E402  (bottom import: shares helpers)
    rule_interleave_iteration,
    rule_interleave_window,
)

ALL_RULES = (
    rule_no_blocking_in_async,
    rule_task_retention,
    rule_wire_type_coverage,
    rule_metric_name_drift,
    rule_env_var_registry,
    rule_interleave_window,
    rule_interleave_iteration,
)
