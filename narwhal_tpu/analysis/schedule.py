"""Deterministic schedule explorer — the dynamic half of narwhal-race.

The static rules (``interleave.py``) prove the *shape* of every
suspendable window; this module drives the other direction: actually
*execute* the protocol under many distinct-but-reproducible task
interleavings and let the frozen golden oracle judge the outcomes
(``benchmark/race_explore.py`` is the harness; madsim/FoundationDB-style
deterministic-simulation testing is the lineage).

Mechanism.  An asyncio loop keeps the callbacks that became runnable in
one tick in a FIFO ``_ready`` queue and runs them in insertion order —
which is exactly ONE of the many orders a legal cooperative scheduler
could pick.  :class:`ExploringEventLoop` subclasses the default selector
loop and, at the top of every tick, permutes the same-tick ready set
with a seeded ``random.Random``: same seed → byte-identical permutation
sequence → byte-identical execution, different seed → a genuinely
different (but still legal) interleaving.  Any schedule-dependent
outcome difference is therefore a reproducible bug with the seed as the
repro.

Scope notes:

- only *same-tick* reordering is explored: callbacks scheduled during a
  tick (timer expiries drained inside ``_run_once``, I/O completions)
  join the NEXT tick's permutation.  This is the productive subset —
  it permutes exactly the wakeup order of tasks that raced into
  runnability together, which is where torn-invariant windows open;
- determinism of the *workload* is the harness's job: a scenario with
  real sockets or wall-clock timers is per-seed reproducible only down
  to OS timing, so the byte-identical cross-seed gate belongs to closed
  scenarios (fixed certificate streams) and the safety-verdict gate
  (oracle replay of whatever order actually happened) to socketed ones.
"""

from __future__ import annotations

import asyncio
import random
from typing import Any, Callable, Coroutine, Optional

__all__ = ["ExploringEventLoop", "run_with_seed"]


def _is_task_step(handle) -> bool:
    """True when ``handle`` is a Task wakeup (``Task.__step``) — the
    only handles the explorer may legally reorder.  asyncio's own
    plumbing relies on FIFO between a future's internal done-callbacks
    and everything scheduled after them (e.g. ``sock_connect``'s
    ``_sock_write_done`` must run before the awaiting task resumes and
    wraps the same fd in a transport), so plain-function callbacks stay
    exactly where they are."""
    cb = getattr(handle, "_callback", None)
    owner = getattr(cb, "__self__", None)
    return isinstance(owner, asyncio.Task)


class ExploringEventLoop(asyncio.SelectorEventLoop):
    """Selector event loop that permutes same-tick ready-callback order
    deterministically from ``seed``.

    Only *consecutive runs of task wakeups* are shuffled: the relative
    order of every non-task callback (and of each task wakeup against
    the plumbing callbacks around it) is preserved, so asyncio's
    internal FIFO assumptions hold while the order in which tasks that
    became runnable together get the loop — the thing torn-invariant
    windows care about — is explored.

    ``permutations`` counts the ticks where some run actually had more
    than one task wakeup to permute — a scenario that never wakes two
    tasks in one tick explores nothing, and the harness asserts this
    stays non-trivial so the gate cannot pass vacuously.
    """

    def __init__(self, seed: int) -> None:
        super().__init__()
        self.seed = seed
        self._rng = random.Random(seed)
        self.permutations = 0
        self.ticks = 0

    def _run_once(self) -> None:  # noqa: D401 (asyncio internal hook)
        self.ticks += 1
        ready = self._ready
        if len(ready) > 1:
            items = list(ready)
            permuted = False
            i, n = 0, len(items)
            while i < n:
                if not _is_task_step(items[i]):
                    i += 1
                    continue
                j = i
                while j < n and _is_task_step(items[j]):
                    j += 1
                if j - i > 1:
                    segment = items[i:j]
                    self._rng.shuffle(segment)
                    items[i:j] = segment
                    permuted = True
                i = j
            if permuted:
                ready.clear()
                ready.extend(items)
                self.permutations += 1
        super()._run_once()


def run_with_seed(
    main: Callable[[], Coroutine],
    seed: int,
    timeout: Optional[float] = None,
    virtual_time: bool = False,
) -> Any:
    """``asyncio.run`` under an :class:`ExploringEventLoop` seeded with
    ``seed``; returns ``(result, loop_stats)`` where ``loop_stats`` is a
    dict with the tick/permutation counts (the non-vacuity witness).

    ``timeout`` (enforced via ``asyncio.wait_for``) turns a
    schedule-induced deadlock into a failure with the seed attached
    instead of a hung harness.

    ``virtual_time=True`` delegates to the simulation harness's
    :func:`narwhal_tpu.sim.clock.run_virtual`: same exploring loop, but
    ``loop.time()`` runs on simulated seconds that jump at quiesce —
    ``timeout`` then bounds VIRTUAL seconds, so the guard is
    deterministic per seed instead of host-speed-dependent."""
    if virtual_time:
        from ..sim.clock import run_virtual

        return run_virtual(main, seed, max_virtual_s=timeout)
    loop = ExploringEventLoop(seed)
    try:
        asyncio.set_event_loop(loop)
        coro = main()
        if timeout is not None:
            coro = asyncio.wait_for(coro, timeout)
        result = loop.run_until_complete(coro)
        return result, {
            "seed": seed,
            "ticks": loop.ticks,
            "permutations": loop.permutations,
        }
    finally:
        try:
            _cancel_pending(loop)
            loop.run_until_complete(loop.shutdown_asyncgens())
            # Join the default executor BEFORE closing: cancelling a
            # run_in_executor future does not stop its thread, and a
            # thread surviving into the NEXT seeded incarnation is
            # cross-run state the explorer exists to rule out (it is
            # also how the checkpoint-tmp collision bug hid: the
            # pre-"crash" incarnation's fsync thread raced the restarted
            # one's).
            loop.run_until_complete(loop.shutdown_default_executor())
        finally:
            asyncio.set_event_loop(None)
            loop.close()


def _cancel_pending(loop: asyncio.AbstractEventLoop) -> None:
    pending = [t for t in asyncio.all_tasks(loop) if not t.done()]
    for task in pending:
        task.cancel()
    if pending:
        loop.run_until_complete(
            asyncio.gather(*pending, return_exceptions=True)
        )
