"""Await-interleaving race rules — the interprocedural half of narwhal-lint.

The whole protocol's safety rests on cooperative-scheduling atomicity:
there is not a single ``asyncio.Lock`` in the tree, so the only thing
protecting ``Core.current_header``, the waiters' pending maps, the
Proposer's digest buffer or the Store's deferred buffer is that no task
yields between reading shared state and writing it back.  The PR 9 rules
are single-statement; the bug class PRs 4-8 kept rediscovering
dynamically (checkpoint-fsync stall, duplicate-flood re-verify,
deferred-flush ordering) is *interleavings* — which need a whole-program
yield analysis.  This module builds one:

1. **Units.**  Every function/method under ``narwhal_tpu/`` (nested
   ``async def``s inside methods — the sender's ``write_loop`` — are
   their own units: they run as their own tasks).

2. **May-yield map.**  A unit may yield iff it contains a *true* yield
   point: ``async for``/``async with``, awaiting an unresolvable target
   (queue/event/socket primitives), or awaiting a project method that
   itself may yield (transitive fixpoint).  Awaiting an ``async def``
   that never suspends does NOT yield — asyncio runs it to completion
   synchronously — which is what keeps the HeaderWaiter's atomic-tick
   handlers (``await self._sync_parents(...)``: no awaits inside) out of
   the findings.

3. **Task roots.**  The tasks that can actually interleave: every
   spawned coroutine (``utils.tasks.spawn`` / ``create_task`` /
   ``ensure_future`` sites, resolved through ``self``/typed attributes/
   typed locals), every async ``run()`` method (the Primary/Worker/
   Consensus wiring spawns one task per protocol actor), receiver
   ``dispatch`` handlers (one task per inbound connection), asyncio
   ``Protocol`` callbacks (loop-invoked), and any bound method whose
   *reference* escapes as a callback argument (``parents_cb=
   proposer.deliver_parents``, ``run_in_executor(None,
   self._write_checkpoint, ...)``, ``Thread(target=self._watch)``).
   Root identity is (class-hierarchy group, method name), so a
   Byzantine override and its base run as ONE root — a node runs either,
   never both.  A root spawned from inside a loop, a per-connection
   dispatcher, and a protocol callback are *self-concurrent*: two
   instances of them can interleave with each other.

4. **Windows** (rule ``interleave-window``).  Per async unit, in source
   order (with transitive read/write summaries of resolved callees
   expanded at their call sites): a ``self.<attr>`` read, then a true
   yield point, then a write/mutation of the same attribute.  Flagged
   only when the attribute is also written from a *different* task root
   (or from two instances of a self-concurrent root) — the classic
   torn-invariant window.  Attributes reached through a typed attribute
   (``self.consensus_round.value``, the Store's internals via its
   methods) are keyed by the owning class, so cross-class sharing of one
   object is seen.

5. **Iteration** (rule ``interleave-iteration``).  ``for … in
   self.<attr>`` / ``.items()/.values()/.keys()`` whose loop body
   contains a true yield point, on an attribute another task root
   writes: mutation-during-iteration under a new interleaving
   (``list(self.attr)`` snapshots are exempt — they copy first).

Findings report the **yield chain** — the call path that makes the
window suspendable (``await self.synchronizer.get_parents →
Synchronizer.get_parents → await self.tx_header_waiter.put``) — so a
pragma can cite the actual window.  Suppression:
``# lint: allow-interleave(reason)`` on the read, yield, write or
``for`` line (or the line above any of them); the reason must name the
invariant that makes the window safe.

Known approximations (all toward over-reporting, never silent misses,
except as noted): straight source order approximates control flow (a
loop's back edge is not modeled, so a read that only precedes the yield
on the *next* iteration is missed — deliberate: the sleep-then-
atomic-tick pattern used by every timer here would otherwise flag);
callee write summaries are expanded flow-insensitively at the call line,
ordered before the call's own yield (the take-then-suspend shape every
consumer here uses); call targets through untyped objects are
unresolvable — their awaits count as yields, their writes are invisible.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .linter import Finding, Project, SourceFile

# Methods whose call MUTATES the receiver (dict/set/list/deque surface).
_MUTATORS = {
    "append", "appendleft", "add", "pop", "popleft", "extend",
    "extendleft", "update", "setdefault", "clear", "remove", "discard",
    "insert", "sort", "reverse",
}

# Loop-invoked asyncio.Protocol callbacks: roots, one invocation per event.
_PROTOCOL_CALLBACKS = {
    "data_received", "connection_made", "connection_lost", "eof_received",
}
# Iteration views that alias the container (no copy).
_ALIAS_VIEWS = {"items", "values", "keys"}

PRAGMA = "interleave"


# -- model ---------------------------------------------------------------------

@dataclass
class Unit:
    key: str                 # "rel::Class.method" (nested: "….<inner>")
    rel: str
    cls: Optional[str]       # defining class name (None: module function)
    name: str                # bare function name
    node: ast.AST
    is_async: bool
    # ordered items: ("r"/"w", attr_key, line)
    #              | ("y", None, line, None, label)
    #              | ("call", None, line, target_key|None, awaited, label)
    items: List[tuple] = field(default_factory=list)
    # iteration spans: (attr_key, for_line, body_end_line)
    iters: List[Tuple[Tuple[str, str], int, int]] = field(default_factory=list)
    external_yield: bool = False   # has an unresolvable yield point


@dataclass
class ClassInfo:
    name: str
    rel: str
    bases: List[str]
    methods: Dict[str, str] = field(default_factory=dict)  # name -> unit key
    attr_types: Dict[str, str] = field(default_factory=dict)  # attr -> class


class Model:
    def __init__(self) -> None:
        self.units: Dict[str, Unit] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.group: Dict[str, str] = {}     # class -> hierarchy group root
        self.may_yield: Dict[str, bool] = {}
        self.reads: Dict[str, Set] = {}     # unit key -> attr-key summary
        self.writes: Dict[str, Set] = {}
        self.roots: Dict[str, Set[str]] = {}   # unit key -> root ids
        self.self_concurrent: Set[str] = set()  # root ids
        self.root_repr: Dict[str, str] = {}    # root id -> a unit key
        # attr key -> {root ids that write it}
        self.attr_writers: Dict[Tuple[str, str], Set[str]] = {}

    def root_id(self, unit: Unit) -> str:
        """Hierarchy-merged task-root identity: a Byzantine override and
        its base method are ONE root (a node runs one or the other)."""
        if unit.cls is not None:
            base = f"{self.group.get(unit.cls, unit.cls)}.{unit.name}"
            if "<" in unit.key:  # nested unit: keep its own identity
                return f"{base}.{unit.key.split('::', 1)[1]}"
            return base
        return unit.key


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _ann_name(ann: Optional[ast.AST]) -> Optional[str]:
    """Class name from a parameter annotation (Name, string constant, or
    Optional[Name])."""
    if ann is None:
        return None
    if isinstance(ann, ast.Name):
        return ann.id
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        inner = ann.value.strip().strip('"\'')
        if inner.startswith("Optional[") and inner.endswith("]"):
            inner = inner[len("Optional["):-1]
        return inner.split("[")[0].split(".")[-1] or None
    if isinstance(ann, ast.Subscript) and isinstance(ann.slice, ast.Name):
        return ann.slice.id  # Optional[X]
    return None


# -- pass 1: units, classes, attr types ---------------------------------------

def _collect(project: Project) -> Model:
    model = Model()
    for sf in project.files.values():
        if not sf.rel.startswith("narwhal_tpu/") or sf.tree is None:
            continue
        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef):
                ci = ClassInfo(
                    name=node.name,
                    rel=sf.rel,
                    bases=[
                        b.id if isinstance(b, ast.Name)
                        else (b.attr if isinstance(b, ast.Attribute) else "")
                        for b in node.bases
                    ],
                )
                # First definition wins on a (rare) duplicate class name;
                # attr keys merge through the hierarchy groups anyway.
                model.classes.setdefault(node.name, ci)
                for item in node.body:
                    if isinstance(
                        item, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        key = f"{sf.rel}::{node.name}.{item.name}"
                        ci.methods.setdefault(item.name, key)
                        model.units[key] = Unit(
                            key, sf.rel, node.name, item.name, item,
                            isinstance(item, ast.AsyncFunctionDef),
                        )
                        _collect_nested(model, sf, node.name, key, item)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = f"{sf.rel}::{node.name}"
                model.units[key] = Unit(
                    key, sf.rel, None, node.name, node,
                    isinstance(node, ast.AsyncFunctionDef),
                )
                _collect_nested(model, sf, None, key, node)
    # Hierarchy groups (union through project bases).
    parent: Dict[str, str] = {c: c for c in model.classes}

    def find(x: str) -> str:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for ci in model.classes.values():
        for b in ci.bases:
            if b in parent:
                parent[find(ci.name)] = find(b)
    model.group = {c: find(c) for c in model.classes}
    # Attribute types, after all classes are known.
    for ci in model.classes.values():
        for ukey in ci.methods.values():
            fn = model.units[ukey].node
            ann_by_param = {}
            for a in list(fn.args.args) + list(fn.args.kwonlyargs):
                t = _ann_name(a.annotation)
                if t in model.classes:
                    ann_by_param[a.arg] = t
            for stmt in ast.walk(fn):
                if not isinstance(stmt, ast.Assign):
                    continue
                for tgt in stmt.targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        t = None
                        v = stmt.value
                        if (
                            isinstance(v, ast.Call)
                            and isinstance(v.func, ast.Name)
                            and v.func.id in model.classes
                        ):
                            t = v.func.id
                        elif isinstance(v, ast.Name):
                            t = ann_by_param.get(v.id)
                        if t is not None:
                            ci.attr_types.setdefault(tgt.attr, t)
    return model


def _collect_nested(
    model: Model, sf: SourceFile, cls: Optional[str], parent_key: str,
    fn: ast.AST,
) -> None:
    """Nested function defs are their own units (they may run as their
    own tasks — the sender's write_loop/read_loop).  Flat keying under
    the defining method; depth beyond one level keeps the same parent."""
    for item in ast.walk(fn):
        if item is fn or not isinstance(
            item, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            continue
        key = f"{parent_key}.<{item.name}>"
        if key not in model.units:
            model.units[key] = Unit(
                key, sf.rel, cls, item.name, item,
                isinstance(item, ast.AsyncFunctionDef),
            )


# -- pass 2: per-unit events, calls, spawns, escapes --------------------------

class _Scan(ast.NodeVisitor):
    """One unit's ordered event stream + call/spawn/escape sites."""

    def __init__(self, model: Model, unit: Unit):
        self.model = model
        self.unit = unit
        self.cls = model.classes.get(unit.cls) if unit.cls else None
        self.locals: Dict[str, str] = {}  # local var -> class name
        self.spawns: List[Tuple[str, bool]] = []   # (target unit, in_loop)
        self.escapes: List[str] = []               # escaped method units
        self._loop_depth = 0
        fn = unit.node
        for a in list(fn.args.args) + list(fn.args.kwonlyargs):
            t = _ann_name(a.annotation)
            if t in model.classes:
                self.locals[a.arg] = t

    # -- resolution helpers --------------------------------------------------

    def _attr_key(self, node: ast.AST) -> Optional[Tuple[str, str]]:
        """(group, attr) for self.<attr>, or for <typed>.<attr> one level
        through a typed expression (self.consensus_round.value)."""
        if not isinstance(node, ast.Attribute):
            return None
        base = node.value
        if isinstance(base, ast.Name) and base.id == "self" and self.cls:
            return (self.model.group[self.cls.name], node.attr)
        inner = self._obj_class(base)
        if inner is not None:
            return (self.model.group[inner], node.attr)
        return None

    def _obj_class(self, node: ast.AST) -> Optional[str]:
        """Class of an object expression, when statically known."""
        if isinstance(node, ast.Name):
            if node.id == "self" and self.cls:
                return self.cls.name
            return self.locals.get(node.id)
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and self.cls
        ):
            return self._lookup_attr_type(self.cls.name, node.attr)
        return None

    def _lookup_attr_type(self, cls: str, attr: str) -> Optional[str]:
        seen: Set[str] = set()
        while cls in self.model.classes and cls not in seen:
            seen.add(cls)
            t = self.model.classes[cls].attr_types.get(attr)
            if t is not None:
                return t
            cls = next(
                (b for b in self.model.classes[cls].bases
                 if b in self.model.classes),
                None,
            )
        return None

    def _resolve_method(self, cls: Optional[str], name: str) -> Optional[str]:
        seen: Set[str] = set()
        while cls in self.model.classes and cls not in seen:
            seen.add(cls)
            key = self.model.classes[cls].methods.get(name)
            if key is not None:
                return key
            cls = next(
                (b for b in self.model.classes[cls].bases
                 if b in self.model.classes),
                None,
            )
        return None

    def _resolve_call(self, func: ast.AST) -> Optional[str]:
        """Unit key of the call target, when statically known."""
        if isinstance(func, ast.Name):
            # Nested unit of this method, or same-module function.
            for cand in (
                f"{self.unit.key}.<{func.id}>",
                f"{self.unit.rel}::{func.id}",
            ):
                if cand in self.model.units:
                    return cand
            return None
        if isinstance(func, ast.Attribute):
            owner = self._obj_class(func.value)
            if owner is not None:
                return self._resolve_method(owner, func.attr)
        return None

    # -- traversal -----------------------------------------------------------

    def run(self) -> None:
        for stmt in self.unit.node.body:
            self._visit(stmt)

    def _visit(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested unit: separate scope, separate task
        if isinstance(node, ast.Lambda):
            return
        method = getattr(self, f"_v_{type(node).__name__}", None)
        if method is not None:
            method(node)
            return
        for child in ast.iter_child_nodes(node):
            self._visit(child)

    def _emit(self, kind: str, attr, line: int, *extra) -> None:
        self.unit.items.append((kind, attr, line, *extra))

    # assignments / mutations -------------------------------------------------

    def _v_Assign(self, node: ast.Assign) -> None:
        self._visit(node.value)
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            v = node.value
            if (
                isinstance(v, ast.Call)
                and isinstance(v.func, ast.Name)
                and v.func.id in self.model.classes
            ):
                # v = ClassName(...): local holds an instance.
                self.locals[node.targets[0].id] = v.func.id
            elif (
                isinstance(v, ast.Call)
                and isinstance(v.func, ast.Name)
                and v.func.id in self.locals
            ):
                # v = cls_var(...) where cls_var holds a class object
                # (primary.py: `proposer = proposer_cls(...)` after
                # `proposer_cls, core_cls = Proposer, Core`).
                self.locals[node.targets[0].id] = self.locals[v.func.id]
            elif isinstance(v, ast.Name) and v.id in self.model.classes:
                # v = ClassName: local holds the class object; `v(...)`
                # then builds an instance of it (primary.py's
                # `proposer_cls, core_cls = Proposer, Core` is the tuple
                # variant below).
                self.locals[node.targets[0].id] = v.id
            elif isinstance(v, ast.Name) and v.id in self.locals:
                self.locals[node.targets[0].id] = self.locals[v.id]
        if (
            len(node.targets) == 1
            and isinstance(node.targets[0], ast.Tuple)
            and isinstance(node.value, ast.Tuple)
            and len(node.targets[0].elts) == len(node.value.elts)
        ):
            for t, v in zip(node.targets[0].elts, node.value.elts):
                if (
                    isinstance(t, ast.Name)
                    and isinstance(v, ast.Name)
                    and v.id in self.model.classes
                ):
                    self.locals[t.id] = v.id
        for tgt in node.targets:
            self._store_target(tgt)

    def _v_AugAssign(self, node: ast.AugAssign) -> None:
        self._visit(node.value)
        key = self._attr_key(node.target)
        if key is None and isinstance(node.target, ast.Subscript):
            key = self._attr_key(node.target.value)
        if key is None and isinstance(node.target, ast.Attribute):
            key = self._attr_key(node.target.value)
        if key is not None:
            self._emit("r", key, node.lineno)
            self._emit("w", key, node.lineno)

    def _store_target(self, tgt: ast.AST) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._store_target(el)
            return
        key = self._attr_key(tgt)
        if key is not None:
            self._emit("w", key, tgt.lineno)
            return
        if isinstance(tgt, ast.Subscript):
            key = self._attr_key(tgt.value)
            if key is not None:
                self._emit("w", key, tgt.lineno)
            else:
                self._visit(tgt.value)
        elif isinstance(tgt, ast.Attribute):
            # self.a.b = x on an untyped a: mutating the object held in
            # a, conservatively a write to a.
            key = self._attr_key(tgt.value)
            if key is not None:
                self._emit("w", key, tgt.lineno)

    def _v_Delete(self, node: ast.Delete) -> None:
        for tgt in node.targets:
            base = tgt.value if isinstance(tgt, ast.Subscript) else tgt
            key = self._attr_key(base)
            if key is not None:
                self._emit("w", key, node.lineno)

    # reads -------------------------------------------------------------------

    def _v_Attribute(self, node: ast.Attribute) -> None:
        key = self._attr_key(node)
        if key is not None and isinstance(node.ctx, ast.Load):
            self._emit("r", key, node.lineno)
        self._visit(node.value)

    # calls / awaits ----------------------------------------------------------

    def _v_Call(self, node: ast.Call) -> None:
        self._visit(node.func)
        fname = (
            node.func.attr if isinstance(node.func, ast.Attribute)
            else node.func.id if isinstance(node.func, ast.Name) else None
        )
        # Mutator call on a tracked attribute: self.pending.pop(...)
        if isinstance(node.func, ast.Attribute) and fname in _MUTATORS:
            key = self._attr_key(node.func.value)
            if key is not None:
                self._emit("r", key, node.lineno)
                self._emit("w", key, node.lineno)
        # Spawn site?  The coroutine argument is only CREATED here — it
        # runs as its own task, so its effects must not be expanded at
        # this call site (mark it so the call item below is suppressed).
        spawned_call = None
        if fname in ("spawn", "create_task", "ensure_future") and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Call):
                spawned_call = arg
                target = self._resolve_call(arg.func)
                if target is not None:
                    self.spawns.append((target, self._loop_depth > 0))
        # Escaping bound-method references in argument position.
        for a in list(node.args) + [k.value for k in node.keywords]:
            if isinstance(a, (ast.Attribute, ast.Name)):
                target = self._resolve_call(a)
                if target is not None:
                    self.escapes.append(target)
        for a in node.args:
            if a is spawned_call:
                # Evaluate only the coroutine call's own arguments (they
                # ARE evaluated at spawn time); the body runs elsewhere.
                for sub in a.args:
                    self._visit(sub)
                for sub in a.keywords:
                    self._visit(sub.value)
                continue
            self._visit(a)
        for k in node.keywords:
            self._visit(k.value)
        target = self._resolve_call(node.func)
        label = _dotted(node.func) or f"{fname or '?'}()"
        self._emit("call", None, node.lineno, target, False, label)

    def _v_Await(self, node: ast.Await) -> None:
        if isinstance(node.value, ast.Call):
            self._v_Call(node.value)
            last = self.unit.items[-1]
            if last[0] == "call":
                # Mark the call item awaited.
                self.unit.items[-1] = (
                    "call", None, last[2], last[3], True, last[5]
                )
                if last[3] is None:
                    # Unresolvable awaited target: a true yield point.
                    self.unit.external_yield = True
                    self._emit(
                        "y", None, node.lineno, None, f"await {last[5]}"
                    )
        else:
            self._visit(node.value)
            self.unit.external_yield = True
            self._emit("y", None, node.lineno, None, "await <future>")

    # control flow ------------------------------------------------------------

    def _v_For(self, node: ast.For) -> None:
        self._iter_common(node)

    def _v_AsyncFor(self, node: ast.AsyncFor) -> None:
        self.unit.external_yield = True
        self._emit("y", None, node.lineno, None, "async for")
        self._iter_common(node)

    def _iter_common(self, node) -> None:
        # Direct (aliasing) iteration over a tracked attribute?
        it = node.iter
        key = self._attr_key(it)
        if (
            key is None
            and isinstance(it, ast.Call)
            and isinstance(it.func, ast.Attribute)
            and it.func.attr in _ALIAS_VIEWS
        ):
            key = self._attr_key(it.func.value)
        if key is not None:
            end = max(
                (getattr(n, "end_lineno", None) or node.lineno)
                for n in ast.walk(node)
            )
            self.unit.iters.append((key, node.lineno, end))
        self._visit(it)
        self._store_target(node.target)
        self._loop_depth += 1
        for stmt in node.body:
            self._visit(stmt)
        self._loop_depth -= 1
        for stmt in node.orelse:
            self._visit(stmt)

    def _v_While(self, node: ast.While) -> None:
        self._visit(node.test)
        self._loop_depth += 1
        for stmt in node.body:
            self._visit(stmt)
        self._loop_depth -= 1
        for stmt in node.orelse:
            self._visit(stmt)

    def _v_AsyncWith(self, node: ast.AsyncWith) -> None:
        self.unit.external_yield = True
        self._emit("y", None, node.lineno, None, "async with")
        for item in node.items:
            self._visit(item.context_expr)
        for stmt in node.body:
            self._visit(stmt)


# -- pass 3: fixpoints, roots, reachability -----------------------------------

def build_model(project: Project) -> Model:
    cached = getattr(project, "_interleave_model", None)
    if cached is not None:
        return cached
    model = _collect(project)
    scans: Dict[str, _Scan] = {}
    for key, unit in model.units.items():
        scan = _Scan(model, unit)
        scan.run()
        scans[key] = scan

    # May-yield fixpoint: seed with external yields, propagate through
    # awaited resolved calls.
    may = {k: u.external_yield for k, u in model.units.items()}
    changed = True
    while changed:
        changed = False
        for key, unit in model.units.items():
            if may[key]:
                continue
            for item in unit.items:
                if item[0] != "call" or not item[4]:
                    continue
                if item[3] is not None and may.get(item[3]):
                    may[key] = True
                    changed = True
                    break
    model.may_yield = may

    # Read/write summaries (transitive through resolved calls).
    reads: Dict[str, Set] = {k: set() for k in model.units}
    writes: Dict[str, Set] = {k: set() for k in model.units}
    for key, unit in model.units.items():
        for item in unit.items:
            if item[0] == "r":
                reads[key].add(item[1])
            elif item[0] == "w":
                writes[key].add(item[1])
    changed = True
    while changed:
        changed = False
        for key, unit in model.units.items():
            for item in unit.items:
                if item[0] != "call" or item[3] is None:
                    continue
                t = item[3]
                if not reads[t] <= reads[key]:
                    reads[key] |= reads[t]
                    changed = True
                if not writes[t] <= writes[key]:
                    writes[key] |= writes[t]
                    changed = True
    model.reads, model.writes = reads, writes

    # Task roots (hierarchy-merged ids).
    root_units: Dict[str, str] = {}  # unit key -> root id

    def add_root(ukey: str, multi: bool = False) -> None:
        unit = model.units[ukey]
        rid = model.root_id(unit)
        root_units[ukey] = rid
        model.root_repr.setdefault(rid, ukey)
        if multi:
            model.self_concurrent.add(rid)

    for key, unit in model.units.items():
        if unit.is_async and unit.cls is not None and unit.name == "run" \
                and "<" not in key:
            add_root(key)
        elif unit.is_async and unit.cls is not None \
                and unit.name == "dispatch":
            add_root(key, multi=True)  # one task per inbound connection
        elif unit.cls is not None and unit.name in _PROTOCOL_CALLBACKS:
            add_root(key, multi=True)  # loop-invoked per event
    for key, scan in scans.items():
        for target, in_loop in scan.spawns:
            add_root(target, multi=in_loop)
        for target in scan.escapes:
            add_root(target)

    # Reachability: BFS from each root through resolved calls, plus
    # sibling methods under the same root id (an override chain).
    callees: Dict[str, Set[str]] = {k: set() for k in model.units}
    for key, unit in model.units.items():
        for item in unit.items:
            if item[0] == "call" and item[3] is not None:
                callees[key].add(item[3])
    roots_of: Dict[str, Set[str]] = {k: set() for k in model.units}
    for ukey, rid in root_units.items():
        stack, seen = [ukey], {ukey}
        while stack:
            cur = stack.pop()
            roots_of[cur].add(rid)
            for nxt in callees[cur]:
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
    model.roots = roots_of

    # Writers per attribute (direct write events only, attributed to the
    # writing unit's roots).
    for key, unit in model.units.items():
        for item in unit.items:
            if item[0] == "w":
                model.attr_writers.setdefault(item[1], set()).update(
                    roots_of[key]
                )

    project._interleave_model = model  # type: ignore[attr-defined]
    return model


# -- yield chains --------------------------------------------------------------

def _yield_chain(model: Model, item, depth: int = 3) -> str:
    """Human-readable suspension path for one awaited-call yield point."""
    label = f"await {item[5]}"
    target = item[3]
    hops: List[str] = []
    while target is not None and depth > 0:
        t = model.units[target]
        hops.append(f"{t.cls + '.' if t.cls else ''}{t.name}")
        nxt = None
        for it in t.items:
            if it[0] == "y":
                hops.append(it[4])
                break
            if it[0] == "call" and it[4] and it[3] is not None \
                    and model.may_yield.get(it[3]):
                nxt = it
                hops.append(f"await {it[5]}")
                break
        if nxt is None:
            break
        target = nxt[3]
        depth -= 1
    return label + "".join(" → " + h for h in hops)


# -- window extraction ---------------------------------------------------------

def _expanded_events(model: Model, unit: Unit):
    """Ordered (kind, attr, line, chain) events with callee summaries
    expanded at their call sites.

    Only summary attrs of the unit's OWN class group are expanded: a
    window on another object's internals (the Store's maps, a sender's
    deques) is reported where it actually sits — inside that class's own
    methods — not duplicated into every caller, where the read and write
    lines would both point at opaque call sites."""
    own = model.group.get(unit.cls) if unit.cls else None
    out = []
    for item in unit.items:
        kind = item[0]
        if kind in ("r", "w"):
            out.append((kind, item[1], item[2], None))
        elif kind == "y":
            out.append(("y", None, item[2], item[4]))
        elif kind == "call":
            _, _, line, target, awaited, label = item
            if target is not None:
                for attr in sorted(model.reads[target]):
                    if attr[0] == own:
                        out.append(("r", attr, line, None))
                for attr in sorted(model.writes[target]):
                    if attr[0] == own:
                        out.append(("w", attr, line, None))
                if awaited and model.may_yield.get(target):
                    out.append(("y", None, line, _yield_chain(model, item)))
    return out


def _racy_roots(model: Model, unit_roots: Set[str], attr) -> Set[str]:
    """Root ids that can write ``attr`` while a task in ``unit_roots`` is
    suspended mid-window: any writer root outside the unit's own root
    set, any writer at all when the unit runs under several roots, and
    any self-concurrent writer root (two instances interleave)."""
    writers = model.attr_writers.get(attr, set())
    if len(unit_roots) > 1:
        other = set(writers)
    else:
        other = writers - unit_roots
    other |= {
        r for r in (writers & unit_roots) if r in model.self_concurrent
    }
    return other


def _suppressed(sf: SourceFile, lines) -> bool:
    for ln in lines:
        probe = ast.Expr(value=ast.Constant(value=0))
        probe.lineno = probe.end_lineno = ln  # type: ignore[attr-defined]
        if sf.suppressed(PRAGMA, probe):
            return True
    return False


def _root_names(model: Model, roots: Set[str]) -> str:
    names = []
    for r in sorted(roots):
        u = model.units[model.root_repr[r]]
        label = f"{u.cls + '.' if u.cls else ''}{u.name}"
        if r in model.self_concurrent:
            label += " (multi-instance)"
        names.append(f"{label} [{u.rel}]")
    return ", ".join(names)


def _unit_label(unit: Unit) -> str:
    return f"{unit.cls + '.' if unit.cls else ''}{unit.name}"


def rule_interleave_window(project: Project) -> Iterator[Finding]:
    model = build_model(project)
    findings: List[Finding] = []
    for key, unit in model.units.items():
        if not unit.is_async or not model.roots.get(key):
            continue
        sf = project.file(unit.rel)
        if sf is None:
            continue
        events = _expanded_events(model, unit)
        for attr in sorted({e[1] for e in events if e[0] == "r"}):
            racy = _racy_roots(model, model.roots[key], attr)
            if not racy:
                continue
            state = 0  # 0: want read, 1: want yield, 2: want write
            r_line = y_line = None
            chain = ""
            for kind, a, line, info in events:
                if state == 0 and kind == "r" and a == attr:
                    state, r_line = 1, line
                elif state == 1 and kind == "y":
                    state, y_line, chain = 2, line, info or ""
                elif state == 2 and kind == "w" and a == attr:
                    if _suppressed(sf, (r_line, y_line, line)):
                        # This window is pragma'd; keep scanning in the
                        # same state — a LATER write on the same
                        # attribute (after the same read/yield) is a new
                        # site the pragma's invariant may not cover, and
                        # silently masking it would violate the
                        # over-reporting contract.
                        continue
                    shared = (
                        ""
                        if unit.cls is not None
                        and model.group.get(unit.cls) == attr[0]
                        else f" (shared state of {attr[0]})"
                    )
                    findings.append(Finding(
                        "interleave-window", unit.rel, r_line,
                        f"{_unit_label(unit)}: self.{attr[1]}{shared} "
                        f"is read at line {r_line}, the task can "
                        f"suspend at line {y_line} ({chain}), and it "
                        f"is written at line {line} — while "
                        f"suspended, task root(s) "
                        f"{_root_names(model, racy)} can also write "
                        "it (torn-invariant window); close the "
                        "window or pragma the invariant that makes "
                        "it safe",
                    ))
                    break
    yield from sorted(findings, key=lambda f: (f.path, f.line, f.message))


def rule_interleave_iteration(project: Project) -> Iterator[Finding]:
    model = build_model(project)
    findings: List[Finding] = []
    for key, unit in model.units.items():
        if not unit.is_async or not model.roots.get(key):
            continue
        sf = project.file(unit.rel)
        if sf is None:
            continue
        events = _expanded_events(model, unit)
        for attr, start, end in unit.iters:
            y = next(
                (e for e in events if e[0] == "y" and start < e[2] <= end),
                None,
            )
            if y is None:
                continue
            racy = _racy_roots(model, model.roots[key], attr)
            if not racy or _suppressed(sf, (start, y[2])):
                continue
            findings.append(Finding(
                "interleave-iteration", unit.rel, start,
                f"{_unit_label(unit)}: iterating self.{attr[1]} directly "
                f"while the loop body can suspend at line {y[2]} "
                f"({y[3]}) — task root(s) {_root_names(model, racy)} can "
                "mutate it mid-iteration (RuntimeError or a silently "
                "skipped entry under a new interleaving); snapshot with "
                "list(...) first, or pragma the invariant that makes it "
                "safe",
            ))
    yield from sorted(findings, key=lambda f: (f.path, f.line, f.message))
