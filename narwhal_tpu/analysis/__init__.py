"""narwhal-lint: the codebase-specific invariant linter (+ runtime
loop-stall watchdog in :mod:`.watchdog`).

Static rules live in :mod:`.rules`, the framework (file loading,
pragmas, findings, overlays) in :mod:`.linter`.  Entry points:

    python -m narwhal_tpu.analysis              # lint, exit 1 on findings
    python -m narwhal_tpu.analysis --env-table  # README env-var table
    make lint                                   # compile + flake8 + this

Kept import-light (stdlib + narwhal_tpu.utils.env only): the lint CI job
runs without jax.
"""

from .linter import Finding, load_project, run_lint  # noqa: F401
