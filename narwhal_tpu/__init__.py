"""narwhal-tpu — a TPU-native Narwhal (DAG mempool) + Tusk (BFT consensus) framework.

Built from scratch against the structural blueprint in SURVEY.md (reference:
asonnino/narwhal, a Rust workspace).  The compute-heavy per-round loops
(batched ed25519 verification, message digesting, Tusk DAG ordering) run on
TPU via JAX; the host runtime (networking, storage, actor pipelines) is
asyncio + native C++ helpers.
"""

__version__ = "0.1.0"
