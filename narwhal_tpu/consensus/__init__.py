from .tusk import Consensus, Tusk, State

__all__ = ["Consensus", "Tusk", "State"]
