from .tusk import (
    COMMIT_RULES,
    CheckpointRuleMismatch,
    Consensus,
    LowDepthTusk,
    State,
    Tusk,
    resolve_commit_rule,
)

__all__ = [
    "COMMIT_RULES",
    "CheckpointRuleMismatch",
    "Consensus",
    "LowDepthTusk",
    "State",
    "Tusk",
    "resolve_commit_rule",
]
