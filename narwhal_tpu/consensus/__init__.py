from .tusk import (
    COMMIT_RULES,
    CheckpointRuleMismatch,
    Consensus,
    LowDepthTusk,
    MultiLeaderTusk,
    State,
    Tusk,
    leader_slots,
    resolve_commit_rule,
)

__all__ = [
    "COMMIT_RULES",
    "CheckpointRuleMismatch",
    "Consensus",
    "LowDepthTusk",
    "MultiLeaderTusk",
    "State",
    "Tusk",
    "leader_slots",
    "resolve_commit_rule",
]
