"""Consensus audit log + golden-oracle replay — the safety-verdict engine.

Every primary can append a per-process **audit segment** (enabled by the
``NARWHAL_CONSENSUS_AUDIT`` env var or the equivalent constructor arg):

    record   := tag(1B) ‖ u32-le length ‖ payload
    'R'      := the checkpoint blob restored at boot ('' for a fresh
                frontier) — always the segment's first record
    'I'      := a certificate entering the commit rule, serialized, in
                arrival order
    'C'      := a committed certificate's 32-byte digest, in commit order

One segment per process incarnation: a crash/restart scenario hands the
restarted node a NEW segment path, so a SIGKILL-torn tail only ever sits
at the end of a segment (the reader stops at the tear instead of
corrupting post-restart records).

:func:`replay_segments` is the machine-checked safety verdict from
arXiv:2407.02167's reusable-invariant playbook, instantiated over the
frozen r06 oracle (``consensus/golden.py``):

- **oracle equivalence** — each segment's 'I' stream replayed through a
  fresh ``GoldenTusk`` (restored from the segment's 'R' blob) must
  reproduce the node's recorded 'C' sequence byte-identically (the
  recorded sequence may be a proper prefix: a crash can lose the tail of
  the last flushed burst, never reorder it);
- **certificate uniqueness** — no digest commits twice within a segment,
  and no two distinct digests commit for one (round, origin) slot across
  the whole run (equivocation must never doubly commit);
- **causal history** — every committed certificate's parents are genesis,
  committed earlier, already below the origin's ROLLING committed
  frontier at the moment the child commits (the walk's ≥-skip may be
  triggered mid-burst by an earlier leader's flush), or GC'd out of the
  window; parents that cannot be resolved against the
  inserted-certificate index are *counted* as unverifiable (a restored
  node legitimately commits above history it never re-synced) rather
  than silently passed.

:func:`cross_node_prefix` is the committee half of the verdict: every
honest node's (re-delivery-deduplicated) commit sequence must be a byte
prefix of the longest one.
"""

from __future__ import annotations

import logging
import os
import struct
from typing import Dict, List, Optional, Tuple

from ..config import Committee
from ..messages import Round
from ..primary.messages import Certificate, genesis
from .golden import GoldenTusk

log = logging.getLogger("narwhal.consensus")


class _CertDecoder:
    """Decode audit certificate payloads, sniffing the RECORDING's wire
    arm and certificate-signature scheme: the nodes that wrote the
    segments may have run the other ``NARWHAL_WIRE_V2`` arm or the
    other ``NARWHAL_CERT_SIG_SCHEME`` than this (harness) process —
    e.g. auditing a halfagg-arm sim workdir after the run bracket
    restored the process scheme.  The live decode path refuses
    cross-scheme frames LOUDLY (SchemeMismatch — the mixed-committee
    guard), but replay judges a FINISHED recording, so that refusal is
    re-read here as arm information: the first payload is tried under
    the process (arm, scheme) and then the flipped combinations;
    whichever decodes is pinned for the rest of the replay (a
    recording is single-arm/single-scheme by construction — both flags
    are committee-wide and process-constant)."""

    __slots__ = ("arm", "scheme")

    def __init__(self) -> None:
        self.arm: Optional[bool] = None  # None = process flags untested
        self.scheme: Optional[str] = None

    @staticmethod
    def _decode(payload: bytes, arm: bool, scheme: str) -> Certificate:
        from ..crypto import aggregate
        from ..network import wirev2

        prev_arm = wirev2.enabled_override()
        prev_scheme = aggregate.scheme_override()
        wirev2.set_enabled(arm)
        aggregate.set_scheme(scheme)
        try:
            return Certificate.deserialize(payload)
        finally:
            wirev2.set_enabled(prev_arm)
            aggregate.set_scheme(prev_scheme)

    def __call__(self, payload: bytes) -> Certificate:
        from ..crypto import aggregate
        from ..network import wirev2

        if self.arm is None:
            proc_arm = wirev2.enabled()
            proc_scheme = aggregate.scheme()
            other_scheme = (
                "halfagg" if proc_scheme == "individual" else "individual"
            )
            last_exc: Optional[Exception] = None
            for arm, scheme in (
                (proc_arm, proc_scheme),
                (proc_arm, other_scheme),
                (not proc_arm, proc_scheme),
                (not proc_arm, other_scheme),
            ):
                try:
                    cert = self._decode(payload, arm, scheme)
                except Exception as exc:
                    last_exc = exc
                    continue
                if (arm, scheme) != (proc_arm, proc_scheme):
                    log.warning(
                        "audit replay: certificates decode under "
                        "NARWHAL_WIRE_V2=%d / cert-sig-scheme %s, not "
                        "this process's arm — the recording ran the "
                        "other configuration; pinning it for this "
                        "replay",
                        1 if arm else 0,
                        scheme,
                    )
                self.arm, self.scheme = arm, scheme
                return cert
            raise last_exc  # type: ignore[misc]
        return self._decode(payload, self.arm, self.scheme)

_LEN = struct.Struct("<I")

TAG_RESTORE = b"R"
TAG_INSERT = b"I"
TAG_COMMIT = b"C"
# Commit-rule marker ('classic' | 'lowdepth' | 'multileader'), written
# immediately after the restore marker.  Segments recorded before the
# marker existed have none and replay under the classic oracle — exactly
# what recorded them.
TAG_RULE = b"M"

_RULE_ORACLES = {"classic": GoldenTusk}


def _oracle_for(rule: str):
    if rule == "lowdepth":
        # Deferred: the classic-only paths never import the other oracles.
        from .golden_lowdepth import GoldenLowDepthTusk

        return GoldenLowDepthTusk
    if rule == "multileader":
        from .golden_multileader import GoldenMultiLeaderTusk

        return GoldenMultiLeaderTusk
    return _RULE_ORACLES[rule]


class AuditWriter:
    """Append-only audit segment (buffered; the Consensus runner flushes
    once per drained burst, so 'I' and 'C' records of one burst always
    land or tear together)."""

    def __init__(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # One segment per process incarnation is the format's invariant
        # (the restore marker must be the FIRST record).  A fixed
        # NARWHAL_CONSENSUS_AUDIT path reused across restarts (systemd
        # unit, operator script) would append a second 'R' mid-file and
        # turn a perfectly safe run into a false safety FAIL — roll to
        # the first free `<path>.N` instead, keeping the previous
        # incarnation's segment intact and replayable.
        self.path = path
        if os.path.exists(path) and os.path.getsize(path) > 0:
            n = 1
            while (
                os.path.exists(f"{path}.{n}")
                and os.path.getsize(f"{path}.{n}") > 0
            ):
                n += 1
            self.path = f"{path}.{n}"
        self._f = open(self.path, "ab")

    def _record(self, tag: bytes, payload: bytes) -> None:
        self._f.write(tag + _LEN.pack(len(payload)) + payload)

    def restore_marker(self, blob: bytes) -> None:
        self._record(TAG_RESTORE, blob)

    def rule_marker(self, rule: str) -> None:
        self._record(TAG_RULE, rule.encode("ascii"))

    def insert(self, certificate: Certificate) -> None:
        self._record(TAG_INSERT, certificate.serialize())

    def commit(self, certificate: Certificate) -> None:
        self._record(TAG_COMMIT, bytes(certificate.digest()))

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        try:
            self._f.flush()
        finally:
            self._f.close()


def read_audit(path: str) -> List[Tuple[bytes, bytes]]:
    """Parse one segment into (tag, payload) records, tolerating a torn
    tail (SIGKILL mid-write) by stopping at the first incomplete record."""
    with open(path, "rb") as f:
        data = f.read()
    out: List[Tuple[bytes, bytes]] = []
    pos, n = 0, len(data)
    while pos + 1 + _LEN.size <= n:
        tag = data[pos : pos + 1]
        if tag not in (TAG_RESTORE, TAG_INSERT, TAG_COMMIT, TAG_RULE):
            break  # corrupt record boundary; treat like a tear
        (length,) = _LEN.unpack_from(data, pos + 1)
        end = pos + 1 + _LEN.size + length
        if end > n:
            break  # torn tail
        out.append((tag, data[pos + 1 + _LEN.size : end]))
        pos = end
    return out


def replay_segments(
    committee: Committee,
    gc_depth: Round,
    segment_paths: List[str],
    fixed_coin: bool = False,
) -> dict:
    """Replay one node's audit segments through the golden oracle and
    check the safety invariants.  Returns a verdict dict (see module
    docstring); ``ok`` is the conjunction of every check.  ``fixed_coin``
    must match the recording node's leader-election mode (live nodes:
    False; golden-test fixtures: True)."""
    # The audit's certificate payloads use the wire-v2 key-index codec
    # when the recording nodes ran v2 (the default): install the same
    # roster in THIS (harness) process before deserializing.
    from ..messages import set_wire_committee

    set_wire_committee(committee)
    decode_cert = _CertDecoder()
    genesis_digests = {c.digest() for c in genesis(committee)}
    violations: List[str] = []
    unverifiable_parents = 0
    recorded_all: List[bytes] = []   # every 'C' digest in record order
    committed_global: set = set()    # deduped across segments
    slot_by_digest: Dict[bytes, Tuple[Round, bytes]] = {}
    slots_committed: Dict[Tuple[Round, bytes], bytes] = {}
    golden_total = 0
    segment_rules: List[str] = []

    for seg_i, path in enumerate(segment_paths):
        records = read_audit(path)
        # Every path through this loop body appends exactly one entry to
        # segment_rules, rejected segments included — the verdict's
        # `rules` list must stay index-aligned with segment order or a
        # consumer joining rules[i] to segment i reads the wrong rule.
        if not records:
            violations.append(f"segment {seg_i}: empty or unreadable")
            segment_rules.append("unreadable")
            continue
        if records[0][0] != TAG_RESTORE:
            violations.append(
                f"segment {seg_i}: does not start with a restore marker"
            )
            segment_rules.append("unreadable")
            continue
        # The rule marker (if present) is the record after the restore
        # marker: each segment replays under the oracle of the rule that
        # RECORDED it — a flag-flip sweep's two arms, or a node restarted
        # under the other rule (new incarnation = new segment), judge
        # themselves without harness plumbing.  Marker-less segments
        # predate the marker and replay classic.
        rule = "classic"
        body = records[1:]
        if body and body[0][0] == TAG_RULE:
            raw = body[0][1].decode("ascii", "replace")
            if raw not in ("classic", "lowdepth", "multileader"):
                violations.append(
                    f"segment {seg_i}: unknown commit-rule marker {raw!r}"
                )
                segment_rules.append(raw)
                continue
            rule = raw
            body = body[1:]
        segment_rules.append(rule)
        golden = _oracle_for(rule)(committee, gc_depth, fixed_coin=fixed_coin)
        blob = records[0][1]
        if blob:
            try:
                golden.state.restore(blob)
            except Exception as exc:
                # Including the cross-rule magic mismatch: a segment whose
                # restore blob was written by the OTHER rule's state is a
                # recording inconsistency the verdict must surface, not
                # crash on.
                violations.append(
                    f"segment {seg_i}: restore blob does not parse under "
                    f"the {rule!r} oracle ({exc!r})"
                )
                continue
        inserts: Dict[bytes, Certificate] = {}
        golden_commits: List[bytes] = []
        golden_committed_set: set = set()
        recorded: List[bytes] = []
        seg_seen: set = set()
        # Rolling committed frontier per origin, updated per EMITTED
        # commit (not per burst): within one multi-leader burst an
        # earlier leader's flush can advance an origin's frontier past a
        # cert the walk then legitimately ≥-skips — a parent excused
        # mid-burst.  A burst-entry snapshot misses that window and
        # flagged byte-identical-to-oracle runs as causal violations
        # (found by the sim sweep's deeper DAGs; the walk itself was
        # correct).
        frontier: Dict[bytes, Round] = dict(golden.state.last_committed)
        for tag, payload in body:
            if tag == TAG_RESTORE:
                violations.append(
                    f"segment {seg_i}: restore marker mid-segment"
                )
                break
            if tag == TAG_RULE:
                violations.append(
                    f"segment {seg_i}: commit-rule marker mid-segment"
                )
                break
            if tag == TAG_COMMIT:
                recorded.append(payload)
                # Within one process lifetime the commit rule must never
                # emit a digest twice (re-delivery across a restart is the
                # allowed at-least-once boundary, NOT within a segment).
                if payload in seg_seen:
                    violations.append(
                        f"segment {seg_i}: digest {payload.hex()[:16]} "
                        "committed twice within one segment"
                    )
                seg_seen.add(payload)
                continue
            try:
                cert = decode_cert(payload)
            except Exception as exc:
                # A complete 'I' record with a garbage payload (disk
                # corruption, writer bug).  The segment's replay can no
                # longer be trusted past this point: record the violation
                # and stop this segment instead of crashing the verdict
                # engine that exists to judge exactly this.
                violations.append(
                    f"segment {seg_i}: undeserializable insert record "
                    f"({exc!r})"
                )
                break
            inserts[bytes(cert.digest())] = cert
            sequence = golden.process_certificate(cert)
            for x in sequence:
                d = bytes(x.digest())
                golden_commits.append(d)
                golden_committed_set.add(d)
                # Causal history: each parent accounted for.
                for parent in x.header.parents:
                    if parent in genesis_digests:
                        continue
                    pb = bytes(parent)
                    if pb in committed_global or pb in golden_committed_set:
                        continue
                    pc = inserts.get(pb)
                    if pc is None:
                        # Not inserted this lifetime: a restored node
                        # commits above history it never re-synced.
                        unverifiable_parents += 1
                        continue
                    if frontier.get(pc.origin, 0) >= pc.round:
                        continue  # excluded by the committed frontier
                    if (
                        pc.round + gc_depth
                        < golden.state.last_committed_round
                    ):
                        continue  # outside the GC window
                    violations.append(
                        f"segment {seg_i}: committed "
                        f"{d.hex()[:16]} (round {x.round}) before its "
                        f"parent {pb.hex()[:16]} (round {pc.round})"
                    )
                # (round, origin) slot uniqueness across the run.
                slot = (x.round, bytes(x.origin))
                prev = slots_committed.get(slot)
                if prev is not None and prev != d:
                    violations.append(
                        f"two certificates committed for slot "
                        f"round={x.round} origin={slot[1].hex()[:16]}"
                    )
                slots_committed[slot] = d
                slot_by_digest[d] = slot
                ob = bytes(x.origin)
                if x.round > frontier.get(ob, 0):
                    frontier[ob] = x.round
        golden_total += len(golden_commits)
        # Oracle equivalence: the node's recorded sequence must be a byte
        # prefix of the oracle's (a crash can lose a flushed burst's tail
        # 'C' records — both channels lose them together — but any
        # REORDER or substitution is a safety violation).
        if recorded != golden_commits[: len(recorded)]:
            div = next(
                (
                    i
                    for i, (a, b) in enumerate(zip(recorded, golden_commits))
                    if a != b
                ),
                min(len(recorded), len(golden_commits)),
            )
            violations.append(
                f"segment {seg_i}: recorded commit sequence diverges from "
                f"the golden oracle at position {div} "
                f"(recorded {len(recorded)}, oracle {len(golden_commits)})"
            )
        recorded_all.extend(recorded)
        for d in recorded:
            committed_global.add(d)

    return {
        "ok": not violations,
        "violations": violations,
        "segments": len(segment_paths),
        "rules": segment_rules,
        "recorded_commits": len(recorded_all),
        "golden_commits": golden_total,
        "unverifiable_parents": unverifiable_parents,
        "commit_digests": [d.hex() for d in _dedupe(recorded_all)],
    }


def _dedupe(digests: List[bytes]) -> List[bytes]:
    """Drop re-deliveries (keep first occurrence): the at-least-once
    restart boundary may repeat a burst; order is otherwise preserved."""
    seen: set = set()
    out = []
    for d in digests:
        if d not in seen:
            seen.add(d)
            out.append(d)
    return out


def cross_node_prefix(per_node: Dict[str, List[str]]) -> dict:
    """Committee-wide safety: every honest node's deduped commit-digest
    sequence (hex strings, from :func:`replay_segments`) must be a byte
    prefix of the longest node's.  Nodes commit at different speeds, so
    prefix — not equality — is the invariant."""
    longest_node = None
    longest: List[str] = []
    for node, seq in per_node.items():
        if len(seq) > len(longest):
            longest, longest_node = seq, node
    violations = []
    for node, seq in sorted(per_node.items()):
        if seq != longest[: len(seq)]:
            div = next(
                (i for i, (a, b) in enumerate(zip(seq, longest)) if a != b),
                min(len(seq), len(longest)),
            )
            violations.append(
                f"{node} diverges from {longest_node} at commit {div}"
            )
    return {
        "ok": not violations,
        "violations": violations,
        "lengths": {n: len(s) for n, s in sorted(per_node.items())},
        "reference_node": longest_node,
    }
