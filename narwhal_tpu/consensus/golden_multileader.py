"""The multi-leader commit rule's frozen dict-walk oracle.

A commit-rule CHANGE (not a rewrite) needs its own oracle: the
multileader rule (``consensus/tusk.py::MultiLeaderTusk``) deliberately
produces a DIFFERENT commit sequence than both Tusk and LowDepthTusk —
every even round carries K leader slots and the commit anchors on the
lowest supported slot — so neither ``GoldenTusk`` nor
``GoldenLowDepthTusk`` can judge it.  This module freezes the reference
walk for the NEW sequence, written in the same deliberately-naive style
as ``golden.py`` (linear parent scans, per-even-round BFS cone
recomputation, from-scratch support rescans, per-certificate GC sweep)
so the live indexed implementation and its oracle share no optimized
code — including an independent copy of the slot schedule.

The decision rules (Mysticeti's multi-leader insight, arXiv:2310.14821,
instantiated over this repo's even-round cadence):

- **slot schedule** — even round L has K = 3 leader slots; slot 0
  rotates through the sorted committee (``(L // 2) % n``) and the
  backup slots are a round-salted rotation of the rest, so the schedule
  is a pure function of (committee, round) and no authority
  monopolizes the anchor slot.
- **direct anchor** — scan slots 0..K-1 in order; a slot with < 2f+1
  support whose non-support has reached 2f+1 is DEAD (at most f stake
  of child certificates remain, so it can never reach quorum anywhere)
  and the scan passes it; a slot with 2f+1 direct support anchors the
  commit; an undecided slot stops the scan (another node could still
  anchor it).  Two nodes that direct-anchor a round therefore anchor
  the same slot.
- **indirect decision** — when an anchor commits, each earlier even
  round's chain member is the first slot whose leader holds f+1 stake
  of supporters inside the causal cone of the chain head above it.  A
  direct-anchored slot always re-derives (its 2f+1 supporters intersect
  any ≥ 2f+1-stake cone level in f+1 stake); dead lower slots (≤ f
  global support) never can — so direct and indirect nodes order the
  same slots at the same positions.

Checkpoints written under this rule carry their own magic (``NCKML1``):
a frontier snapshot is only meaningful to the rule that produced the
frontier, so a cross-rule restore must refuse, not reinterpret.

Do not optimize this file.  Its only job is to stay what it is.
"""

from __future__ import annotations

import hashlib
import logging
import struct
from typing import Dict, List, Optional, Tuple

from ..config import Committee
from ..crypto import Digest, PublicKey
from ..messages import Round
from ..primary.messages import Certificate, genesis
from .tusk import _check_scheme_trailer, _scheme_trailer

log = logging.getLogger("narwhal.consensus")

# dag: Round → {origin → (certificate digest, certificate)}
Dag = Dict[Round, Dict[PublicKey, Tuple[Digest, Certificate]]]

# Frozen copy of the live schedule's constants and derivation
# (consensus/tusk.py::leader_slots) — the oracle must derive the
# schedule independently, not import the code under test.
MULTILEADER_SLOTS = 3


def _leader_slots(
    sorted_keys: List[PublicKey], round_: Round, fixed_coin: bool
) -> List[PublicKey]:
    n = len(sorted_keys)
    k = min(n, MULTILEADER_SLOTS)
    if fixed_coin:
        return list(sorted_keys[:k])
    base = (round_ // 2) % n
    order = [sorted_keys[(base + j) % n] for j in range(n)]
    head, rest = order[0], order[1:]
    if len(rest) > 1:
        salt = int.from_bytes(
            hashlib.sha256(struct.pack("<Q", round_)).digest()[:8], "little"
        )
        off = salt % len(rest)
        rest = rest[off:] + rest[:off]
    return [head] + rest[: k - 1]


class GoldenMultiLeaderState:
    """Consensus state — dict-DAG only, ``golden.py`` shape."""

    def __init__(self, genesis_certs: List[Certificate]) -> None:
        gen = {c.origin: (c.digest(), c) for c in genesis_certs}
        self.last_committed_round: Round = 0
        self.last_committed: Dict[PublicKey, Round] = {
            name: cert.round for name, (_, cert) in gen.items()
        }
        self.dag: Dag = {0: gen}

    _CKPT_MAGIC = b"NCKML1"

    def snapshot_bytes(self) -> bytes:
        out = bytearray(self._CKPT_MAGIC)
        out += struct.pack("<Q", self.last_committed_round)
        items = sorted(self.last_committed.items())
        out += struct.pack("<I", len(items))
        for name, round in items:
            if len(bytes(name)) != 32:
                raise ValueError("checkpoint: authority key must be 32 bytes")
            out += bytes(name) + struct.pack("<Q", round)
        out += _scheme_trailer()
        return bytes(out)

    def restore(self, blob: bytes) -> None:
        if len(blob) < 18 or blob[:6] != self._CKPT_MAGIC:
            raise ValueError("checkpoint: bad magic")
        (last_round,) = struct.unpack_from("<Q", blob, 6)
        (n,) = struct.unpack_from("<I", blob, 14)
        _check_scheme_trailer(blob, 18 + 40 * n)
        entries = []
        pos = 18
        for _ in range(n):
            name = PublicKey(blob[pos : pos + 32])
            (round,) = struct.unpack_from("<Q", blob, pos + 32)
            entries.append((name, round))
            pos += 40
        self.last_committed_round = last_round
        for name, round in entries:
            self.last_committed[name] = round

    def update(self, certificate: Certificate, gc_depth: Round) -> None:
        """Record a commit and garbage-collect the DAG window — one full
        sweep per committed certificate (the naive form)."""
        origin = certificate.origin
        self.last_committed[origin] = max(
            self.last_committed.get(origin, 0), certificate.round
        )
        self.last_committed_round = max(self.last_committed.values())
        last = self.last_committed_round
        for name, round in self.last_committed.items():
            for r in list(self.dag):
                authorities = self.dag[r]
                if name in authorities and r < round:
                    del authorities[name]
                if not authorities or r + gc_depth < last:
                    del self.dag[r]


class GoldenMultiLeaderTusk:
    """The multi-leader commit rule: feed certificates, get ordered
    commit batches anchored on the lowest committable leader slot."""

    commit_rule = "multileader"

    def __init__(
        self, committee: Committee, gc_depth: Round, fixed_coin: bool = False
    ) -> None:
        self.committee = committee
        self.gc_depth = gc_depth
        self.fixed_coin = fixed_coin
        self.state = GoldenMultiLeaderState(genesis(committee))
        self._sorted_keys = sorted(committee.authorities.keys())

    def _slots(self, round_: Round) -> List[PublicKey]:
        return _leader_slots(self._sorted_keys, round_, self.fixed_coin)

    def insert_certificate(self, certificate: Certificate) -> None:
        self.state.dag.setdefault(certificate.round, {})[
            certificate.origin
        ] = (certificate.digest(), certificate)

    def _slot_support(self, leader_round: Round, digest: Digest) -> int:
        """From-scratch support for one slot leader: stake of
        round-(L+1) certificates citing its digest."""
        return sum(
            self.committee.stake(cert.origin)
            for _, cert in self.state.dag.get(leader_round + 1, {}).values()
            if digest in cert.header.parents
        )

    def _child_stake(self, leader_round: Round) -> int:
        return sum(
            self.committee.stake(cert.origin)
            for _, cert in self.state.dag.get(leader_round + 1, {}).values()
        )

    def process_certificate(self, certificate: Certificate) -> List[Certificate]:
        state = self.state
        round = certificate.round
        self.insert_certificate(certificate)

        # Which leader round can this arrival have affected?  A
        # round-(L+1) certificate changes slot support and child stake
        # for round L (both sides of the anchor scan); a slot leader's
        # own arrival makes already-present support countable.  Any
        # other arrival changes no slot decision and cannot trigger.
        if round % 2 == 1:
            leader_round = round - 1
        elif certificate.origin in self._slots(round):
            leader_round = round
        else:
            return []
        if leader_round < 2 or leader_round <= state.last_committed_round:
            return []

        # Slot-ordered anchor scan (module docstring): lowest slot with
        # direct 2f+1 support, passing only DEAD lower slots.  All
        # tallies recomputed from scratch over the whole child round.
        quorum = self.committee.quorum_threshold()
        child_stake = self._child_stake(leader_round)
        anchor = None
        for name in self._slots(leader_round):
            got = state.dag.get(leader_round, {}).get(name)
            support = (
                self._slot_support(leader_round, got[0])
                if got is not None
                else 0
            )
            if support >= quorum:
                if got is None:
                    return []
                anchor = got[1]
                break
            if child_stake - support < quorum:
                return []  # undecided slot: nothing may anchor past it
            # dead slot: scan on
        if anchor is None:
            return []

        sequence: List[Certificate] = []
        for past_leader in reversed(self.order_leaders(anchor)):
            for x in self.order_dag(past_leader):
                state.update(x, self.gc_depth)
                sequence.append(x)
        return sequence

    def order_leaders(self, leader: Certificate) -> List[Certificate]:
        to_commit = [leader]
        state = self.state
        for r in range(
            leader.round - 2, state.last_committed_round + 1, -2
        ):
            member = self._cone_member(leader, r, state.dag)
            if member is not None:
                to_commit.append(member)
                leader = member
        return to_commit

    def _cone_member(
        self, chain_tail: Certificate, leader_round: Round, dag: Dag
    ) -> Optional[Certificate]:
        """Chain member for even round ``leader_round``: the first slot
        whose leader holds f+1 stake of supporters inside the causal
        cone of ``chain_tail`` at round leader_round+1.  The cone level
        is recomputed by a fresh round-by-round BFS per even round (the
        naive form of the live walk's single descending frontier)."""
        frontier = [chain_tail]
        for r in range(chain_tail.round - 1, leader_round, -1):
            frontier = [
                certificate
                for digest, certificate in dag.get(r, {}).values()
                if any(digest in x.header.parents for x in frontier)
            ]
        validity = self.committee.validity_threshold()
        for name in self._slots(leader_round):
            got = dag.get(leader_round, {}).get(name)
            if got is None:
                continue
            digest = got[0]
            support = sum(
                self.committee.stake(x.origin)
                for x in frontier
                if digest in x.header.parents
            )
            if support >= validity:
                return got[1]
        return None

    def order_dag(self, leader: Certificate) -> List[Certificate]:
        """DFS flatten with linear-scan parent resolution."""
        state = self.state
        ordered: List[Certificate] = []
        already_ordered = set()
        buffer = [leader]
        while buffer:
            x = buffer.pop()
            ordered.append(x)
            for parent in sorted(x.header.parents):
                found = None
                for digest, certificate in state.dag.get(x.round - 1, {}).values():
                    if digest == parent:
                        found = (digest, certificate)
                        break
                if found is None:
                    continue  # already ordered or GC'd up to here
                digest, certificate = found
                skip = digest in already_ordered
                skip |= (
                    state.last_committed.get(certificate.origin, -1)
                    >= certificate.round
                )
                if not skip:
                    buffer.append(certificate)
                    already_ordered.add(digest)
        ordered = [
            x
            for x in ordered
            if x.round + self.gc_depth >= state.last_committed_round
        ]
        ordered.sort(key=lambda x: x.round)  # stable: prettier sequence
        return ordered
