"""The lower-depth commit rule's frozen dict-walk oracle.

A commit-rule CHANGE (not a rewrite) needs its own oracle: the lowdepth
rule (``consensus/tusk.py::LowDepthTusk``) deliberately produces a
DIFFERENT commit sequence than Tusk — leaders commit on direct 2f+1
support one round earlier than the classic two-round pattern — so the
r06 ``GoldenTusk`` cannot judge it.  This module freezes the reference
walk for the NEW sequence, written in the same deliberately-naive style
as ``golden.py`` (linear parent scans, per-hop ``linked()`` BFS,
from-scratch support rescans, per-certificate GC sweep) so the live
indexed implementation and its oracle share no optimized code.

The decision rule (Mysticeti's direct-decision insight, arXiv:2310.14821,
instantiated over this repo's even-round leader schedule):

- **direct commit** — the leader of even round L is committed the moment
  the local DAG holds round-(L+1) certificates citing it with ≥ 2f+1
  stake (the classic rule waits for a round-(L+3) certificate and only
  f+1 support).  2f+1 *direct* support is what makes the lower depth
  safe across nodes: any later certificate's 2f+1 parents at L+1
  intersect the support set in f+1 certificates, so EVERY certificate at
  round ≥ L+2 — in particular every later committed anchor — is linked
  to L, and a node that decides L indirectly (below) orders it exactly
  where a direct committer did.
- **indirect decision** — when an anchor commits, every earlier
  undecided leader is ordered by the same linked-chain walk as the
  classic rule (``order_leaders`` with its frontier reset): linked
  leaders join the chain oldest-first, unlinked leaders are skipped —
  deterministically, because certificates only reach the commit rule
  causally complete (Core delivers ancestors first), so linkage is a
  property of the DAG, not of arrival order.

Checkpoints written under this rule carry their own magic (``NCKLD1``):
a frontier snapshot is only meaningful to the rule that produced the
frontier, so a cross-rule restore must refuse, not reinterpret.

Do not optimize this file.  Its only job is to stay what it is.
"""

from __future__ import annotations

import logging
import struct
from typing import Dict, List, Optional, Tuple

from ..config import Committee
from ..crypto import Digest, PublicKey
from ..messages import Round
from ..primary.messages import Certificate, genesis
from .tusk import _check_scheme_trailer, _scheme_trailer

log = logging.getLogger("narwhal.consensus")

# dag: Round → {origin → (certificate digest, certificate)}
Dag = Dict[Round, Dict[PublicKey, Tuple[Digest, Certificate]]]


class GoldenLowDepthState:
    """Consensus state — dict-DAG only, ``golden.py`` shape."""

    def __init__(self, genesis_certs: List[Certificate]) -> None:
        gen = {c.origin: (c.digest(), c) for c in genesis_certs}
        self.last_committed_round: Round = 0
        self.last_committed: Dict[PublicKey, Round] = {
            name: cert.round for name, (_, cert) in gen.items()
        }
        self.dag: Dag = {0: gen}

    _CKPT_MAGIC = b"NCKLD1"

    def snapshot_bytes(self) -> bytes:
        out = bytearray(self._CKPT_MAGIC)
        out += struct.pack("<Q", self.last_committed_round)
        items = sorted(self.last_committed.items())
        out += struct.pack("<I", len(items))
        for name, round in items:
            if len(bytes(name)) != 32:
                raise ValueError("checkpoint: authority key must be 32 bytes")
            out += bytes(name) + struct.pack("<Q", round)
        out += _scheme_trailer()
        return bytes(out)

    def restore(self, blob: bytes) -> None:
        if len(blob) < 18 or blob[:6] != self._CKPT_MAGIC:
            raise ValueError("checkpoint: bad magic")
        (last_round,) = struct.unpack_from("<Q", blob, 6)
        (n,) = struct.unpack_from("<I", blob, 14)
        _check_scheme_trailer(blob, 18 + 40 * n)
        entries = []
        pos = 18
        for _ in range(n):
            name = PublicKey(blob[pos : pos + 32])
            (round,) = struct.unpack_from("<Q", blob, pos + 32)
            entries.append((name, round))
            pos += 40
        self.last_committed_round = last_round
        for name, round in entries:
            self.last_committed[name] = round

    def update(self, certificate: Certificate, gc_depth: Round) -> None:
        """Record a commit and garbage-collect the DAG window — one full
        sweep per committed certificate (the naive form)."""
        origin = certificate.origin
        self.last_committed[origin] = max(
            self.last_committed.get(origin, 0), certificate.round
        )
        self.last_committed_round = max(self.last_committed.values())
        last = self.last_committed_round
        for name, round in self.last_committed.items():
            for r in list(self.dag):
                authorities = self.dag[r]
                if name in authorities and r < round:
                    del authorities[name]
                if not authorities or r + gc_depth < last:
                    del self.dag[r]


class GoldenLowDepthTusk:
    """The lower-depth commit rule: feed certificates, get ordered commit
    batches one round earlier than the classic walk."""

    commit_rule = "lowdepth"

    def __init__(
        self, committee: Committee, gc_depth: Round, fixed_coin: bool = False
    ) -> None:
        self.committee = committee
        self.gc_depth = gc_depth
        self.fixed_coin = fixed_coin
        self.state = GoldenLowDepthState(genesis(committee))
        self._sorted_keys = sorted(committee.authorities.keys())

    def leader(self, round: Round, dag: Dag) -> Optional[Tuple[Digest, Certificate]]:
        coin = 0 if self.fixed_coin else round
        name = self._sorted_keys[coin % len(self._sorted_keys)]
        return dag.get(round, {}).get(name)

    def _leader_name(self, round_: Round) -> PublicKey:
        coin = 0 if self.fixed_coin else round_
        return self._sorted_keys[coin % len(self._sorted_keys)]

    def insert_certificate(self, certificate: Certificate) -> None:
        self.state.dag.setdefault(certificate.round, {})[
            certificate.origin
        ] = (certificate.digest(), certificate)

    def process_certificate(self, certificate: Certificate) -> List[Certificate]:
        state = self.state
        round = certificate.round
        self.insert_certificate(certificate)

        # Which leader can this arrival have affected?  A round-(L+1)
        # certificate adds direct support for the round-L leader; the
        # round-L leader itself arriving (possibly after its supporters)
        # makes already-present support countable.  Any other arrival
        # changes no leader's direct support and cannot trigger.
        if round % 2 == 1:
            leader_round = round - 1
        elif certificate.origin == self._leader_name(round):
            leader_round = round
        else:
            return []
        if leader_round < 2 or leader_round <= state.last_committed_round:
            return []
        got = self.leader(leader_round, state.dag)
        if got is None:
            return []
        leader_digest, leader = got

        # DIRECT commit gate: 2f+1 stake among the children (round
        # leader_round+1 certificates citing the leader), recomputed from
        # scratch over the whole child round.  2f+1 — not the classic
        # f+1 — is what guarantees every later anchor links to this
        # leader (module docstring), which is what makes committing
        # without the classic round-(L+3) trigger certificate safe.
        stake = sum(
            self.committee.stake(cert.origin)
            for _, cert in state.dag.get(leader_round + 1, {}).values()
            if leader_digest in cert.header.parents
        )
        if stake < self.committee.quorum_threshold():
            return []

        # INDIRECT decision path: identical to the classic walk — every
        # earlier uncommitted leader linked to the new anchor's chain
        # joins it (oldest first), unlinked leaders are skipped for good.
        sequence: List[Certificate] = []
        for past_leader in reversed(self.order_leaders(leader)):
            for x in self.order_dag(past_leader):
                state.update(x, self.gc_depth)
                sequence.append(x)
        return sequence

    def order_leaders(self, leader: Certificate) -> List[Certificate]:
        to_commit = [leader]
        state = self.state
        for r in range(
            leader.round - 2, state.last_committed_round + 1, -2
        ):
            got = self.leader(r, state.dag)
            if got is None:
                continue
            _, prev_leader = got
            if self.linked(leader, prev_leader, state.dag):
                to_commit.append(prev_leader)
                leader = prev_leader
        return to_commit

    def linked(
        self, leader: Certificate, prev_leader: Certificate, dag: Dag
    ) -> bool:
        """Round-by-round BFS with per-hop list-membership checks."""
        parents = [leader]
        for r in range(leader.round - 1, prev_leader.round - 1, -1):
            parents = [
                certificate
                for digest, certificate in dag.get(r, {}).values()
                if any(digest in x.header.parents for x in parents)
            ]
        return any(x is prev_leader or x == prev_leader for x in parents)

    def order_dag(self, leader: Certificate) -> List[Certificate]:
        """DFS flatten with linear-scan parent resolution."""
        state = self.state
        ordered: List[Certificate] = []
        already_ordered = set()
        buffer = [leader]
        while buffer:
            x = buffer.pop()
            ordered.append(x)
            for parent in sorted(x.header.parents):
                found = None
                for digest, certificate in state.dag.get(x.round - 1, {}).values():
                    if digest == parent:
                        found = (digest, certificate)
                        break
                if found is None:
                    continue  # already ordered or GC'd up to here
                digest, certificate = found
                skip = digest in already_ordered
                skip |= (
                    state.last_committed.get(certificate.origin, -1)
                    >= certificate.round
                )
                if not skip:
                    buffer.append(certificate)
                    already_ordered.add(digest)
        ordered = [
            x
            for x in ordered
            if x.round + self.gc_depth >= state.last_committed_round
        ]
        ordered.sort(key=lambda x: x.round)  # stable: prettier sequence
        return ordered
