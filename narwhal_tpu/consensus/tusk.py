"""Tusk: zero-message asynchronous BFT commit over the shared DAG.

Reference consensus/src/lib.rs (304 LoC).  Every even round r has a leader;
when the leader of round r−2 gathers f+1 stake support among round r−1
certificates, it commits — together with every preceding uncommitted leader
it is linked to, each flattening its causal sub-DAG in deterministic order.
No extra messages: the commit rule is a pure function of the DAG.

The pure state machine (`Tusk.process_certificate`) is separated from the
async runner (`Consensus`) so the commit rule can be golden-tested directly
and later swapped for the JAX adjacency-matrix kernel
(narwhal_tpu/ops/reachability.py) validated certificate-for-certificate
against this implementation.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, List, Optional, Tuple

from ..config import Committee
from ..crypto import Digest, PublicKey
from ..messages import Round
from ..primary.messages import Certificate, genesis

log = logging.getLogger("narwhal.consensus")

# dag: Round → {origin → (certificate digest, certificate)}
Dag = Dict[Round, Dict[PublicKey, Tuple[Digest, Certificate]]]


class State:
    """Consensus state (reference lib.rs:19-62)."""

    def __init__(self, genesis_certs: List[Certificate]) -> None:
        gen = {c.origin: (c.digest(), c) for c in genesis_certs}
        self.last_committed_round: Round = 0
        self.last_committed: Dict[PublicKey, Round] = {
            name: cert.round for name, (_, cert) in gen.items()
        }
        self.dag: Dag = {0: gen}

    def update(self, certificate: Certificate, gc_depth: Round) -> None:
        """Record a commit and garbage-collect the DAG window."""
        origin = certificate.origin
        self.last_committed[origin] = max(
            self.last_committed.get(origin, 0), certificate.round
        )
        self.last_committed_round = max(self.last_committed.values())
        last = self.last_committed_round
        for name, round in self.last_committed.items():
            for r in list(self.dag):
                authorities = self.dag[r]
                if name in authorities and r < round:
                    del authorities[name]
                if not authorities or r + gc_depth < last:
                    del self.dag[r]


class Tusk:
    """The pure commit rule: feed certificates, get ordered commit batches."""

    def __init__(
        self, committee: Committee, gc_depth: Round, fixed_coin: bool = False
    ) -> None:
        self.committee = committee
        self.gc_depth = gc_depth
        # fixed_coin pins the leader to the first authority — the reference's
        # #[cfg(test)] coin = 0 (lib.rs:209-212) used by the golden tests.
        self.fixed_coin = fixed_coin
        self.state = State(genesis(committee))
        self._sorted_keys = sorted(committee.authorities.keys())

    def leader(self, round: Round, dag: Dag) -> Optional[Tuple[Digest, Certificate]]:
        """Round-robin leader (a common coin in the full protocol —
        reference lib.rs:205-221)."""
        coin = 0 if self.fixed_coin else round
        name = self._sorted_keys[coin % len(self._sorted_keys)]
        return dag.get(round, {}).get(name)

    def insert_certificate(self, certificate: Certificate) -> None:
        """Insert into the DAG without running the commit rule.  Separate
        seam so KernelTusk can maintain its dense device window
        incrementally, and benchmarks can build large DAG states."""
        self.state.dag.setdefault(certificate.round, {})[
            certificate.origin
        ] = (certificate.digest(), certificate)

    def process_certificate(self, certificate: Certificate) -> List[Certificate]:
        """Insert a certificate; return the newly committed sequence
        (possibly empty).  Reference lib.rs:105-201."""
        state = self.state
        round = certificate.round
        self.insert_certificate(certificate)

        # Order from the highest round with a 2f+1 frontier (needed to
        # reveal the common coin).  Leaders live on even rounds.
        r = round - 1
        if r % 2 != 0 or r < 4:
            return []
        leader_round = r - 2
        if leader_round <= state.last_committed_round:
            return []
        got = self.leader(leader_round, state.dag)
        if got is None:
            return []
        leader_digest, leader = got

        # f+1 support among the children (round r-1 certificates).
        stake = sum(
            self.committee.stake(cert.origin)
            for _, cert in state.dag.get(r - 1, {}).values()
            if leader_digest in cert.header.parents
        )
        if stake < self.committee.validity_threshold():
            log.debug("Leader %r does not have enough support", leader)
            return []

        # Commit every linked uncommitted leader, oldest first, each
        # flattening its causal sub-DAG.
        log.debug("Leader %r has enough support", leader)
        sequence: List[Certificate] = []
        for past_leader in reversed(self.order_leaders(leader)):
            for x in self.order_dag(past_leader):
                state.update(x, self.gc_depth)
                sequence.append(x)
        return sequence

    def order_leaders(self, leader: Certificate) -> List[Certificate]:
        """Walk back two rounds at a time, keeping leaders linked to the
        chain (reference lib.rs:224-244)."""
        to_commit = [leader]
        state = self.state
        for r in range(
            leader.round - 2, state.last_committed_round + 1, -2
        ):
            got = self.leader(r, state.dag)
            if got is None:
                continue
            _, prev_leader = got
            if self.linked(leader, prev_leader, state.dag):
                to_commit.append(prev_leader)
                leader = prev_leader
        return to_commit

    def linked(
        self, leader: Certificate, prev_leader: Certificate, dag: Dag
    ) -> bool:
        """Round-by-round BFS reachability (reference lib.rs:247-259).
        This is the loop the TPU kernel re-expresses as boolean
        adjacency-matrix products."""
        parents = [leader]
        for r in range(leader.round - 1, prev_leader.round - 1, -1):
            parents = [
                certificate
                for digest, certificate in dag.get(r, {}).values()
                if any(digest in x.header.parents for x in parents)
            ]
        return any(x is prev_leader or x == prev_leader for x in parents)

    def order_dag(self, leader: Certificate) -> List[Certificate]:
        """DFS flatten of the leader's causal history, skipping
        already-committed certificates (reference lib.rs:263-303)."""
        state = self.state
        ordered: List[Certificate] = []
        already_ordered = set()
        buffer = [leader]
        while buffer:
            x = buffer.pop()
            ordered.append(x)
            # Sorted iteration (the reference's BTreeSet order): a Python
            # set's iteration order depends on insertion history, which
            # differs between the author's in-memory header and decoded
            # copies — unsorted DFS would give each node a different
            # intra-round commit order.
            for parent in sorted(x.header.parents):
                found = None
                for digest, certificate in state.dag.get(x.round - 1, {}).values():
                    if digest == parent:
                        found = (digest, certificate)
                        break
                if found is None:
                    continue  # already ordered or GC'd up to here
                digest, certificate = found
                skip = digest in already_ordered
                skip |= (
                    state.last_committed.get(certificate.origin)
                    == certificate.round
                )
                if not skip:
                    buffer.append(certificate)
                    already_ordered.add(digest)
        # Never commit garbage-collected certificates.
        ordered = [
            x
            for x in ordered
            if x.round + self.gc_depth >= state.last_committed_round
        ]
        ordered.sort(key=lambda x: x.round)  # stable: prettier sequence
        return ordered


class Consensus:
    """Async runner: certificates in from the primary, ordered certificates
    out to the application and back to the primary for GC."""

    def __init__(
        self,
        committee: Committee,
        gc_depth: Round,
        rx_primary: asyncio.Queue,
        tx_primary: asyncio.Queue,
        tx_output: asyncio.Queue,
        benchmark: bool = False,
        fixed_coin: bool = False,
        use_kernel: bool = False,
    ) -> None:
        if use_kernel:
            # Deferred: the pure-CPU node path must not pay the JAX import.
            from ..ops.reachability import KernelTusk

            self.tusk = KernelTusk(committee, gc_depth, fixed_coin=fixed_coin)
        else:
            self.tusk = Tusk(committee, gc_depth, fixed_coin=fixed_coin)
        self.rx_primary = rx_primary
        self.tx_primary = tx_primary
        self.tx_output = tx_output
        self.benchmark = benchmark

    async def run(self) -> None:
        while True:
            certificate = await self.rx_primary.get()
            for committed in self.tusk.process_certificate(certificate):
                header = committed.header
                if self.benchmark and header.payload:
                    for digest in header.payload:
                        # Parsed by the benchmark log parser (reference
                        # lib.rs:185-189).
                        log.info(
                            "Committed B%d(%r) -> %r",
                            header.round,
                            header.id,
                            digest,
                        )
                else:
                    log.info("Committed B%d(%r)", header.round, header.id)
                await self.tx_primary.put(committed)
                await self.tx_output.put(committed)
