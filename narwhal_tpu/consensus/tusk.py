"""Tusk: zero-message asynchronous BFT commit over the shared DAG.

Reference consensus/src/lib.rs (304 LoC).  Every even round r has a leader;
when the leader of round r−2 gathers f+1 stake support among round r−1
certificates, it commits — together with every preceding uncommitted leader
it is linked to, each flattening its causal sub-DAG in deterministic order.
No extra messages: the commit rule is a pure function of the DAG.

The pure state machine (`Tusk.process_certificate`) is separated from the
async runner (`Consensus`) so the commit rule can be golden-tested directly
and later swapped for the JAX adjacency-matrix kernel
(narwhal_tpu/ops/reachability.py) validated certificate-for-certificate
against this implementation.
"""

from __future__ import annotations

import asyncio
import logging
import os
import struct
from typing import Dict, List, Optional, Tuple

from .. import metrics
from ..config import Committee
from ..crypto import Digest, PublicKey
from ..messages import Round
from ..primary.messages import Certificate, genesis

log = logging.getLogger("narwhal.consensus")

# dag: Round → {origin → (certificate digest, certificate)}
Dag = Dict[Round, Dict[PublicKey, Tuple[Digest, Certificate]]]


class State:
    """Consensus state (reference lib.rs:19-62)."""

    def __init__(self, genesis_certs: List[Certificate]) -> None:
        gen = {c.origin: (c.digest(), c) for c in genesis_certs}
        self.last_committed_round: Round = 0
        self.last_committed: Dict[PublicKey, Round] = {
            name: cert.round for name, (_, cert) in gen.items()
        }
        self.dag: Dag = {0: gen}

    _CKPT_MAGIC = b"NCKPT1"

    def snapshot_bytes(self) -> bytes:
        """Canonical encoding of the committed frontier — the part of
        consensus state that crash-recovery needs (the reference marks
        this persisted-state duty as intended-but-unimplemented,
        consensus/src/lib.rs:18-19; here it IS implemented).  The DAG
        itself is not snapshotted: it is rebuilt by the sync machinery,
        and the restored frontier keeps re-synced history out of the
        commit sequence (see order_dag's skip)."""
        out = bytearray(self._CKPT_MAGIC)
        out += struct.pack("<Q", self.last_committed_round)
        items = sorted(self.last_committed.items())
        out += struct.pack("<I", len(items))
        for name, round in items:
            if len(bytes(name)) != 32:
                raise ValueError("checkpoint: authority key must be 32 bytes")
            out += bytes(name) + struct.pack("<Q", round)
        return bytes(out)

    def restore(self, blob: bytes) -> None:
        """Seed the committed frontier from snapshot_bytes output.
        Validation raises (never asserts — a malformed blob misparsed
        under ``python -O`` would silently wedge the commit rule at a
        garbage frontier), and the WHOLE blob parses before any state
        mutates: a torn checkpoint must leave the fresh frontier intact
        so the caller can fall back to it (ADVICE.md r05)."""
        if len(blob) < 18 or blob[:6] != self._CKPT_MAGIC:
            raise ValueError("checkpoint: bad magic")
        (last_round,) = struct.unpack_from("<Q", blob, 6)
        (n,) = struct.unpack_from("<I", blob, 14)
        if len(blob) != 18 + 40 * n:
            raise ValueError("checkpoint: truncated or oversized blob")
        entries = []
        pos = 18
        for _ in range(n):
            name = PublicKey(blob[pos : pos + 32])
            (round,) = struct.unpack_from("<Q", blob, pos + 32)
            entries.append((name, round))
            pos += 40
        self.last_committed_round = last_round
        for name, round in entries:
            self.last_committed[name] = round

    def update(self, certificate: Certificate, gc_depth: Round) -> None:
        """Record a commit and garbage-collect the DAG window."""
        origin = certificate.origin
        self.last_committed[origin] = max(
            self.last_committed.get(origin, 0), certificate.round
        )
        self.last_committed_round = max(self.last_committed.values())
        last = self.last_committed_round
        for name, round in self.last_committed.items():
            for r in list(self.dag):
                authorities = self.dag[r]
                if name in authorities and r < round:
                    del authorities[name]
                if not authorities or r + gc_depth < last:
                    del self.dag[r]


class Tusk:
    """The pure commit rule: feed certificates, get ordered commit batches."""

    def __init__(
        self, committee: Committee, gc_depth: Round, fixed_coin: bool = False
    ) -> None:
        self.committee = committee
        self.gc_depth = gc_depth
        # fixed_coin pins the leader to the first authority — the reference's
        # #[cfg(test)] coin = 0 (lib.rs:209-212) used by the golden tests.
        self.fixed_coin = fixed_coin
        self.state = State(genesis(committee))
        self._sorted_keys = sorted(committee.authorities.keys())

    def leader(self, round: Round, dag: Dag) -> Optional[Tuple[Digest, Certificate]]:
        """Round-robin leader (a common coin in the full protocol —
        reference lib.rs:205-221)."""
        coin = 0 if self.fixed_coin else round
        name = self._sorted_keys[coin % len(self._sorted_keys)]
        return dag.get(round, {}).get(name)

    def insert_certificate(self, certificate: Certificate) -> None:
        """Insert into the DAG without running the commit rule.  Separate
        seam so KernelTusk can maintain its dense device window
        incrementally, and benchmarks can build large DAG states."""
        self.state.dag.setdefault(certificate.round, {})[
            certificate.origin
        ] = (certificate.digest(), certificate)

    def process_certificate(self, certificate: Certificate) -> List[Certificate]:
        """Insert a certificate; return the newly committed sequence
        (possibly empty).  Reference lib.rs:105-201."""
        state = self.state
        round = certificate.round
        self.insert_certificate(certificate)

        # Order from the highest round with a 2f+1 frontier (needed to
        # reveal the common coin).  Leaders live on even rounds.
        r = round - 1
        if r % 2 != 0 or r < 4:
            return []
        leader_round = r - 2
        if leader_round <= state.last_committed_round:
            return []
        got = self.leader(leader_round, state.dag)
        if got is None:
            return []
        leader_digest, leader = got

        # f+1 support among the children (round r-1 certificates).
        stake = sum(
            self.committee.stake(cert.origin)
            for _, cert in state.dag.get(r - 1, {}).values()
            if leader_digest in cert.header.parents
        )
        if stake < self.committee.validity_threshold():
            log.debug("Leader %r does not have enough support", leader)
            return []

        # Commit every linked uncommitted leader, oldest first, each
        # flattening its causal sub-DAG.
        log.debug("Leader %r has enough support", leader)
        sequence: List[Certificate] = []
        for past_leader in reversed(self.order_leaders(leader)):
            for x in self.order_dag(past_leader):
                state.update(x, self.gc_depth)
                sequence.append(x)
        return sequence

    def order_leaders(self, leader: Certificate) -> List[Certificate]:
        """Walk back two rounds at a time, keeping leaders linked to the
        chain (reference lib.rs:224-244)."""
        to_commit = [leader]
        state = self.state
        for r in range(
            leader.round - 2, state.last_committed_round + 1, -2
        ):
            got = self.leader(r, state.dag)
            if got is None:
                continue
            _, prev_leader = got
            if self.linked(leader, prev_leader, state.dag):
                to_commit.append(prev_leader)
                leader = prev_leader
        return to_commit

    def linked(
        self, leader: Certificate, prev_leader: Certificate, dag: Dag
    ) -> bool:
        """Round-by-round BFS reachability (reference lib.rs:247-259).
        This is the loop the TPU kernel re-expresses as boolean
        adjacency-matrix products."""
        parents = [leader]
        for r in range(leader.round - 1, prev_leader.round - 1, -1):
            parents = [
                certificate
                for digest, certificate in dag.get(r, {}).values()
                if any(digest in x.header.parents for x in parents)
            ]
        return any(x is prev_leader or x == prev_leader for x in parents)

    def order_dag(self, leader: Certificate) -> List[Certificate]:
        """DFS flatten of the leader's causal history, skipping
        already-committed certificates (reference lib.rs:263-303)."""
        state = self.state
        ordered: List[Certificate] = []
        already_ordered = set()
        buffer = [leader]
        while buffer:
            x = buffer.pop()
            ordered.append(x)
            # Sorted iteration (the reference's BTreeSet order): a Python
            # set's iteration order depends on insertion history, which
            # differs between the author's in-memory header and decoded
            # copies — unsorted DFS would give each node a different
            # intra-round commit order.
            for parent in sorted(x.header.parents):
                found = None
                for digest, certificate in state.dag.get(x.round - 1, {}).values():
                    if digest == parent:
                        found = (digest, certificate)
                        break
                if found is None:
                    continue  # already ordered or GC'd up to here
                digest, certificate = found
                skip = digest in already_ordered
                # ≥, not ==: in-process they are equivalent (State.update
                # deletes every DAG entry strictly below an authority's
                # last-committed round, so only the boundary round can
                # still be encountered — the reference's equality check,
                # lib.rs:263-303, relies on exactly that), but after a
                # checkpoint restore the DAG is rebuilt by sync from
                # BEFORE the committed frontier and older rounds reappear;
                # ≥ keeps them out of the sequence.
                skip |= (
                    state.last_committed.get(certificate.origin, -1)
                    >= certificate.round
                )
                if not skip:
                    buffer.append(certificate)
                    already_ordered.add(digest)
        # Never commit garbage-collected certificates.
        ordered = [
            x
            for x in ordered
            if x.round + self.gc_depth >= state.last_committed_round
        ]
        ordered.sort(key=lambda x: x.round)  # stable: prettier sequence
        return ordered


class Consensus:
    """Async runner: certificates in from the primary, ordered certificates
    out to the application and back to the primary for GC."""

    def __init__(
        self,
        committee: Committee,
        gc_depth: Round,
        rx_primary: asyncio.Queue,
        tx_primary: asyncio.Queue,
        tx_output: asyncio.Queue,
        benchmark: bool = False,
        fixed_coin: bool = False,
        use_kernel: bool = False,
        checkpoint_path: Optional[str] = None,
    ) -> None:
        if use_kernel:
            # Deferred: the pure-CPU node path must not pay the JAX import.
            from ..ops.reachability import KernelTusk

            self.tusk = KernelTusk(committee, gc_depth, fixed_coin=fixed_coin)
        else:
            self.tusk = Tusk(committee, gc_depth, fixed_coin=fixed_coin)
        self.rx_primary = rx_primary
        self.tx_primary = tx_primary
        self.tx_output = tx_output
        self.benchmark = benchmark
        self._m_certs_in = metrics.counter("consensus.certificates_in")
        self._m_commits = metrics.counter("consensus.committed_certificates")
        self._m_batches = metrics.counter("consensus.committed_batch_digests")
        self._m_commit_batch = metrics.histogram(
            "consensus.commit_batch_size", metrics.COUNT_BUCKETS
        )
        self._m_round = metrics.gauge("consensus.last_committed_round")
        self._m_lag = metrics.gauge("consensus.commit_lag_rounds")
        self._mtrace = metrics.trace()
        # Crash-recovery of the committed frontier (beyond reference
        # parity — it leaves consensus state unpersisted,
        # consensus/src/lib.rs:18-19).  The checkpoint is its own small
        # file rewritten atomically (write-temp + os.replace), NOT a
        # record in the append-only store log — only the latest frontier
        # is live, so appending one per commit batch would grow the log
        # and every boot-time replay without bound.  What it buys a
        # restarted node: order_leaders and the GC filter anchor at the
        # true frontier instead of round 0, and pre-crash certificates
        # replayed INTO consensus (a lagging peer's catch-up flood routed
        # through the Core) stay out of the commit sequence (order_dag's
        # ≥ skip) — demonstrated directly in tests/test_consensus.py::
        # test_checkpoint_restore_resumes_without_redelivery.  (On a
        # store-preserving restart with healthy peers, history doesn't
        # reach consensus at all — the persisted header/cert store
        # satisfies dependency checks without replay — so the checkpoint
        # is the backstop for the paths where it does.)
        self.checkpoint_path = checkpoint_path
        if checkpoint_path is not None and os.path.exists(checkpoint_path):
            try:
                with open(checkpoint_path, "rb") as f:
                    self.tusk.state.restore(f.read())
            except Exception:
                # A torn/corrupt checkpoint must not crash-loop the node:
                # the file is a recovery OPTIMIZATION (restore validates
                # before mutating, so the fresh frontier below is intact).
                # Booting fresh is always safe — at worst already-committed
                # certificates re-deliver, dedupable downstream by digest.
                log.exception(
                    "Checkpoint %s is corrupt or torn; IGNORING it and "
                    "booting from a fresh consensus frontier",
                    checkpoint_path,
                )
            else:
                if hasattr(self.tusk, "_win_shift"):
                    # Realign the kernel's dense window to the restored
                    # frontier (slot 0 == last_committed_round).
                    self.tusk._win_shift()
                log.info(
                    "Restored consensus frontier at round %d",
                    self.tusk.state.last_committed_round,
                )

    async def run(self) -> None:
        while True:
            certificate = await self.rx_primary.get()
            self._m_certs_in.inc()
            sequence = self.tusk.process_certificate(certificate)
            state = self.tusk.state
            # Committed-certificate lag: how far the DAG head has run ahead
            # of the committed frontier.  A steadily growing lag means the
            # commit rule is starved (missing leader support) while
            # certificates keep arriving.
            self._m_lag.set(
                max(0, certificate.round - state.last_committed_round)
            )
            self._m_round.set(state.last_committed_round)
            if sequence:
                self._m_commits.inc(len(sequence))
                self._m_commit_batch.observe(len(sequence))
            for committed in sequence:
                header = committed.header
                self._m_batches.inc(len(header.payload))
                for digest in header.payload:
                    self._mtrace.mark(bytes(digest).hex(), "commit")
                if self.benchmark and header.payload:
                    for digest in header.payload:
                        # Parsed by the benchmark log parser (reference
                        # lib.rs:185-189).
                        log.info(
                            "Committed B%d(%r) -> %r",
                            header.round,
                            header.id,
                            digest,
                        )
                else:
                    log.info("Committed B%d(%r)", header.round, header.id)
                await self.tx_primary.put(committed)
                await self.tx_output.put(committed)
            if sequence and self.checkpoint_path is not None:
                # One atomic rewrite per commit batch, AFTER delivery: a
                # crash in the window re-delivers at most this one batch
                # on restart (at-least-once at the boundary, dedupable by
                # certificate digest downstream) instead of silently
                # LOSING it, which nothing downstream could repair.
                tmp = self.checkpoint_path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(self.tusk.state.snapshot_bytes())
                    # fsync BEFORE the rename: os.replace is atomic against
                    # process crash, but on power loss the rename can become
                    # durable before the data, leaving a torn file under the
                    # final name (ADVICE.md r05).
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.checkpoint_path)
